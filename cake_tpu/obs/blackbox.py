"""Black-box anomaly capture: diagnostic bundles at the moment of failure.

The observability stack can reconstruct an incident AFTER the fact — if the
rings haven't wrapped past it. This module captures the moment itself: when
a request breaches its tenant's SLO objective, lands past a rolling
p99 x K latency multiplier, or dies to a watchdog stall / failover /
whole-epoch error, the serving engine snapshots a diagnostic bundle into a
bounded, rate-limited on-disk ring (``--blackbox-dir``). A bundle is one
JSON file holding everything a post-mortem needs with no live server:

  * ``explain``   — the critical-path attribution (obs/critpath.py),
  * ``timeline``  — the request's timeline slice (raw ring events),
  * ``events``    — the flight-recorder tail,
  * ``engine`` / ``pool`` / ``prefix`` / ``slo`` — engine counters, page
    allocator, prefix-tree and SLO snapshots,
  * ``metrics``   — the registry snapshot.

``cake-tpu doctor <bundle|dir>`` renders a human report naming the dominant
phase and the likely cause (``diagnose``): convoy / queue / stall / wire /
compute / shed / failover. The capture ring is bounded two ways — at most
``keep`` bundles on disk (oldest deleted) and at most one capture per
``min_interval_s`` (an incident storm writes one bundle, not a disk full of
identical ones; suppressions are counted, not silent).

Stdlib-only; the engine guards every capture behind ``--blackbox-dir``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from cake_tpu.utils import metrics

BUNDLE_SCHEMA = 1
_PREFIX = "bundle-"

# Rolling end-to-end latency window for the p99 x K outlier trigger: the
# multiplier needs this many samples before it can fire (a cold server's
# first slow request is warmup, not an anomaly).
_MIN_SAMPLES = 30
_WINDOW = 512

# Capture reasons are a bounded enum (they become metric labels and file
# names); the engine maps its failure taxonomy onto them.
REASONS = (
    "stall", "epoch-error", "failover", "slo-ttft", "slo-deadline",
    "latency-outlier", "manual",
)


class BlackBox:
    """Bounded, rate-limited on-disk ring of diagnostic bundles."""

    def __init__(
        self,
        dir: str,
        *,
        keep: int = 16,
        min_interval_s: float = 5.0,
        p99_mult: float = 0.0,
    ):
        if keep < 1:
            raise ValueError(f"blackbox keep must be >= 1, got {keep}")
        if min_interval_s < 0 or p99_mult < 0:
            raise ValueError(
                "blackbox min_interval_s and p99_mult must be >= 0"
            )
        self.dir = dir
        self.keep = int(keep)
        self.min_interval_s = float(min_interval_s)
        self.p99_mult = float(p99_mult)
        self._lock = threading.Lock()
        self._seq = 0
        self._last_capture = 0.0
        self._lat: deque[float] = deque(maxlen=_WINDOW)
        self.captured = 0
        self.suppressed = 0
        os.makedirs(dir, exist_ok=True)

    # ------------------------------------------------------------ triggers

    def observe_latency(self, e2e_s: float) -> bool:
        """Record one end-to-end latency; True when it is a p99 x K outlier
        (the trigger needs ``p99_mult`` > 0 and a warm window). The verdict
        compares against the window BEFORE the sample joins it — an outlier
        must not raise its own bar — but the sample is recorded either way,
        so a sustained slowdown becomes the new normal instead of a
        bundle-per-request storm."""
        if self.p99_mult <= 0:
            return False
        with self._lock:
            warm = len(self._lat) >= _MIN_SAMPLES
            if warm:
                s = sorted(self._lat)
                p99 = s[min(len(s) - 1, int(round(0.99 * (len(s) - 1))))]
            self._lat.append(float(e2e_s))
        return warm and e2e_s > self.p99_mult * p99 > 0.0

    # ------------------------------------------------------------- capture

    def capture(
        self,
        reason: str,
        request_id: str | None = None,
        *,
        explain: dict | None = None,
        timeline: list[dict] | None = None,
        events: list[dict] | None = None,
        extra: dict | None = None,
    ) -> str | None:
        """Write one bundle; returns its path, or None when rate-limited.

        The rate limit is global (not per reason): an incident usually
        trips several triggers at once — the stall, then the epoch error,
        then the latency outliers — and ONE bundle captures them all."""
        now = time.monotonic()
        with self._lock:
            if (
                self.min_interval_s > 0
                and self.captured > 0
                and now - self._last_capture < self.min_interval_s
            ):
                self.suppressed += 1
                metrics.registry.counter(
                    "cake_blackbox_suppressed_total",
                    "Blackbox captures suppressed by the rate limit.",
                ).inc()
                return None
            self._seq += 1
            seq = self._seq
        bundle = {
            "schema": BUNDLE_SCHEMA,
            "captured_wall": round(time.time(), 6),
            "reason": reason,
            "request_id": request_id,
            "explain": explain,
            "timeline": timeline or [],
            "events": events or [],
        }
        if extra:
            bundle.update(extra)
        safe_reason = "".join(
            c if c.isalnum() or c in "-_" else "-" for c in reason
        )[:32]
        path = os.path.join(
            self.dir, f"{_PREFIX}{int(time.time())}-{seq:04d}-{safe_reason}.json"
        )
        try:
            with open(path, "w") as f:
                json.dump(bundle, f, separators=(",", ":"), default=str)
        except OSError:
            # A full disk must not take the engine down — and a FAILED
            # write must not consume the rate-limit slot: nothing landed,
            # so the next trigger deserves a fresh attempt.
            return None
        with self._lock:
            # Commit the rate-limit slot only once a bundle actually
            # exists on disk.
            self._last_capture = now
            self.captured += 1
        metrics.registry.counter(
            "cake_blackbox_bundles_total",
            "Diagnostic bundles captured (labelled by trigger reason).",
        ).inc(reason=safe_reason)
        metrics.flight.record(
            "blackbox-capture", request_id, reason=reason, path=path,
        )
        self._trim()
        return path

    def _trim(self) -> None:
        """Keep only the newest ``keep`` bundles (the on-disk ring bound)."""
        try:
            names = sorted(
                n for n in os.listdir(self.dir)
                if n.startswith(_PREFIX) and n.endswith(".json")
            )
        except OSError:
            return
        for n in names[: max(0, len(names) - self.keep)]:
            try:
                os.unlink(os.path.join(self.dir, n))
            except OSError:
                pass

    def bundles(self) -> list[str]:
        """Bundle paths, oldest first."""
        try:
            return [
                os.path.join(self.dir, n)
                for n in sorted(os.listdir(self.dir))
                if n.startswith(_PREFIX) and n.endswith(".json")
            ]
        except OSError:
            return []

    def stats(self) -> dict:
        with self._lock:
            return {
                "dir": self.dir,
                "keep": self.keep,
                "captured": self.captured,
                "suppressed": self.suppressed,
                "on_disk": len(self.bundles()),
            }


# ------------------------------------------------------------------ doctor


def load_bundle(path: str) -> dict:
    """Read one bundle file (or the NEWEST bundle of a directory)."""
    if os.path.isdir(path):
        names = sorted(
            n for n in os.listdir(path)
            if n.startswith(_PREFIX) and n.endswith(".json")
        )
        if not names:
            raise FileNotFoundError(f"no {_PREFIX}*.json bundles in {path}")
        path = os.path.join(path, names[-1])
    with open(path) as f:
        bundle = json.load(f)
    bundle.setdefault("_path", path)
    return bundle


def diagnose(bundle: dict) -> dict:
    """Name the likely cause of the captured anomaly.

    Precedence (pinned by tests/test_blackbox.py): a watchdog-stall or shed
    trigger IS the cause; otherwise the dominant attribution phase maps —
    queue -> queue, convoy/spec_wasted -> convoy, wire -> wire,
    stall -> stall, failover -> failover, everything compute-shaped
    (prefill/decode/spec_accepted/host) -> compute.
    """
    reason = str(bundle.get("reason", ""))
    exp = bundle.get("explain") or {}
    phases = exp.get("phases") or {}
    dom = exp.get("dominant") or (
        max(phases, key=lambda p: phases.get(p) or 0.0) if phases else None
    )
    if reason == "stall" or dom == "stall":
        # Only a stall TRIGGER or stall-dominated attribution blames the
        # watchdog — a few ms of stall residue on a convoy-dominated
        # request must not steer the operator at worker health.
        cause = "stall"
    elif reason == "shed":
        cause = "shed"
    elif reason == "failover" or dom == "failover":
        cause = "failover"
    elif dom in ("queue", "admission"):
        cause = "queue"
    elif dom in ("convoy", "spec_wasted"):
        cause = "convoy"
    elif dom == "wire":
        cause = "wire"
    elif dom in ("prefill", "decode", "spec_accepted", "prefix_fork",
                 "host", "other"):
        cause = "compute"
    else:
        cause = "unknown"
    out = {"cause": cause, "dominant": dom, "reason": reason}
    eff = bundle.get("efficiency") or {}
    if eff.get("bucket_frac"):
        # Utilization view (obs/efficiency.py ledger, captured with the
        # bundle): which WASTE bucket dominated the device while the
        # anomaly built. Additive — the latency cause above stays pinned;
        # bundles captured before the ledger existed diagnose unchanged.
        frac = eff["bucket_frac"]
        waste = {
            b: float(frac.get(b) or 0.0)
            for b in ("pad", "convoy", "spec_wasted", "host_gap", "stall",
                      "failover", "restore_prefill")
        }
        top = max(waste, key=waste.get)
        out["goodput_frac"] = float(eff.get("goodput_frac") or 0.0)
        if waste[top] >= 0.15:
            out["utilization"] = top
            out["utilization_frac"] = waste[top]
    return out


_HINTS = {
    "stall": "a backend dispatch made no progress within the watchdog "
    "bound (--epoch-stall); check worker/device health and the "
    "cake_epoch_stalls_total trend",
    "queue": "the request waited for a lane, not compute; raise capacity, "
    "lower --api-batch contention, or shed earlier (--shed-queue-depth)",
    "convoy": "the lockstep epoch taxed this request with co-batched "
    "streams' work (the ROADMAP's continuous-batching refactor target); "
    "see cake_convoy_seconds and /stats phases",
    "wire": "worker round trips dominate; check the per-node wire_nodes "
    "breakdown and the cluster RTT table in cake-tpu stats",
    "compute": "prefill/decode compute dominates; this is the kernel "
    "budget — see the bench ledger (BENCH_HISTORY.jsonl / benchdiff)",
    "shed": "admission refused the request (server saturation); see "
    "cake_shed_total and per-tenant /slo burn",
    "failover": "a live-stream migration carried (or failed) this "
    "request; see cake_failover_total and the router events",
    "unknown": "no attribution available; inspect the bundle's timeline "
    "slice and flight events directly",
}

# Hints for the utilization (device-waste) annotation — where the
# HARDWARE went while the anomaly built (obs/efficiency.py buckets).
_UTIL_HINTS = {
    "pad": "the device mostly computed padding / dead lanes; batch shapes "
    "are too tall for the live load — lower --decode-chunk, or let "
    "continuous mode join mid-flight",
    "convoy": "the device computed chunk tails past streams' needs (the "
    "lockstep tax); see /stats phases and --scheduler continuous",
    "spec_wasted": "rejected speculative drafts dominate; lower "
    "--speculative-k or check draft/model divergence",
    "host_gap": "the device sat idle between dispatches; host scheduling "
    "or sampling readback glue dominates — see cake-tpu top",
    "stall": "watchdog-abandoned dispatch wall dominates; check worker "
    "and device health",
    "failover": "migration re-prefills dominate; workers are flapping — "
    "see cake_failover_total",
    "restore_prefill": "preemption restore re-prefills dominate; page "
    "pressure is thrashing lanes — raise --max-pages or shed earlier",
}


def render_report(bundle: dict) -> str:
    """Human report for ``cake-tpu doctor`` — deterministic from the bundle
    alone (the golden-snapshot test depends on that)."""
    d = diagnose(bundle)
    exp = bundle.get("explain") or {}
    phases = exp.get("phases") or {}
    lines = [
        "cake-tpu doctor report",
        f"  bundle:   {bundle.get('_path', '<memory>')}",
        f"  reason:   {bundle.get('reason', '?')}",
        f"  request:  {bundle.get('request_id') or '-'}",
        f"  cause:    {d['cause']}",
        f"  dominant: {d['dominant'] or '-'}",
    ]
    wall = exp.get("wall_s")
    if wall:
        lines.append(
            f"  wall:     {wall * 1e3:.2f} ms  "
            f"(convoy_frac {exp.get('convoy_frac', 0.0):.3f}, "
            f"coverage {exp.get('coverage', 0.0):.3f})"
        )
    if phases:
        lines.append("")
        lines.append(f"  {'phase':14} {'ms':>10}")
        from cake_tpu.obs.critpath import PHASES

        for p in PHASES:
            v = float(phases.get(p, 0.0) or 0.0)
            if v > 0.0:
                lines.append(f"  {p:14} {v * 1e3:>10.2f}")
    eng = bundle.get("engine") or {}
    if eng:
        keys = (
            "batches", "rows", "joins", "shed", "stream_errors",
            "epoch_stalls", "deadline_expired", "page_truncations",
        )
        shown = "  ".join(f"{k}={eng[k]}" for k in keys if k in eng)
        if shown:
            lines.append("")
            lines.append(f"  engine: {shown}")
    pool = bundle.get("pool") or {}
    if pool:
        lines.append(
            f"  pool:   {pool.get('pages_free', '?')}/"
            f"{pool.get('pages_total', '?')} pages free"
        )
    if "goodput_frac" in d:
        # Only bundles captured with the efficiency ledger carry this —
        # older bundles (and the golden snapshot) render unchanged.
        util = d.get("utilization")
        line = f"  device: goodput_frac {d['goodput_frac']:.3f}"
        if util:
            line += (
                f", dominant waste {util} "
                f"({d.get('utilization_frac', 0.0):.3f})"
            )
        lines.append("")
        lines.append(line)
        if util:
            lines.append(f"  waste:  {_UTIL_HINTS.get(util, '')}")
    lines.append("")
    lines.append(f"  likely: {_HINTS.get(d['cause'], _HINTS['unknown'])}")
    return "\n".join(lines)
