"""Jit retrace/compile watchdog: make "it never retraces" a runtime invariant.

The paged serving path's core perf promise — block tables are traced operands,
so join/growth/release never retrace (PR 4) — and the static lint rules (PR 2/3)
both assert jit DISCIPLINE, but nothing at runtime counted what jax actually
did. This module wraps the project's jit families in a tracker:

  * ``tracked_jit(fn, name=..., **jit_kwargs)`` — a drop-in ``jax.jit`` whose
    wrapped body bumps a per-name trace counter AT TRACE TIME (the body only
    runs while jax is tracing, so the count is exact, with zero steady-state
    overhead: a cache hit never enters Python).
  * Traces land in ``cake_jit_traces_total{fn}``; the wall time of each
    tracing call (trace + lower + backend compile, the thing that stalls a
    serving epoch) lands in ``cake_jit_compile_seconds``.
  * A RETRACE — tracing a (name, abstract-signature) pair that was already
    traced in this process (an evicted-and-rebuilt wrapper recompiling the
    same program), or ANY trace while the watchdog is armed — increments
    ``cake_jit_retraces_total{fn}``, records a ``jit-retrace`` flight event,
    and (opt-in ``CAKE_RETRACE_FATAL=1``, for tests) raises RetraceError.
  * ``arm()`` declares warmup over: steady state must not trace at all.
    Tests warm the decode path, arm in fatal mode, and pin zero retraces.
  * ``install_compile_listener()`` taps ``jax.monitoring`` for process-wide
    XLA backend-compile seconds — bench.py diffs it around each section for
    the ``compile_s_*`` / ``retrace_count_*`` keys.

Importing this module does NOT import jax; ``tracked_jit`` does (its callers
already have).
"""

from __future__ import annotations

import contextlib
import functools
import os
import threading
import time

from cake_tpu.utils import metrics


class RetraceError(RuntimeError):
    """A tracked jit function retraced while the watchdog was armed (or
    recompiled an already-compiled signature) under CAKE_RETRACE_FATAL=1."""


class JitWatch:
    """Process-global trace/compile bookkeeping for tracked jit families."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._traces: dict[str, int] = {}
        self._retraces: dict[str, int] = {}
        self._compile_s: dict[str, float] = {}
        self._sigs: dict[str, set] = {}
        self._armed = False

    # ------------------------------------------------------------- arming

    def arm(self) -> None:
        """Warmup is over: any tracked trace from now on is a retrace."""
        with self._lock:
            self._armed = True

    def disarm(self) -> None:
        with self._lock:
            self._armed = False

    @property
    def armed(self) -> bool:
        with self._lock:
            return self._armed

    @contextlib.contextmanager
    def expect_no_retrace(self):
        """Armed for the duration (tests: steady state must not trace)."""
        self.arm()
        try:
            yield
        finally:
            self.disarm()

    # ------------------------------------------------------------- recording

    def note_trace(self, name: str, sig) -> None:
        """Called from INSIDE the traced body — i.e. exactly once per trace."""
        with self._lock:
            self._traces[name] = self._traces.get(name, 0) + 1
            seen = self._sigs.setdefault(name, set())
            duplicate = sig in seen
            seen.add(sig)
            armed = self._armed
        metrics.registry.counter(
            "cake_jit_traces_total",
            "Times jax traced a tracked function (one compile each).",
        ).inc(fn=name)
        if duplicate or armed:
            why = "armed" if armed and not duplicate else "duplicate-signature"
            with self._lock:
                self._retraces[name] = self._retraces.get(name, 0) + 1
            metrics.registry.counter(
                "cake_jit_retraces_total",
                "Traces of a tracked function after warmup (armed watchdog) "
                "or of an already-compiled signature (rebuilt wrapper).",
            ).inc(fn=name)
            metrics.flight.record("jit-retrace", fn=name, reason=why)
            if os.environ.get("CAKE_RETRACE_FATAL") == "1":
                raise RetraceError(
                    f"jit retrace of {name!r} ({why}); steady state must not "
                    "trace — see cake_jit_traces_total{fn} for the history"
                )

    def note_compile(self, name: str, seconds: float) -> None:
        with self._lock:
            self._compile_s[name] = self._compile_s.get(name, 0.0) + seconds
        metrics.registry.histogram(
            "cake_jit_compile_seconds",
            "Wall time of each tracing call (trace + lower + XLA compile).",
        ).observe(seconds, fn=name)

    def trace_count(self, name: str) -> int:
        with self._lock:
            return self._traces.get(name, 0)

    def retrace_total(self) -> int:
        with self._lock:
            return sum(self._retraces.values())

    def snapshot(self) -> dict[str, dict]:
        with self._lock:
            names = set(self._traces) | set(self._compile_s)
            return {
                n: {
                    "traces": self._traces.get(n, 0),
                    "retraces": self._retraces.get(n, 0),
                    "compile_s": round(self._compile_s.get(n, 0.0), 6),
                }
                for n in sorted(names)
            }

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()
            self._retraces.clear()
            self._compile_s.clear()
            self._sigs.clear()
            self._armed = False


watch = JitWatch()
arm = watch.arm
disarm = watch.disarm
expect_no_retrace = watch.expect_no_retrace
snapshot = watch.snapshot
retrace_total = watch.retrace_total


def _abstract_sig(args: tuple, kwargs: dict):
    """Hashable abstraction of a call: array leaves -> (shape, dtype), other
    leaves (statics: python scalars, strings, configs) -> their repr. Two
    calls sharing it would hit the same executable, so tracing it twice IS a
    recompile of an already-compiled program."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, tuple(sorted(
        kwargs.items()
    ))))
    parts = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        if shape is not None:
            parts.append((tuple(shape), str(getattr(leaf, "dtype", "?"))))
        else:
            parts.append(repr(leaf)[:80])
    return (str(treedef), tuple(parts))


def tracked_jit(fn, *, name: str | None = None, **jit_kwargs):
    """``jax.jit`` with the watchdog attached; same call surface/donation.

    ``name`` labels the metrics series — include the builder's cache key for
    per-cached-entry functions (``batch.decode[n=8,t=0.0,...]``) so a rebuilt
    lru entry retracing its old signature is flagged, while two entries that
    legitimately share shapes are not.
    """
    import jax

    label = name or getattr(fn, "__name__", "jit")

    @functools.wraps(fn)
    def traced(*args, **kwargs):
        # Runs ONLY while jax traces (a compile-cache hit never enters
        # Python), so this is the exact trace count.
        watch.note_trace(label, _abstract_sig(args, kwargs))
        return fn(*args, **kwargs)

    jitted = jax.jit(traced, **jit_kwargs)

    @functools.wraps(fn)
    def call(*args, **kwargs):
        before = watch.trace_count(label)
        t0 = time.perf_counter()
        out = jitted(*args, **kwargs)
        if watch.trace_count(label) > before:
            # This call traced: the wall delta is trace+lower+compile plus
            # one async dispatch — compile dominates, and that is the number
            # a serving operator needs ("what stalled the epoch").
            # cake-lint: disable-next-line=unblocked-timing
            watch.note_compile(label, time.perf_counter() - t0)
        return out

    call._jitted = jitted  # escape hatch (lower/compile introspection)
    call._watch_name = label
    return call


# ------------------------------------------------- process-wide compile tap

_listener_lock = threading.Lock()
_listener_installed = False
_compile_events = 0
_compile_total_s = 0.0


def install_compile_listener() -> bool:
    """Tap jax.monitoring for EVERY backend compile in the process (tracked
    or not). Idempotent; returns False when the monitoring API is absent."""
    global _listener_installed
    with _listener_lock:
        if _listener_installed:
            return True
        try:
            import jax.monitoring as monitoring

            monitoring.register_event_duration_secs_listener(_on_duration)
        except (ImportError, AttributeError):
            return False
        _listener_installed = True
        return True


def _on_duration(name: str, seconds: float, **kw) -> None:
    global _compile_events, _compile_total_s
    if "backend_compile" in name:
        with _listener_lock:
            _compile_events += 1
            _compile_total_s += seconds


def compile_totals() -> tuple[int, float]:
    """(backend compiles seen, total seconds) since the listener went in."""
    with _listener_lock:
        return _compile_events, _compile_total_s
