"""Rolling SLI time-series (the first over-time surface, not a counter).

A sliding window of fixed-width buckets — the obs/slo.py bucket idiom,
server-wide instead of per-tenant — each holding a bounded TTFT sample
reservoir plus token/finish/refusal tallies. ``series()`` renders the
window as one point per bucket (p50/p99 TTFT, tok/s, shed+429 rate), the
shape ``GET /timeseries`` serves and ``cake-tpu top`` draws as sparkline
columns. Feeds are engine-side: first-token observations from
``_RowState.push`` and terminal outcomes from the request-log funnel
(runtime/serving.py), so the time-series and the request log always agree
on what finished when.

Stdlib only, injectable clock — the closed-form window math is unit
tested on a fake clock (tests/test_timeseries.py).
"""

from __future__ import annotations

import collections
import threading
import time


def _percentile(samples: list[float], q: float) -> float:
    """Nearest-rank percentile over a bounded sample list (the obs/slo.py
    estimator: exact for the small reservoirs these buckets keep)."""
    if not samples:
        return 0.0
    s = sorted(samples)
    idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
    return s[idx]


class _Bucket:
    __slots__ = ("idx", "ttft", "tokens", "finished", "refused", "errors")

    def __init__(self, idx: int):
        self.idx = idx  # integer bucket number: floor(now / bucket_s)
        self.ttft: list[float] = []
        self.tokens = 0
        self.finished = 0   # admitted terminals (any finish_reason)
        self.refused = 0    # quota (429) + shed (503)
        self.errors = 0

    def point(self, bucket_s: float, age_s: float) -> dict:
        offered = self.finished + self.refused
        return {
            "age_s": round(age_s, 1),
            "ttft_p50_ms": round(_percentile(self.ttft, 0.50) * 1e3, 2),
            "ttft_p99_ms": round(_percentile(self.ttft, 0.99) * 1e3, 2),
            "tok_s": round(self.tokens / bucket_s, 2),
            "finished": self.finished,
            "refused": self.refused,
            "errors": self.errors,
            "shed_frac": round(self.refused / offered, 4) if offered else 0.0,
        }


class SliTimeseries:
    """Sliding-window histogram rings behind ``GET /timeseries``."""

    def __init__(
        self,
        window_s: float = 300.0,
        bucket_s: float = 5.0,
        max_samples: int = 512,
        time_fn=time.monotonic,
    ):
        if bucket_s <= 0 or window_s < bucket_s:
            raise ValueError(
                f"need window_s >= bucket_s > 0, got {window_s}/{bucket_s}"
            )
        self.window_s = float(window_s)
        self.bucket_s = float(bucket_s)
        self._max_samples = max_samples
        self._time = time_fn
        self._lock = threading.Lock()
        self._buckets: collections.deque[_Bucket] = collections.deque()

    def _bucket(self) -> _Bucket:
        """Current (aligned) bucket; evicts everything past the horizon.
        Caller holds the lock."""
        idx = int(self._time() // self.bucket_s)
        if not self._buckets or self._buckets[-1].idx < idx:
            self._buckets.append(_Bucket(idx))
        oldest = idx - int(round(self.window_s / self.bucket_s))
        while self._buckets and self._buckets[0].idx < oldest:
            self._buckets.popleft()
        return self._buckets[-1]

    def observe_ttft(self, ttft_s: float) -> None:
        with self._lock:
            b = self._bucket()
            if len(b.ttft) < self._max_samples:
                b.ttft.append(float(ttft_s))

    def observe_tokens(self, n: int = 1) -> None:
        with self._lock:
            self._bucket().tokens += n

    def observe_finish(self, finish_reason: str) -> None:
        """Terminal outcome tally — REQUEST_OUTCOMES vocabulary: the two
        refusal kinds feed the shed/429 rate, everything else counts as an
        admitted finish (errors also tallied separately)."""
        with self._lock:
            b = self._bucket()
            if finish_reason in ("quota", "shed"):
                b.refused += 1
            else:
                b.finished += 1
                if finish_reason == "error":
                    b.errors += 1

    def series(self) -> dict:
        """The window as chronological per-bucket points (newest last).
        Empty gaps between observed buckets are materialized as zero
        points so sparklines render real time, not event time."""
        with self._lock:
            now = self._time()
            buckets = {b.idx: b for b in self._buckets}
        head = int(now // self.bucket_s)
        points: list[dict] = []
        n_buckets = int(round(self.window_s / self.bucket_s))
        for idx in range(head - n_buckets + 1, head + 1):
            if idx < 0:
                continue
            b = buckets.get(idx) or _Bucket(idx)
            points.append(
                b.point(self.bucket_s, now - idx * self.bucket_s)
            )
        # Leading all-zero history (a server younger than the window)
        # renders as noise-free left padding; trim it for compactness.
        while points and not (
            points[0]["finished"] or points[0]["refused"]
            or points[0]["tok_s"]
        ):
            points.pop(0)
        return {
            "window_s": self.window_s,
            "bucket_s": self.bucket_s,
            "t_wall": round(time.time(), 3),
            "points": points,
        }
