"""Span-tree timeline: structured profiling events -> Perfetto export.

utils/trace.py answers "how much time did X take in aggregate"; this module
answers "WHERE did this request's milliseconds go": a contextvar-based span
tree records begin/end events with parent ids, request ids, and attributes
into a bounded per-process ring, and the exporter renders Chrome trace-event
JSON (``ph: "B"/"E"/"X"`` slices, ``"s"/"f"`` flow arrows, ``"C"`` counter
tracks) loadable in Perfetto or ``chrome://tracing``.

Event model (one dict per ring entry, JSON-serializable end to end):

  * ``span(name)`` — lexically scoped spans become ONE ``"X"`` complete event
    at exit (begin timestamp + duration); nesting rides a contextvar, so the
    parent id is correct across threads and across ``yield`` points.
  * ``begin()/end()`` — non-lexical spans (a serving lane's request occupies
    the lane from admission to finish, across many scheduler iterations)
    become a ``"B"``/``"E"`` pair matched by span id.
  * ``instant()`` / ``counter()`` — point events and counter-track samples
    (HBM bytes-in-use, pool occupancy) on the same clock.
  * ``flow_start()/flow_end()`` — cross-node arrows: the master marks "s"
    when a FORWARD frame leaves, the worker marks "f" when it lands, linked
    by the flow id that rides the frame header — a cross-node request renders
    as one connected timeline.

Every event records BOTH clocks: ``wall`` (time.time — comparable across
processes, the export timestamp) and ``mono`` (perf_counter — drift-free
durations). Merging two nodes' exports needs only NTP-level wall agreement.

The ring is sized, not timed (newest ``capacity`` events win). Everything is
stdlib-only and thread-safe; a ``jsonl`` sink streams each event as one JSON
line for ``--trace-jsonl``.

Scheduler shapes (runtime/serving.py): the lockstep epoch roots its tree in
an ``epoch`` span; the continuous scheduler roots a ``segment`` span and
nests one ``step`` span per scheduler iteration (restores + budgeted joins),
with ``preempted``/``restored`` instants on the lane tracks — obs/critpath.py
attributes ``restore`` spans to their own phase.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import json
import threading
import time
from collections import deque
from typing import Any, Iterable

# Current innermost span: (timeline instance, span id). Context-local, so the
# engine thread, HTTP handler threads, and tests nest independently.
_CURRENT: contextvars.ContextVar[tuple["Timeline", int] | None] = (
    contextvars.ContextVar("cake_obs_span", default=None)
)

_ids = itertools.count(1)


def current_span_id() -> int | None:
    """Span id of the innermost open ``span()`` in this context (None when
    outside any span). utils/metrics.py stamps it onto flight events."""
    cur = _CURRENT.get()
    return cur[1] if cur is not None else None


def _clocks() -> tuple[float, float]:
    return time.time(), time.perf_counter()


class Timeline:
    """Bounded ring of profiling events + the Perfetto exporter over it."""

    def __init__(self, capacity: int = 8192, node: str = "local"):
        self._lock = threading.Lock()
        self._ring: deque[dict] = deque(maxlen=int(capacity))
        self._jsonl_path: str | None = None
        self.node = node  # default pid label; per-event ``node=`` overrides

    @property
    def capacity(self) -> int:
        return self._ring.maxlen or 0

    # ------------------------------------------------------------- recording

    def _record(self, ev: dict) -> dict:
        with self._lock:
            self._ring.append(ev)
            path = self._jsonl_path
        if path is not None:
            # Outside the lock (a slow disk must not serialize the engine);
            # whole-line appends interleave atomically on POSIX O_APPEND.
            try:
                with open(path, "a") as f:
                    f.write(json.dumps(ev, separators=(",", ":")) + "\n")
            except (OSError, TypeError, ValueError):
                pass
        return ev

    def _event(
        self,
        ph: str,
        name: str,
        *,
        sid: int | None = None,
        parent: int | None = None,
        rid: str | None = None,
        node: str | None = None,
        track: str | None = None,
        args: dict | None = None,
        wall: float | None = None,
        mono: float | None = None,
        dur: float | None = None,
        flow: int | None = None,
        tag: str | None = None,
    ) -> dict:
        if wall is None or mono is None:
            wall, mono = _clocks()
        ev: dict[str, Any] = {
            "ph": ph,
            "name": name,
            "wall": round(wall, 6),
            "mono": round(mono, 6),
        }
        if sid is not None:
            ev["id"] = sid
        if parent is not None:
            ev["parent"] = parent
        if rid is not None:
            ev["rid"] = rid
        if node is not None:
            ev["node"] = node
        if track is not None:
            ev["track"] = track
        if dur is not None:
            ev["dur"] = round(dur, 6)
        if flow is not None:
            ev["flow"] = flow
        if tag is not None:
            ev["tag"] = tag
        if args:
            ev["args"] = args
        return self._record(ev)

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        *,
        rid: str | None = None,
        node: str | None = None,
        track: str | None = None,
        args: dict | None = None,
    ):
        """Lexically scoped span -> one "X" complete event at exit. Yields the
        span id so the body can parent flight events / flow arrows to it."""
        sid = next(_ids)
        parent = current_span_id()
        wall, mono = _clocks()
        token = _CURRENT.set((self, sid))
        try:
            yield sid
        finally:
            _CURRENT.reset(token)
            self._event(
                "X", name, sid=sid, parent=parent, rid=rid, node=node,
                track=track, args=args, wall=wall, mono=mono,
                dur=time.perf_counter() - mono,
            )

    def begin(
        self,
        name: str,
        *,
        rid: str | None = None,
        node: str | None = None,
        track: str | None = None,
        args: dict | None = None,
        parent: int | None | str = "auto",
    ) -> int:
        """Open a non-lexical span ("B"); pair it with ``end(sid)``. The
        parent defaults to whatever span is current at BEGIN time; pass
        ``parent=None`` for a track-root span (e.g. a serving lane's request
        span, which outlives the engine spans that happen to be open when it
        is admitted — parenting it there would double-count their self time)."""
        sid = next(_ids)
        self._event(
            "B", name, sid=sid,
            parent=current_span_id() if parent == "auto" else parent,
            rid=rid, node=node, track=track, args=args,
        )
        return sid

    def end(self, sid: int, *, args: dict | None = None) -> None:
        """Close a ``begin()`` span. The name/track ride the B side; the
        exporter pairs by id. Unknown/evicted ids still record honestly (the
        exporter drops unpaired ends)."""
        self._event("E", "", sid=sid, args=args)

    def instant(self, name: str, **kw) -> None:
        self._event("i", name, **kw)

    def counter(
        self, name: str, values: dict[str, float], *,
        node: str | None = None, track: str | None = None,
        tag: str | None = None,
    ) -> None:
        """One sample on a counter track (rendered as a stacked area chart).

        ``args`` must stay numeric (Chrome counter values), so ``tag`` — the
        phase-boundary label — rides the raw ring/JSONL event instead; the
        rendered chart shows the series, the raw events say which phase
        sampled them."""
        self._event("C", name, node=node, track=track, args=dict(values),
                    tag=tag)

    def flow_start(self, flow_id: int, name: str, **kw) -> None:
        """Arrow tail: anchored at the current span/track at the call site."""
        self._event("s", name, flow=int(flow_id), **kw)

    def flow_end(self, flow_id: int, name: str, **kw) -> None:
        """Arrow head (binding point = enclosing slice, Chrome ``bp:"e"``)."""
        self._event("f", name, flow=int(flow_id), **kw)

    # ------------------------------------------------------------- sinks

    def attach_jsonl(self, path: str | None) -> None:
        """Stream every future event to ``path`` as one JSON line each
        (``--trace-jsonl``; None detaches)."""
        with self._lock:
            self._jsonl_path = path

    def snapshot(self, request_id: str | None = None) -> list[dict]:
        with self._lock:
            events = list(self._ring)
        if request_id is not None:
            keep_ids = {
                e["id"] for e in events
                if e.get("rid") == request_id and "id" in e
            }
            events = [
                e
                for e in events
                if e.get("rid") == request_id or e.get("id") in keep_ids
            ]
        return events

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    # ------------------------------------------------------------- analysis

    def aggregate(self) -> dict[str, dict]:
        """Per-span-name {count, total_s, self_s} over the ring's CLOSED
        spans — the ``cake-tpu stats --spans`` table. Self time = a span's
        duration minus its direct children's (children evicted from the ring
        simply count as self time; the ring is a window, not an archive)."""
        spans = _closed_spans(self.snapshot())
        child_total: dict[int, float] = {}
        for s in spans.values():
            p = s.get("parent")
            if p is not None:
                child_total[p] = child_total.get(p, 0.0) + s["dur"]
        out: dict[str, dict] = {}
        for sid, s in spans.items():
            agg = out.setdefault(
                s["name"], {"count": 0, "total_s": 0.0, "self_s": 0.0}
            )
            agg["count"] += 1
            agg["total_s"] += s["dur"]
            agg["self_s"] += max(0.0, s["dur"] - child_total.get(sid, 0.0))
        for agg in out.values():
            agg["total_s"] = round(agg["total_s"], 6)
            agg["self_s"] = round(agg["self_s"], 6)
        return out

    def export(self, request_id: str | None = None) -> dict:
        """Chrome trace-event JSON for Perfetto / chrome://tracing."""
        return export_events(self.snapshot(request_id), default_node=self.node)


def _closed_spans(events: Iterable[dict]) -> dict[int, dict]:
    """Span id -> {name, parent, dur, ...} for X spans and CLOSED B/E pairs."""
    out: dict[int, dict] = {}
    opens: dict[int, dict] = {}
    for e in events:
        ph = e.get("ph")
        if ph == "X" and "id" in e:
            out[e["id"]] = {
                "name": e["name"], "parent": e.get("parent"),
                "dur": float(e.get("dur", 0.0)),
            }
        elif ph == "B" and "id" in e:
            opens[e["id"]] = e
        elif ph == "E" and e.get("id") in opens:
            b = opens.pop(e["id"])
            out[e["id"]] = {
                "name": b["name"], "parent": b.get("parent"),
                "dur": max(0.0, float(e["mono"]) - float(b["mono"])),
            }
    return out


# ------------------------------------------------------------------ exporter


def export_events(events: list[dict], default_node: str = "local") -> dict:
    """Render ring events as a Chrome trace-event dict.

    pid = node (one Perfetto process group per cluster node), tid = lane /
    stream / track. Timestamps are WALL microseconds so exports from several
    nodes concatenate into one timeline; durations come from the monotonic
    clock. Contract (pinned by tests/test_timeline.py): every emitted "B" has
    a matching "E" on the same pid/tid — open spans and eviction-orphaned
    ends are dropped, never half-emitted.
    """
    pids: dict[str, int] = {}
    tids: dict[tuple[int, str], int] = {}
    meta: list[dict] = []
    out: list[dict] = []

    def pid_of(node: str) -> int:
        if node not in pids:
            pids[node] = len(pids) + 1
            meta.append({
                "ph": "M", "name": "process_name", "pid": pids[node],
                "args": {"name": node},
            })
        return pids[node]

    def tid_of(pid: int, track: str) -> int:
        key = (pid, track)
        if key not in tids:
            tids[key] = len([k for k in tids if k[0] == pid]) + 1
            meta.append({
                "ph": "M", "name": "thread_name", "pid": pid,
                "tid": tids[key], "args": {"name": track},
            })
        return tids[key]

    # Pair B/E by span id first: the exporter only emits COMPLETE pairs.
    ends: dict[int, dict] = {
        e["id"]: e
        for e in events
        if e.get("ph") == "E" and e.get("id") is not None
    }

    for e in events:
        ph = e.get("ph")
        node = e.get("node") or default_node
        pid = pid_of(node)
        track = e.get("track") or "main"
        tid = tid_of(pid, track)
        ts = float(e["wall"]) * 1e6
        args = dict(e.get("args") or {})
        if e.get("rid"):
            args["request_id"] = e["rid"]
        if e.get("parent") is not None:
            args["parent_span"] = e["parent"]
        if e.get("id") is not None:
            args["span_id"] = e["id"]
        base = {"pid": pid, "tid": tid, "ts": round(ts, 3)}
        if ph == "X":
            out.append({
                "ph": "X", "name": e["name"], "cat": "cake",
                "dur": round(float(e.get("dur", 0.0)) * 1e6, 3),
                "args": args, **base,
            })
        elif ph == "B":
            end = ends.get(e.get("id"))
            if end is None:
                continue  # still open: emit nothing rather than a lone B
            out.append({
                "ph": "B", "name": e["name"], "cat": "cake",
                "args": args, **base,
            })
            e_args = dict(end.get("args") or {})
            out.append({
                "ph": "E", "name": e["name"], "cat": "cake",
                "pid": pid, "tid": tid,
                "ts": round(float(end["wall"]) * 1e6, 3),
                "args": e_args,
            })
        elif ph == "E":
            continue  # emitted with its B (orphans dropped)
        elif ph == "i":
            out.append({
                "ph": "i", "name": e["name"], "cat": "cake", "s": "t",
                "args": args, **base,
            })
        elif ph == "C":
            out.append({
                "ph": "C", "name": e["name"], "cat": "cake",
                "args": dict(e.get("args") or {}), **base,
            })
        elif ph in ("s", "f"):
            ev = {
                "ph": ph, "name": e["name"], "cat": "flow",
                "id": e.get("flow", 0), "args": args, **base,
            }
            if ph == "f":
                ev["bp"] = "e"  # bind to the enclosing slice
            out.append(ev)
    return {"traceEvents": meta + out, "displayTimeUnit": "ms"}


def validate_export(trace: dict) -> list[str]:
    """Schema checks over an exported trace; returns problems (empty = OK).

    Pinned contract: valid trace-event JSON, every "B" matched by an "E" on
    the same pid/tid (properly nested per track), flow "s"/"f" pairs that
    land inside real slices on their track.
    """
    problems: list[str] = []
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents is not a list"]
    stacks: dict[tuple, list[tuple[str, float]]] = {}
    slices: dict[tuple, list[tuple[float, float]]] = {}
    flows: dict[tuple, list[str]] = {}
    flow_sites: list[tuple[tuple, float, Any, str]] = []
    for i, e in enumerate(events):
        if not isinstance(e, dict) or "ph" not in e or "name" not in e:
            problems.append(f"event {i} lacks ph/name: {e!r}")
            continue
        ph = e["ph"]
        if ph == "M":
            continue
        if "ts" not in e or not isinstance(e["ts"], (int, float)):
            problems.append(f"event {i} ({ph} {e['name']!r}) lacks numeric ts")
            continue
        key = (e.get("pid"), e.get("tid"))
        if ph == "X":
            if not isinstance(e.get("dur"), (int, float)) or e["dur"] < 0:
                problems.append(f"X event {e['name']!r} lacks dur >= 0")
            else:
                slices.setdefault(key, []).append(
                    (e["ts"], e["ts"] + e["dur"])
                )
        elif ph == "B":
            stacks.setdefault(key, []).append((e["name"], e["ts"]))
        elif ph == "E":
            stack = stacks.get(key)
            if not stack:
                problems.append(
                    f"E {e['name']!r} on track {key} without an open B"
                )
            else:
                _, b_ts = stack.pop()
                slices.setdefault(key, []).append((b_ts, e["ts"]))
        elif ph in ("s", "f"):
            if "id" not in e:
                problems.append(f"flow event {e['name']!r} lacks an id")
                continue
            flows.setdefault((e["id"],), []).append(ph)
            flow_sites.append((key, e["ts"], e["id"], ph))
    for key, stack in stacks.items():
        for name, _ in stack:
            problems.append(f"B {name!r} on track {key} never closed by an E")
    for (fid,), phases in flows.items():
        if "s" not in phases:
            problems.append(f"flow {fid} has an 'f' but no 's'")
    # Flow arrows must land inside a real slice on their track ("flow events
    # reference existing spans"): an arrow anchored in empty space would
    # render detached (or not at all) in Perfetto.
    for key, ts, fid, ph in flow_sites:
        if not any(lo <= ts <= hi for lo, hi in slices.get(key, ())):
            problems.append(
                f"flow {ph} (id {fid}) at ts {ts} on track {key} lands in "
                "no slice"
            )
    return problems


def load_jsonl(path: str) -> list[dict]:
    """Read a ``--trace-jsonl`` stream back into ring-event dicts (malformed
    lines raise — the smoke gate WANTS to fail on a torn write)."""
    events: list[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                events.append(json.loads(line))
    return events


# Process-global instance: one timeline serves the whole runtime (tests may
# build private ones). Mirrors metrics.registry / trace.spans.
timeline = Timeline()
span = timeline.span
