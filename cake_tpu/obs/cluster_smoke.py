"""Cluster observability smoke gate: a REAL 2-process master/worker serve
must yield ONE merged telemetry plane.

``make obs-smoke`` (wired into ``make verify`` after chaos-smoke) starts a
TCP worker as a SEPARATE PROCESS (its own registry, timeline, and clock —
the honest shape for federation; the in-process test clusters share
globals) and a batch-engine master with heartbeat probing, drives traffic
through the OpenAI API + engine, and exits nonzero unless:

  * the master's ``GET /metrics`` is ONE exposition carrying BOTH nodes'
    series under ``node`` labels — worker-side ``cake_worker_op_seconds
    {node="w0"}`` (pulled over the STATS wire message) next to master-side
    series under ``node="master"`` — plus the clock-offset gauge;
  * ``GET /trace?cluster=1`` passes ``validate_export`` with >= 2 process
    tracks, at least one cross-process flow arrow (``s`` on the master
    pid, ``f`` on the worker pid), and at least one worker op span whose
    interval NESTS inside the master ``wire.w0`` span that caused it
    after clock alignment;
  * ``GET /slo`` reports a NONZERO burn rate for a tenant driven past its
    declared TTFT objective (its requests expire without ever producing a
    first token) while the compliant tenant's burn rate stays 0;
  * ``GET /explain`` decomposes the long stream's latency into the
    critical-path phases (obs/critpath.py) with the phase sum matching
    the CLIENT-measured end-to-end elapsed within 15% (CI-safe bound;
    the tier-1 batch-8 oracle pins the tighter 95% contract);
  * a seeded ``stall@backend.decode`` (8s, against a 3s watchdog) yields
    exactly ONE new blackbox bundle (obs/blackbox.py) that ``cake-tpu
    doctor`` attributes to ``stall``;
  * ``GET /efficiency`` (obs/efficiency.py) accounts >= 95% of the
    measured device wall into buckets with goodput > 0, its decision ring
    holds the run's admit verdicts, ``cake_device_seconds_total`` rides
    the node-labelled federated exposition, and ``cake-tpu top --once``
    renders the dashboard against the live server and exits 0.

Usage: ``python -m cake_tpu.obs.cluster_smoke [--tokens N]``
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _get(base: str, route: str):
    with urllib.request.urlopen(base + route, timeout=30) as r:
        body = r.read()
    ctype = r.headers.get("Content-Type", "")
    return body.decode() if "text/plain" in ctype else json.loads(body)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="cake-tpu obs-smoke")
    p.add_argument("--tokens", type=int, default=200)
    args = p.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from cake_tpu.io.safetensors_io import save_tiny_checkpoint
    from cake_tpu.models.llama import model as M
    from cake_tpu.models.llama.chat import Message
    from cake_tpu.models.llama.config import LlamaConfig
    from cake_tpu.models.llama.generator import LlamaGenerator, SamplingConfig
    from cake_tpu.models.llama.tokenizer import ByteTokenizer
    from cake_tpu.obs.timeline import validate_export
    from cake_tpu.parallel.topology import Topology
    from cake_tpu.runtime import faults
    from cake_tpu.runtime.api import ApiServer
    from cake_tpu.runtime.batch_backend import DistributedBatchBackend
    from cake_tpu.runtime.master import DistributedForwardStep
    from cake_tpu.runtime.serving import BatchEngine, ServeConfig

    problems: list[str] = []
    greedy = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
    work = tempfile.mkdtemp(prefix="cake-obs-smoke-")
    model_dir = os.path.join(work, "model")
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(3), jnp.float32)
    save_tiny_checkpoint(model_dir, params, cfg)

    port = _free_port()
    topo = Topology.from_dict(
        {"w0": {"host": f"127.0.0.1:{port}",
                "layers": ["model.layers.0-1"]}}
    )
    topo_path = os.path.join(work, "topology.yaml")
    topo.save(topo_path)

    # The worker is a REAL separate process: its own registry/timeline/
    # clock — what the federation plane exists to reach.
    worker_env = dict(os.environ, JAX_PLATFORMS="cpu")
    worker_env.pop("CAKE_FAULTS", None)
    worker = subprocess.Popen(
        [
            sys.executable, "-m", "cake_tpu.cli",
            "--model", model_dir, "--mode", "worker", "--name", "w0",
            "--topology", topo_path, "--address", f"127.0.0.1:{port}",
            "--cpu", "--dtype", "f32", "--max-seq-len", "256",
        ],
        env=worker_env,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )

    server = None
    engine = None
    step = None
    try:
        # Wait for the worker to answer the handshake.
        deadline = time.monotonic() + 120.0
        while True:
            try:
                with socket.create_connection(
                    ("127.0.0.1", port), timeout=1.0
                ):
                    break
            except OSError:
                if time.monotonic() > deadline or worker.poll() is not None:
                    print("FAIL: worker process never came up")
                    return 1
                time.sleep(0.25)

        step = DistributedForwardStep(
            cfg, model_dir, topo, dtype=jnp.float32, max_seq_len=256,
            op_deadline_s=30.0,
        )
        engine = BatchEngine(
            cfg, None, ByteTokenizer(),
            max_seq_len=256, cache_dtype=jnp.float32,
            backend=DistributedBatchBackend(
                step, max_seq_len=256, cache_dtype=jnp.float32
            ),
            serve=ServeConfig(
                max_batch=1,            # storm requests cannot join: they
                decode_chunk_size=4,    # queue behind the long epoch
                admission_window=0.01,
                heartbeat_interval_s=0.25,
                slo_ttft_ms=60_000.0,   # generous: compile-laden warmup
                slo_ttft_target=0.9,    # still complies
                slo_deadline_rate=0.9,
                slo_fast_window_s=10.0,
                slo_slow_window_s=60.0,
                # Watchdog + black-box capture for gate 5: 3s bound (10x
                # first-call grace per op covers the worker's compiles)
                # against an 8s seeded stall; every trigger captures (no
                # rate limit) so "exactly one NEW bundle" is exact.
                epoch_stall_s=3.0,
                blackbox_dir=os.path.join(work, "blackbox"),
                blackbox_min_interval_s=0.0,
            ),
        )
        generator = LlamaGenerator(cfg, step, ByteTokenizer(), greedy)
        api = ApiServer(generator, engine=engine)  # starts the engine
        server = api.make_server("127.0.0.1", 0)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{server.server_address[1]}"

        # ---- drive traffic --------------------------------------------
        # Warmup + compliant tenant over the REAL HTTP path.
        req = urllib.request.Request(
            base + "/api/v1/chat/completions",
            data=json.dumps({
                "messages": [{"role": "user", "content": "hello obs"}],
                "max_tokens": 4, "tenant": "gold",
            }).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=300) as r:
            json.load(r)

        # A long greedy stream holds the single lane while each decode
        # dispatch is deterministically slowed — the storm tenant's
        # requests are GUARANTEED to expire queued (no first token ever),
        # which is what "driven past its declared TTFT objective" means
        # for a request that dies tokenless.
        faults.install(
            faults.parse("stall@backend.decode:delay_s=0.03:count=0")
        )
        long_t0 = time.monotonic()
        long_h = engine.submit(
            [Message.user("hold the lane " * 3)], args.tokens, greedy,
            tenant="gold",
        )
        time.sleep(0.3)  # let the epoch start before the storm queues
        storm = [
            engine.submit(
                [Message.user("storm")], 4, greedy,
                tenant="storm", deadline_s=0.3,
            )
            for _ in range(3)
        ]
        for h in storm:
            h.text()
        long_h.text()
        # Client-measured end-to-end for gate 4: the storm text() calls
        # above return at their 0.3s deadlines while the long stream is
        # still decoding, so this read lands at its real finish.
        long_elapsed = time.monotonic() - long_t0
        faults.clear()
        storm_reasons = [h.finish_reason for h in storm]
        if "deadline" not in storm_reasons:
            problems.append(
                f"storm requests never expired (got {storm_reasons}); "
                "the burn gate below would be vacuous"
            )

        # Fresh federation pull so the scrapes see post-traffic state.
        pulled = step.pull_cluster_stats()
        if pulled != ["w0"]:
            problems.append(f"stats pull reached {pulled}, wanted ['w0']")
        engine._apply_slo_feedback(force=True)

        # ---- gate 1: ONE merged /metrics ------------------------------
        text = _get(base, "/metrics")
        if 'cake_worker_op_seconds_count{kind="prefill",node="w0"}' \
                not in text:
            problems.append(
                "/metrics lacks worker-side cake_worker_op_seconds"
                '{node="w0"} series (federation pull broken?)'
            )
        if 'node="master"' not in text:
            problems.append(
                '/metrics carries no node="master" series: the merged '
                "exposition did not label the master's own metrics"
            )
        if 'cake_clock_offset_seconds{node="w0"}' not in text:
            problems.append(
                "/metrics lacks cake_clock_offset_seconds{node=\"w0\"}"
            )

        # ---- gate 2: merged trace, aligned + nested -------------------
        trace = _get(base, "/trace?cluster=1")
        bad = validate_export(trace)
        if bad:
            problems.append(f"merged trace invalid: {bad[:3]}")
        events = trace.get("traceEvents", [])
        pid_names = {
            e["pid"]: e["args"]["name"]
            for e in events
            if e.get("ph") == "M" and e.get("name") == "process_name"
        }
        if len(pid_names) < 2:
            problems.append(
                f"merged trace has {len(pid_names)} process track(s); "
                "wanted master + w0"
            )
        wire_slices = [
            (e["ts"], e["ts"] + e["dur"])
            for e in events
            if e.get("ph") == "X" and e.get("name") == "wire.w0"
            and pid_names.get(e.get("pid")) != "w0"
        ]
        op_slices = [
            (e["ts"], e["ts"] + e["dur"])
            for e in events
            if e.get("ph") == "X"
            and str(e.get("name", "")).startswith("worker.")
            and pid_names.get(e.get("pid")) == "w0"
        ]
        nested = sum(
            any(w0 <= o0 and o1 <= w1 for (w0, w1) in wire_slices)
            for (o0, o1) in op_slices
        )
        if not op_slices or not wire_slices or nested == 0:
            problems.append(
                f"no worker op span nests inside a master wire.w0 span "
                f"after clock alignment ({len(op_slices)} op, "
                f"{len(wire_slices)} wire slices, {nested} nested)"
            )
        flow_pids = {}
        for e in events:
            if e.get("ph") in ("s", "f"):
                flow_pids.setdefault(e["id"], {})[e["ph"]] = pid_names.get(
                    e.get("pid")
                )
        cross = sum(
            1 for v in flow_pids.values()
            if v.get("s") and v.get("f") and v["s"] != v["f"]
        )
        if cross == 0:
            problems.append(
                "no flow arrow crosses process tracks in the merged trace"
            )

        # ---- gate 3: /slo burn attribution ----------------------------
        slo = _get(base, "/slo")
        tenants = slo.get("tenants", {})
        storm_burn = tenants.get("storm", {}).get("burn_rate", 0.0)
        gold_burn = tenants.get("gold", {}).get("burn_rate", 0.0)
        if storm_burn <= 0:
            problems.append(
                f"storm tenant burn rate is {storm_burn}; wanted > 0 "
                f"(slo body: {json.dumps(tenants)[:400]})"
            )
        if gold_burn != 0:
            problems.append(
                f"compliant gold tenant burn rate is {gold_burn}; wanted 0"
            )

        # ---- gate 4: /explain phase decomposition ---------------------
        # The sum is gated against the CLIENT-measured end-to-end
        # elapsed, not the response's own wall_s (host/other are
        # complements of that, so wall_s == sum by construction and
        # would gate nothing).
        exp = _get(base, f"/explain?request_id={long_h.request_id}")
        phases = exp.get("phases") or {}
        total = sum(float(v) for v in phases.values())
        if not phases:
            problems.append(f"/explain returned no phases ({exp})")
        elif abs(total - long_elapsed) > max(0.15 * long_elapsed, 0.5):
            problems.append(
                f"/explain phases sum {total:.4f}s != client-measured "
                f"end-to-end {long_elapsed:.4f}s within 15%"
            )
        elif float(phases.get("decode", 0.0)) <= 0.0:
            problems.append(
                f"/explain attributes no decode time to a 200-token "
                f"stream (phases: {phases})"
            )
        elif float(exp.get("coverage", 0.0)) < 0.5:
            problems.append(
                f"/explain named-phase coverage {exp.get('coverage')} "
                "< 0.5: attribution is mostly unexplained host time"
            )

        # ---- gate 5: seeded stall -> ONE bundle doctor blames on stall -
        from cake_tpu.obs import blackbox as bb

        bdir = engine.blackbox.dir
        before = set(engine.blackbox.bundles())
        faults.install(faults.parse("stall@backend.decode:delay_s=8"))
        stall_h = engine.submit(
            [Message.user("stall victim")], 8, greedy, tenant="gold",
        )
        stall_h.text()
        faults.clear()
        if stall_h.finish_reason != "error":
            problems.append(
                f"stalled stream finished {stall_h.finish_reason!r}; "
                "wanted the watchdog's 'error' isolation"
            )
        new = [p2 for p2 in engine.blackbox.bundles() if p2 not in before]
        if len(new) != 1:
            problems.append(
                f"seeded stall produced {len(new)} new blackbox "
                f"bundle(s) in {bdir}; wanted exactly 1"
            )
        else:
            bundle = bb.load_bundle(new[0])
            diag = bb.diagnose(bundle)
            if diag["cause"] != "stall":
                problems.append(
                    f"doctor blames {diag['cause']!r} (reason="
                    f"{bundle.get('reason')!r}); wanted 'stall'"
                )
            report = bb.render_report(bundle)
            if "cause:    stall" not in report:
                problems.append(
                    f"doctor report does not name the stall cause:\n"
                    f"{report[:400]}"
                )

        # ---- gate 6: /efficiency ledger + federated buckets + top -----
        # The goodput ledger's accounting invariant on a REAL serve:
        # bucket seconds sum to >= 95% of the wall between the engine's
        # first and last dispatch (the ledger claims 100% by
        # construction; the gate absorbs rounding), useful work landed,
        # and the device-seconds counter rides the same node-labelled
        # federation plane as every other series.
        eff = _get(base, "/efficiency")
        wall = float(eff.get("wall_s", 0.0))
        accounted = float(eff.get("accounted_s", 0.0))
        if wall <= 0 or eff.get("dispatches", 0) <= 0:
            problems.append(
                f"/efficiency saw no dispatches after the traffic above "
                f"(body: {json.dumps(eff)[:300]})"
            )
        elif accounted < 0.95 * wall:
            problems.append(
                f"/efficiency buckets sum to {accounted:.4f}s of "
                f"{wall:.4f}s device wall (< 95%)"
            )
        if eff.get("goodput_frac", 0.0) <= 0.0:
            problems.append(
                f"/efficiency goodput_frac is {eff.get('goodput_frac')}; "
                "wanted > 0 after served streams"
            )
        if eff.get("goodput_tokens", 0) <= 0:
            problems.append(
                "/efficiency goodput_tokens is 0 after completed streams"
            )
        decisions = eff.get("decision_ring", [])
        if not any(d.get("action") == "admit" for d in decisions):
            problems.append(
                "/efficiency decision ring recorded no admit verdicts"
            )
        text = _get(base, "/metrics")
        if not any(
            line.startswith("cake_device_seconds_total{")
            and 'node="master"' in line
            for line in text.splitlines()
        ):
            problems.append(
                "/metrics lacks node-labelled cake_device_seconds_total "
                "buckets in the federated exposition"
            )
        top = subprocess.run(
            [
                sys.executable, "-m", "cake_tpu.cli", "top",
                "--once", "--url", base,
            ],
            env=worker_env, capture_output=True, text=True, timeout=60,
        )
        if top.returncode != 0:
            problems.append(
                f"cake-tpu top --once exited {top.returncode}: "
                f"{(top.stderr or top.stdout)[:300]}"
            )
        elif "goodput" not in top.stdout:
            problems.append(
                f"cake-tpu top --once rendered no goodput headline:\n"
                f"{top.stdout[:300]}"
            )
    finally:
        faults.clear()
        if server is not None:
            server.shutdown()
        if engine is not None:
            engine.stop()
        if step is not None:
            step.close()
        worker.terminate()
        try:
            worker.wait(timeout=10)
        except subprocess.TimeoutExpired:
            worker.kill()

    if problems:
        print("FAIL cluster-obs smoke:")
        for prob in problems:
            print(f"  - {prob}")
        return 1
    print(
        "PASS cluster-obs smoke: merged /metrics carries both nodes, the "
        "cluster trace aligns and nests across processes, /slo attributes "
        "burn to the offending tenant only, /explain decomposes the "
        "stream's latency to its wall, the seeded stall yields one "
        "doctor-attributed blackbox bundle, and /efficiency accounts the "
        "device wall with cake-tpu top rendering it live"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
