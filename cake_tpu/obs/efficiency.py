"""Goodput & hardware-efficiency ledger + scheduler decision audit.

The critical-path explainer (obs/critpath.py) answers "where did this
REQUEST's latency go"; this module answers "where did the HARDWARE go":
every second between the engine's first and last backend dispatch is
classified into exactly one bucket of the shared taxonomy
(obs/taxonomy.py BUCKETS), every emitted token into a goodput/waste
class, and an analytic FLOPs/HBM-bytes model per dispatch turns the
useful fraction into MFU / memory-bandwidth-utilization estimates
against device peaks. Paired with it, the :class:`DecisionAudit` ring
records a structured cause for every scheduler verdict — admit, defer,
preempt, spill, restore, shed — so ``cake-tpu explain`` can answer "WHY
was this request queued/preempted", not just "how long".

Accounting invariant (pinned by tests/test_efficiency.py): the engine
thread calls one ``note_*`` per dispatch with the dispatch's measured
wall; the ledger derives the device-idle gap between consecutive
dispatches itself (``host_gap``), so the buckets ALWAYS sum to the
measured device wall — the obs-smoke gate checks ≥95% only to absorb
float rounding and the final in-flight dispatch.

Roofline model (README "Goodput & hardware efficiency"): per dispatch,
``FLOPs ≈ positions · 2 · P_active + 4 · L · d_attn · Σctx +
logit_positions · 2 · V · d_model`` and ``bytes ≈ passes · P_active ·
dtype + (Σctx + positions) · kv_bytes_per_slot`` — an ESTIMATE from the
model config, not a profile; expect ±20% against hardware counters
(attention masking, remat, and collective traffic are not modelled).
MFU/MBU are reported only when a peak is known: ``--peak-tflops`` /
``--peak-hbm-gbps`` override a small built-in TPU table keyed by
``jax.devices()[0].device_kind``; on CPU (no entry, no override) the
snapshot carries absolute achieved numbers only.

Everything here is host-side arithmetic — a few float adds per dispatch
on numbers the engine already measured; no device work, no extra
dispatches.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from cake_tpu.obs.taxonomy import (
    BUCKETS,
    DECISION_ACTIONS,
    DECISION_CAUSES,
    GOODPUT_BUCKETS,
    TOKEN_CLASSES,
)
from cake_tpu.utils import metrics

# bf16 dense peaks per chip, (TFLOP/s, HBM GB/s), matched by substring
# against ``device_kind`` (most specific first). Datasheet numbers — the
# point is a stable denominator for A/Bs, not a lab-grade MFU.
_DEVICE_PEAKS: tuple[tuple[str, float, float], ...] = (
    ("v6 lite", 918.0, 1640.0),
    ("v6e", 918.0, 1640.0),
    ("v5 lite", 197.0, 819.0),
    ("v5e", 197.0, 819.0),
    ("v5p", 459.0, 2765.0),
    ("v5", 459.0, 2765.0),
    ("v4", 275.0, 1228.0),
    ("v3", 123.0, 900.0),
    ("v2", 46.0, 700.0),
)


def device_peaks() -> tuple[float, float, str] | None:
    """(peak_tflops, peak_hbm_gbps, device_kind) for the first visible
    accelerator, or None when the platform has no table entry (CPU)."""
    try:
        import jax

        kind = jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001 — no jax / no devices = no peaks
        return None
    low = kind.lower()
    for sub, tf, bw in _DEVICE_PEAKS:
        if sub in low:
            return tf, bw, kind
    return None


def model_active_params(config) -> int:
    """Parameters touched per token (decoder stack only; embeddings and
    the LM head are costed separately at their own positions). MoE
    counts only the routed-active experts."""
    h = int(getattr(config, "hidden_size", 0))
    heads = int(getattr(config, "num_attention_heads", 1))
    kv_heads = int(getattr(config, "num_key_value_heads", heads))
    hd = int(getattr(config, "head_dim_override", None) or (h // max(1, heads)))
    inter = int(getattr(config, "intermediate_size", 0))
    layers = int(getattr(config, "num_hidden_layers", 0))
    attn = h * heads * hd + 2 * h * kv_heads * hd + heads * hd * h
    n_experts = int(getattr(config, "num_local_experts", 0) or 0)
    if n_experts:
        top_k = int(getattr(config, "num_experts_per_tok", 1) or 1)
        e_inter = int(getattr(config, "moe_intermediate_size", 0) or inter)
        mlp = top_k * 3 * h * e_inter + h * n_experts  # + router
    else:
        mlp = 3 * h * inter
    return layers * (attn + mlp)


def dispatch_flops(
    config, positions: int, ctx_sum: int, logit_positions: int = 0
) -> float:
    """Analytic FLOPs of one batched forward: ``positions`` token slots
    through the decoder (2 FLOPs per param per position), attention
    score+value over ``ctx_sum`` total key slots (4·d_attn each per
    layer), plus the LM-head matmul at ``logit_positions``."""
    h = int(getattr(config, "hidden_size", 0))
    heads = int(getattr(config, "num_attention_heads", 1))
    hd = int(getattr(config, "head_dim_override", None) or (h // max(1, heads)))
    layers = int(getattr(config, "num_hidden_layers", 0))
    vocab = int(getattr(config, "vocab_size", 0))
    return (
        2.0 * positions * model_active_params(config)
        + 4.0 * layers * heads * hd * float(ctx_sum)
        + 2.0 * logit_positions * vocab * h
    )


def dispatch_hbm_bytes(
    config, positions: int, ctx_sum: int, passes: int = 1,
    dtype_bytes: int = 2,
) -> float:
    """Analytic HBM traffic of one batched forward: the weight matrices
    stream once per sequential pass (a decode chunk of n steps = n
    passes; a prefill/verify window = 1), KV reads cover ``ctx_sum``
    total key slots, KV writes cover ``positions`` new slots."""
    h = int(getattr(config, "hidden_size", 0))
    heads = int(getattr(config, "num_attention_heads", 1))
    kv_heads = int(getattr(config, "num_key_value_heads", heads))
    hd = int(getattr(config, "head_dim_override", None) or (h // max(1, heads)))
    layers = int(getattr(config, "num_hidden_layers", 0))
    kv_slot = 2 * layers * kv_heads * hd * dtype_bytes  # k + v, one slot
    return (
        float(passes) * model_active_params(config) * dtype_bytes
        + float(ctx_sum + positions) * kv_slot
    )


class DecisionAudit:
    """Bounded ring of structured scheduler verdicts.

    Every admit/defer/preempt/spill/restore/shed decision the engine
    takes lands here as ``{t, action, cause, rid, tenant, detail}`` with
    the action/cause vocabulary pinned to obs/taxonomy.py (an unknown
    name raises — drift fails loudly, and the lint rule catches it
    statically). ``for_request`` answers "why was THIS request
    queued/preempted"; the counters ride
    ``cake_sched_decisions_total{action,cause}``.
    """

    def __init__(self, keep: int = 1024, time_fn=time.time):
        self._ring: deque[dict] = deque(maxlen=max(1, keep))
        self._lock = threading.Lock()
        self._time = time_fn
        self._counts: dict[tuple[str, str], int] = {}
        # Resolved once: record() runs on the scheduler's per-step path.
        self._metric = metrics.registry.counter(
            "cake_sched_decisions_total",
            "Scheduler decision-audit verdicts by action and structured "
            "cause (obs/taxonomy.py vocabulary).",
        )
        # A stuck verdict repeats every scheduler step (a request deferred
        # on page pressure, the engine-wide budget grant): the ring keeps
        # only the FIRST of a consecutive identical run — the counters
        # still count every occurrence — so per-request causes are never
        # evicted by a thousand identical lines.
        self._last: tuple | None = None

    def record(
        self, action: str, cause: str, rid: str = "", tenant: str = "",
        detail: str = "",
    ) -> None:
        if action not in DECISION_ACTIONS:
            raise ValueError(f"unknown decision action {action!r}")
        if cause not in DECISION_CAUSES:
            raise ValueError(f"unknown decision cause {cause!r}")
        key = (action, cause, rid, detail)
        entry = {
            "t": round(self._time(), 3), "action": action, "cause": cause,
            "rid": rid, "tenant": tenant, "detail": detail,
        }
        with self._lock:
            if key != self._last:
                self._ring.append(entry)
                self._last = key
            k = (action, cause)
            self._counts[k] = self._counts.get(k, 0) + 1
        self._metric.inc(action=action, cause=cause)

    def for_request(self, rid: str) -> list[dict]:
        with self._lock:
            return [e for e in self._ring if e["rid"] == rid]

    def snapshot(self, limit: int = 0) -> list[dict]:
        with self._lock:
            out = list(self._ring)
        return out[-limit:] if limit else out

    def counts(self) -> dict[str, int]:
        with self._lock:
            return {
                f"{a}:{c}": n for (a, c), n in sorted(self._counts.items())
            }


class EfficiencyLedger:
    """Per-step device-time + token-goodput accounting (engine thread
    writes, HTTP threads snapshot under one small lock)."""

    def __init__(
        self, config=None, peak_tflops: float = 0.0,
        peak_hbm_gbps: float = 0.0, time_fn=time.perf_counter,
        audit: DecisionAudit | None = None,
    ):
        self._config = config
        self._time = time_fn
        self._lock = threading.Lock()
        self.audit = audit if audit is not None else DecisionAudit()
        self.buckets: dict[str, float] = {b: 0.0 for b in BUCKETS}
        self.tokens: dict[str, int] = {c: 0 for c in TOKEN_CLASSES}
        self.tenants: dict[str, dict[str, int]] = {}
        self.flops_total = 0.0
        self.hbm_bytes_total = 0.0
        self.dispatches = 0
        self._t_first = 0.0
        self._t_last = 0.0
        # Resolved once: _add/note_finish run per dispatch on the engine
        # thread — the registry lookup must not ride the hot path.
        self._seconds_metric = metrics.registry.counter(
            "cake_device_seconds_total",
            "Device wall seconds by efficiency bucket (obs/taxonomy.py "
            "BUCKETS; host_gap = idle between dispatches).",
        )
        self._tokens_metric = metrics.registry.counter(
            "cake_goodput_tokens_total",
            "Emitted tokens by goodput class (completed = kept output; "
            "cancelled/deadline/error = wasted device work).",
        )
        if peak_tflops > 0 or peak_hbm_gbps > 0:
            self.peak_tflops = float(peak_tflops)
            self.peak_hbm_gbps = float(peak_hbm_gbps)
            self.peak_source = "flag"
        else:
            found = device_peaks()
            if found is not None:
                self.peak_tflops, self.peak_hbm_gbps, self.peak_source = found
            else:
                self.peak_tflops = self.peak_hbm_gbps = 0.0
                self.peak_source = "none"

    def reset(self) -> None:
        """Restart the accounting window. The bench warms engines up one
        round so jit compiles land outside its clocks — a reset after
        that round keeps the snapshot to steady state too (the first
        engine to compile would otherwise book multi-second compile
        walls as prefill/pad and skew the scheduler A/B). Prometheus
        counters are monotonic by contract and keep running."""
        with self._lock:
            self.buckets = {b: 0.0 for b in BUCKETS}
            self.tokens = {c: 0 for c in TOKEN_CLASSES}
            self.tenants = {}
            self.flops_total = 0.0
            self.hbm_bytes_total = 0.0
            self.dispatches = 0
            self._t_first = self._t_last = 0.0

    # ------------------------------------------------- dispatch accounting

    def _add(self, dt: float, splits: dict[str, float]) -> None:
        """Land one dispatch's wall into buckets (``splits`` fractions
        must cover 1.0) and advance the host-gap tracker."""
        if dt <= 0.0:
            return
        now = self._time()
        start = now - dt
        counter = self._seconds_metric
        with self._lock:
            if self._t_first == 0.0:
                self._t_first = start
            gap = start - self._t_last if self._t_last else 0.0
            if gap > 0.0:
                self.buckets["host_gap"] += gap
                counter.inc(gap, bucket="host_gap")
            self._t_last = max(self._t_last, now)
            self.dispatches += 1
            for bucket, frac in splits.items():
                if frac <= 0.0:
                    continue
                self.buckets[bucket] += dt * frac
                counter.inc(dt * frac, bucket=bucket)

    def _model(self, positions: int, ctx_sum: int, logit_positions: int,
               passes: int) -> None:
        if self._config is None:
            return
        with self._lock:
            self.flops_total += dispatch_flops(
                self._config, positions, ctx_sum, logit_positions
            )
            self.hbm_bytes_total += dispatch_hbm_bytes(
                self._config, positions, ctx_sum, passes
            )

    def note_prefill(
        self, dt: float, lanes: int, width: int, own_tokens: int,
        restore: bool = False,
    ) -> None:
        """A batched prefill window: ``lanes`` × ``width`` positions
        computed, ``own_tokens`` of them live prompt/history (the rest
        is left-padding + dummy lanes). ``restore=True`` books the live
        share as re-prefill (spill/restore redone work) instead of
        useful prefill."""
        total = max(1, lanes * width)
        own = min(1.0, own_tokens / total)
        self._add(dt, {
            "restore_prefill" if restore else "prefill": own,
            "pad": 1.0 - own,
        })
        # Causal window: position i attends ~i keys; Σctx ≈ width²/2.
        self._model(
            lanes * width, lanes * (width * width) // 2, lanes, passes=1
        )

    def note_decode(
        self, dt: float, lanes: int, n: int, live: int, consumed: int,
        slot: int = 0,
    ) -> None:
        """One decode chunk: ``lanes`` × ``n`` positions computed,
        ``live`` lanes carrying real streams which consumed ``consumed``
        tokens in total. Unconsumed live positions are convoy
        (EOS/budget mid-chunk); dead-lane positions are pad."""
        total = max(1, lanes * n)
        used = min(1.0, consumed / total)
        live_frac = min(1.0, (live * n) / total)
        self._add(dt, {
            "decode": used,
            "convoy": max(0.0, live_frac - used),
            "pad": 1.0 - live_frac,
        })
        self._model(
            lanes * n, lanes * n * (slot + n // 2), lanes * n, passes=n
        )

    def note_spec(
        self, dt: float, lanes: int, k: int, live: int, used: int,
        slot: int = 0,
    ) -> None:
        """One speculative verify round: ``lanes`` × ``k+1`` positions,
        ``used`` accepted into live streams; the rest of the live share
        is the wasted half of the speculative split."""
        width = k + 1
        total = max(1, lanes * width)
        acc = min(1.0, used / total)
        live_frac = min(1.0, (live * width) / total)
        self._add(dt, {
            "spec_accepted": acc,
            "spec_wasted": max(0.0, live_frac - acc),
            "pad": 1.0 - live_frac,
        })
        self._model(
            lanes * width, lanes * width * (slot + width // 2),
            lanes * width, passes=1,
        )

    def note_stall(self, dt: float) -> None:
        """Dispatch wall abandoned by the stuck-epoch watchdog."""
        self._add(dt, {"stall": 1.0})

    def note_failover(self, dt: float) -> None:
        """A live-stream migration's re-prefill wall (redone work)."""
        self._add(dt, {"failover": 1.0})

    # --------------------------------------------------- token accounting

    def note_finish(self, tenant: str, finish_reason: str, tokens: int) -> None:
        """Class every emitted token of a finished stream: ``stop`` /
        ``length`` finishes are goodput (``completed``); cancelled /
        deadline / error tokens were device work for output nobody kept.
        The per-tenant tallies are the attribution the SLO tracker's
        goodput SLI rides next to."""
        if tokens <= 0:
            return
        cls = (
            "completed" if finish_reason in ("stop", "length")
            else finish_reason if finish_reason in TOKEN_CLASSES
            else "error"
        )
        with self._lock:
            self.tokens[cls] += tokens
            t = self.tenants.setdefault(
                tenant, {"goodput_tokens": 0, "wasted_tokens": 0}
            )
            t["goodput_tokens" if cls == "completed" else "wasted_tokens"] += (
                tokens
            )
        self._tokens_metric.inc(tokens, **{"class": cls})

    # ------------------------------------------------------------- views

    def snapshot(self) -> dict:
        with self._lock:
            buckets = dict(self.buckets)
            tokens = dict(self.tokens)
            tenants = {t: dict(d) for t, d in self.tenants.items()}
            flops, hbm = self.flops_total, self.hbm_bytes_total
            dispatches = self.dispatches
            wall = max(0.0, self._t_last - self._t_first)
        accounted = sum(buckets.values())
        device_s = accounted - buckets["host_gap"]
        useful = sum(buckets[b] for b in GOODPUT_BUCKETS)
        goodput_tok = tokens["completed"]
        out = {
            "wall_s": round(wall, 6),
            "accounted_s": round(accounted, 6),
            "device_s": round(device_s, 6),
            "dispatches": dispatches,
            "buckets": {b: round(v, 6) for b, v in buckets.items()},
            "bucket_frac": {
                b: round(v / accounted, 4) if accounted else 0.0
                for b, v in buckets.items()
            },
            "goodput_frac": round(useful / accounted, 4) if accounted else 0.0,
            "tokens": tokens,
            "goodput_tokens": goodput_tok,
            "tenants": tenants,
            "decisions": self.audit.counts(),
        }
        model: dict = {
            "flops_total": round(flops, 1),
            "hbm_bytes_total": round(hbm, 1),
        }
        if device_s > 0:
            model["achieved_tflops"] = round(flops / device_s / 1e12, 4)
            model["achieved_hbm_gbps"] = round(hbm / device_s / 1e9, 4)
        out["model"] = model
        roof: dict = {"source": self.peak_source}
        if self.peak_source != "none":
            roof["peak_tflops"] = self.peak_tflops
            roof["peak_hbm_gbps"] = self.peak_hbm_gbps
            if device_s > 0 and self.peak_tflops > 0:
                roof["mfu"] = round(
                    flops / device_s / (self.peak_tflops * 1e12), 4
                )
            if device_s > 0 and self.peak_hbm_gbps > 0:
                roof["mbu"] = round(
                    hbm / device_s / (self.peak_hbm_gbps * 1e9), 4
                )
        out["roofline"] = roof
        return out

    def refresh_metrics(self) -> None:
        """Scrape-time gauges (the /metrics route calls this, mirroring
        SloTracker.refresh_metrics): snapshot-derived ratios that cannot
        ride monotonic counters."""
        snap = self.snapshot()
        metrics.registry.gauge(
            "cake_goodput_frac",
            "Useful fraction of accounted device wall "
            "(prefill + decode + spec_accepted over all buckets).",
        ).set(snap["goodput_frac"])
        mfu = snap["roofline"].get("mfu")
        if mfu is not None:
            metrics.registry.gauge(
                "cake_mfu",
                "Model FLOPs utilization estimate against the device "
                "peak (analytic roofline; ±20%).",
            ).set(mfu)
        mbu = snap["roofline"].get("mbu")
        if mbu is not None:
            metrics.registry.gauge(
                "cake_mbu",
                "HBM bandwidth utilization estimate against the device "
                "peak (analytic roofline; ±20%).",
            ).set(mbu)
