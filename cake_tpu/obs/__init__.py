"""cake_tpu.obs — structured profiling: span-tree timeline (Perfetto export),
jit retrace/compile watchdog, HBM/host memory watermarks — plus the
interpretation layer: per-request critical-path attribution (``critpath``),
black-box anomaly bundles (``blackbox``), and the bench perf ledger
(``perf_ledger``).

Pillars over the PR 1 metrics layer (utils/metrics.py):

  * ``obs.timeline`` — contextvar span trees in a bounded ring; Chrome
    trace-event export for Perfetto (``GET /trace``, ``cake-tpu trace``,
    ``--trace-jsonl``). Import-light (stdlib only).
  * ``obs.jitwatch`` — counts traces and wall compile time per tracked jit
    family; armed mode turns "steady state never retraces" into a pinned
    (optionally fatal) runtime invariant. Imports jax lazily.
  * ``obs.memwatch`` — per-device bytes_in_use / peak + host RSS sampled at
    phase boundaries into gauges AND timeline counter tracks.

``from cake_tpu import obs`` never imports jax; the jax-touching submodules
load on first attribute access so the lint CLI / stats poller stay light.
"""

from __future__ import annotations

from cake_tpu.obs.timeline import (  # noqa: F401  (re-exports)
    Timeline,
    current_span_id,
    export_events,
    load_jsonl,
    span,
    timeline,
    validate_export,
)

_LAZY = ("jitwatch", "memwatch", "critpath", "blackbox")


def __getattr__(name: str):
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(f"cake_tpu.obs.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'cake_tpu.obs' has no attribute {name!r}")
