"""HBM/host memory watermarks: allocator truth on the same clock as spans.

The paged pool's gauges (cake_kv_pages_*) say what the ALLOCATOR thinks; this
module samples what the BACKEND says — per-device ``bytes_in_use`` /
``peak_bytes_in_use`` plus host RSS — at phase boundaries, into:

  * gauges: ``cake_hbm_bytes_in_use{device}``, ``cake_hbm_peak_bytes_in_use
    {device}``, ``cake_host_rss_bytes`` (scraped with everything else), and
  * timeline counter events (ph "C"), so pool occupancy, allocator gauges,
    and real HBM line up on ONE Perfetto view.

Sampling is throttled (``min_interval_s``) because phase boundaries on a fast
decode loop arrive every few ms; devices without memory_stats (CPU) simply
contribute no HBM series — host RSS still lands.
"""

from __future__ import annotations

import logging
import os
import threading
import time

from cake_tpu.obs.timeline import timeline
from cake_tpu.utils import metrics

log = logging.getLogger("cake_tpu.obs.memwatch")

_lock = threading.Lock()
_last_sample = 0.0


def host_rss_bytes() -> int | None:
    """Current resident set (not the peak): /proc on Linux, peak-RSS
    fallback elsewhere."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource

        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except (ImportError, AttributeError, OSError):
        return None


def device_memory() -> list[dict]:
    """Per-device {device, bytes_in_use, peak_bytes_in_use, bytes_limit}
    where the backend exposes memory_stats (TPU/GPU; CPU yields nothing)."""
    out: list[dict] = []
    try:
        import jax

        devices = jax.local_devices()
    except (ImportError, RuntimeError):
        return out
    for d in devices:
        stats = getattr(d, "memory_stats", None)
        if not callable(stats):
            continue
        try:
            s = stats() or {}
        except Exception as e:  # backend-specific failure modes
            log.debug("memory_stats failed for %s: %s", d, e)
            continue
        entry = {"device": str(d)}
        for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if k in s:
                entry[k] = int(s[k])
        if len(entry) > 1:
            out.append(entry)
    return out


def sample(tag: str, *, min_interval_s: float = 0.0) -> bool:
    """One watermark sample (gauges + timeline counters); returns False when
    throttled. ``tag`` names the triggering phase boundary on the raw
    ring/JSONL counter events (chart args stay numeric)."""
    global _last_sample
    now = time.monotonic()
    with _lock:
        if min_interval_s > 0 and now - _last_sample < min_interval_s:
            return False
        _last_sample = now
    rss = host_rss_bytes()
    if rss is not None:
        metrics.registry.gauge(
            "cake_host_rss_bytes", "Current host resident set size."
        ).set(rss)
        timeline.counter(
            "host_rss", {"bytes": float(rss)}, track="mem", tag=tag
        )
    in_use = metrics.registry.gauge(
        "cake_hbm_bytes_in_use", "Device allocator bytes in use."
    )
    peak = metrics.registry.gauge(
        "cake_hbm_peak_bytes_in_use", "Device allocator peak bytes in use."
    )
    for entry in device_memory():
        dev = entry["device"]
        vals: dict[str, float] = {}
        if "bytes_in_use" in entry:
            in_use.set(entry["bytes_in_use"], device=dev)
            vals["bytes_in_use"] = float(entry["bytes_in_use"])
        if "peak_bytes_in_use" in entry:
            peak.set(entry["peak_bytes_in_use"], device=dev)
            vals["peak_bytes_in_use"] = float(entry["peak_bytes_in_use"])
        if vals:
            timeline.counter(f"hbm[{dev}]", vals, track="mem", tag=tag)
    return True
