"""Preallocated KV cache.

Replaces the reference's concat-per-token cache
(cake-core/src/models/llama3/cache.rs:93-122), which grows by ``Tensor::cat`` each
step (O(n^2) copies) and has a buggy sliding-window trim (cache.rs:105-116, see
SURVEY.md §2.6). Here the cache is a fixed-shape array pair written in place with
``dynamic_update_slice`` — jit-compatible, donatable, and O(1) per token.

Layout: [n_layers, batch, n_kv_heads, max_seq, head_dim] — **head-major**: each
KV head's sequence is contiguous, so the decode-attention kernel's per-head block
DMA (ops/pallas/decode_attention.py) streams one contiguous stride per block
instead of gathering across an interleaved head axis. The leading layer axis lets
``lax.scan`` over stacked layer params carry the matching cache slice, and a
pipeline stage simply holds the [own_layers, ...] shard of the same structure.

Causality makes explicit length tracking unnecessary for reads: slots at index
> current position are masked by the position-comparison causal mask, so only the
write position ``pos`` must be carried (as a scalar, not a shape).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class KVCache(NamedTuple):
    """Fixed-shape KV storage for a contiguous run of layers."""

    k: jnp.ndarray  # [n_layers, batch, n_kv_heads, max_seq, head_dim]
    v: jnp.ndarray

    @property
    def n_layers(self) -> int:
        return self.k.shape[0]

    @property
    def batch_size(self) -> int:
        return self.k.shape[1]

    @property
    def max_seq_len(self) -> int:
        return self.k.shape[3]


SEQ_MULTIPLE = 128  # one TPU lane tile: keeps decode-kernel blocks full-width


def init_cache(
    n_layers: int,
    batch: int,
    max_seq_len: int,
    n_kv_heads: int,
    head_dim: int,
    dtype: jnp.dtype = jnp.bfloat16,
) -> KVCache:
    """Allocate a zeroed cache; the seq dim is rounded up to SEQ_MULTIPLE.

    The padding slots are invisible (causal masking / length pruning never reads
    past the live prefix) and keep ops/pallas/decode_attention.py at its full
    128-row block size for any user-requested ``max_seq_len``.
    """
    padded = -(-max_seq_len // SEQ_MULTIPLE) * SEQ_MULTIPLE
    shape = (n_layers, batch, n_kv_heads, padded, head_dim)
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


def write_layer(
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    pos: jnp.ndarray,
    row: jnp.ndarray | int = 0,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Write a [batch, chunk, n_kv, head_dim] chunk at sequence offset ``pos``.

    Operates on one layer's [batch, n_kv, max_seq, head_dim] slice (the layer axis
    is scanned over in the model). ``pos`` is a traced scalar. ``row`` offsets
    the write down the batch axis when ``k_new`` carries a WINDOW of the
    cache's rows (the 1F1B interleaved pipeline's per-group decode,
    models/llama/batch.py row_offset mode).
    """
    start = (row, 0, pos, 0)
    k_new = jnp.moveaxis(k_new, 1, 2).astype(k_cache.dtype)
    v_new = jnp.moveaxis(v_new, 1, 2).astype(v_cache.dtype)
    k_cache = jax.lax.dynamic_update_slice(k_cache, k_new, start)
    v_cache = jax.lax.dynamic_update_slice(v_cache, v_new, start)
    return k_cache, v_cache


# ------------------------------------------------------------- rolling cache
#
# Sliding-window models (Mistral family) never attend past `window` keys, so
# the cache need only hold the last `window + chunk_budget` positions:
# position p lives in slot p % cache_len, and the slot's absolute position is
# reconstructed at read time (slot contents are unambiguous because cache_len
# exceeds the window plus the largest chunk written in one dispatch — a chunk
# write can only evict keys already outside every live query's window). This
# bounds KV memory by the window, not the sequence length: a 32K-context
# Mistral-7B with window 4096 stores 4608 slots instead of 32768.


def write_layer_rolling(
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    pos: jnp.ndarray,
    valid_len: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Write a chunk at slots ``(pos + j) % cache_len`` for j < valid_len.

    Padded tail tokens (j >= valid_len, from prefill buckets) are DROPPED —
    in a rolling cache a clamped garbage write would destroy live keys
    instead of landing in dead future slots like the dense layout.
    """
    cache_len = k_cache.shape[2]
    chunk = k_new.shape[1]
    j = jnp.arange(chunk)
    slots = jnp.where(j < valid_len, (pos + j) % cache_len, cache_len)
    k_new = jnp.moveaxis(k_new, 1, 2).astype(k_cache.dtype)
    v_new = jnp.moveaxis(v_new, 1, 2).astype(v_cache.dtype)
    k_cache = k_cache.at[:, :, slots, :].set(k_new, mode="drop")
    v_cache = v_cache.at[:, :, slots, :].set(v_new, mode="drop")
    return k_cache, v_cache


ROLLING_DEAD = jnp.int32(2**30)  # sentinel: slot never written (masked out)


def rolling_kv_positions(
    cache_len: int, pos: jnp.ndarray, valid_len: jnp.ndarray
) -> jnp.ndarray:
    """Absolute position of each rolling-cache slot, [cache_len] int32.

    Slot s holds the unique position q ≡ s (mod cache_len) in
    (p_max - cache_len, p_max], where p_max = pos + valid_len - 1 is the
    newest position just written. Slots never written (q < 0) get a large
    sentinel so the causal mask excludes them.
    """
    p_max = pos + valid_len - 1
    s = jnp.arange(cache_len, dtype=jnp.int32)
    q = p_max - ((p_max - s) % cache_len)
    return jnp.where(q >= 0, q, ROLLING_DEAD)
