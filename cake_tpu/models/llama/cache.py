"""Preallocated KV cache.

Replaces the reference's concat-per-token cache
(cake-core/src/models/llama3/cache.rs:93-122), which grows by ``Tensor::cat`` each
step (O(n^2) copies) and has a buggy sliding-window trim (cache.rs:105-116, see
SURVEY.md §2.6). Here the cache is a fixed-shape array pair written in place with
``dynamic_update_slice`` — jit-compatible, donatable, and O(1) per token.

Layout: [n_layers, batch, max_seq, n_kv_heads, head_dim]. The leading layer axis
lets ``lax.scan`` over stacked layer params carry the matching cache slice, and a
pipeline stage simply holds the [own_layers, ...] shard of the same structure.

Causality makes explicit length tracking unnecessary for reads: slots at index
> current position are masked by the position-comparison causal mask, so only the
write position ``pos`` must be carried (as a scalar, not a shape).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class KVCache(NamedTuple):
    """Fixed-shape KV storage for a contiguous run of layers."""

    k: jnp.ndarray  # [n_layers, batch, max_seq, n_kv_heads, head_dim]
    v: jnp.ndarray

    @property
    def n_layers(self) -> int:
        return self.k.shape[0]

    @property
    def batch_size(self) -> int:
        return self.k.shape[1]

    @property
    def max_seq_len(self) -> int:
        return self.k.shape[2]


def init_cache(
    n_layers: int,
    batch: int,
    max_seq_len: int,
    n_kv_heads: int,
    head_dim: int,
    dtype: jnp.dtype = jnp.bfloat16,
) -> KVCache:
    shape = (n_layers, batch, max_seq_len, n_kv_heads, head_dim)
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


def write_layer(
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    pos: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Write a [batch, chunk, n_kv, head_dim] chunk at sequence offset ``pos``.

    Operates on one layer's [batch, max_seq, n_kv, head_dim] slice (the layer axis is
    scanned over in the model). ``pos`` is a traced scalar.
    """
    start = (0, pos, 0, 0)
    k_cache = jax.lax.dynamic_update_slice(k_cache, k_new.astype(k_cache.dtype), start)
    v_cache = jax.lax.dynamic_update_slice(v_cache, v_new.astype(v_cache.dtype), start)
    return k_cache, v_cache
