"""Tokenizer loading and a dependency-free fallback.

The reference loads a HF `tokenizer.json` via the tokenizers crate
(cake-core/src/models/llama3/llama.rs:19-32). Here:

  * ``HFTokenizer`` wraps the Python ``tokenizers`` package when the model dir has
    a ``tokenizer.json`` (the Llama-3 file carries its special tokens as added
    tokens, so chat-template markers encode to single ids).
  * ``ByteTokenizer`` is a self-contained byte-level fallback used by tests and
    tiny random models: ids 0-255 are raw bytes, 256+ are the Llama-3 special
    tokens. This is the testing seam the reference lacks (SURVEY.md §4): real
    tokenization behavior without a 2 MB fixture.
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Protocol

from cake_tpu.models.llama.chat import (
    BEGIN_OF_TEXT,
    END_HEADER,
    EOT,
    START_HEADER,
)

END_OF_TEXT = "<|end_of_text|>"

_BYTE_SPECIALS = {
    BEGIN_OF_TEXT: 256,
    START_HEADER: 257,
    END_HEADER: 258,
    EOT: 259,
    END_OF_TEXT: 260,
}
_BYTE_SPECIALS_INV = {v: k for k, v in _BYTE_SPECIALS.items()}
_SPECIAL_RE = re.compile(
    "(" + "|".join(re.escape(s) for s in _BYTE_SPECIALS) + ")"
)


class Tokenizer(Protocol):
    def encode(self, text: str) -> list[int]: ...

    def decode(self, ids: list[int]) -> str: ...

    @property
    def vocab_size(self) -> int: ...


class ByteTokenizer:
    """Byte-level tokenizer with Llama-3 special markers. Vocab: 512."""

    vocab_size = 512

    def encode(self, text: str) -> list[int]:
        ids: list[int] = []
        for part in _SPECIAL_RE.split(text):
            if not part:
                continue
            if part in _BYTE_SPECIALS:
                ids.append(_BYTE_SPECIALS[part])
            else:
                ids.extend(part.encode("utf-8"))
        return ids

    def decode(self, ids: list[int]) -> str:
        out: list[str] = []
        buf = bytearray()
        for i in ids:
            if i < 256:
                buf.append(i)
            else:
                if buf:
                    out.append(buf.decode("utf-8", errors="replace"))
                    buf.clear()
                out.append(_BYTE_SPECIALS_INV.get(i, ""))
        if buf:
            out.append(buf.decode("utf-8", errors="replace"))
        return "".join(out)


class HFTokenizer:
    """Wrapper over a HuggingFace ``tokenizer.json``."""

    def __init__(self, path: str | Path):
        from tokenizers import Tokenizer as _Tok

        self._tok = _Tok.from_file(str(path))

    @property
    def vocab_size(self) -> int:
        return self._tok.get_vocab_size()

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text, add_special_tokens=False).ids

    def decode(self, ids: list[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)


def load_tokenizer(model_dir: str | Path) -> Tokenizer:
    """``tokenizer.json`` if present (llama.rs:19-32), else the byte fallback."""
    path = Path(model_dir) / "tokenizer.json"
    if path.exists():
        return HFTokenizer(path)
    return ByteTokenizer()
