"""Paged KV cache: a shared page pool + host-side block-table allocator.

The dense cache (cache.py) reserves a full ``[max_seq]`` strip per batch slot,
so HBM is committed for the LONGEST POSSIBLE sequence per lane and the serving
engine's admission is capped by ``batch * max_seq`` — the memory-capacity wall
the ragged-paged-attention line of work (PAPERS.md) removes. Here KV storage is
a pool of fixed-size pages shared by every lane:

  pool:        [n_layers, n_pages, n_kv_heads, page_size, head_dim]
  block table: int32 [batch, max_pages_per_seq], physical page per logical
               page, UNMAPPED (-1) where the lane holds no storage

The layout is **head-major inside a page** (n_kv before page_size), exactly the
dense cache's stride order, so one page is one contiguous
``page_size * head_dim`` strip per KV head and the paged decode kernel
(ops/pallas/paged_attention.py) streams it as a single block DMA.

HBM committed = pages actually holding live tokens (rounded up to the page),
not ``batch * max_seq`` — a pool sized well below the dense footprint admits
strictly more concurrent short requests (pinned in tests/test_paged_serving.py).

The ``PageAllocator`` is HOST-side bookkeeping (free list, refcounts, block
tables as numpy); only the block tables cross into jit as small int32 operands.
Refcounts let a shared prompt prefix map the same physical pages from several
lanes (``fork``), copy-on-write (``make_private`` + ``copy_pages``) splitting a
page only when a lane is about to write it.

Writes through an UNMAPPED table entry are DROPPED (out-of-bounds scatter with
``mode="drop"``): left-pad garbage, dummy lanes, and finished lanes cost no
storage and can never corrupt a recycled page.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from cake_tpu.utils import metrics

UNMAPPED = np.int32(-1)  # block-table sentinel: no physical page mapped

# Metric names (PR 1 observability convention; README "Observability").
_G_TOTAL = "cake_kv_pages_total"
_G_FREE = "cake_kv_pages_free"
_G_SHARED = "cake_kv_pages_shared"
_C_FAIL = "cake_kv_page_alloc_failures_total"


class PagedKVCache(NamedTuple):
    """Page-pool KV storage for a contiguous run of layers."""

    k: jnp.ndarray  # [n_layers, n_pages, n_kv_heads, page_size, head_dim]
    v: jnp.ndarray

    @property
    def n_layers(self) -> int:
        return self.k.shape[0]

    @property
    def n_pages(self) -> int:
        return self.k.shape[1]

    @property
    def page_size(self) -> int:
        return self.k.shape[3]


def init_paged_cache(
    n_layers: int,
    n_pages: int,
    n_kv_heads: int,
    page_size: int,
    head_dim: int,
    dtype: jnp.dtype = jnp.bfloat16,
) -> PagedKVCache:
    """Allocate a zeroed page pool.

    ``page_size`` is free on the CPU/XLA fallback path; the Pallas kernel
    (ops/pallas/paged_attention.py) requires a multiple of its 128-lane tile —
    that constraint is enforced at kernel dispatch, not here, so CPU tests can
    exercise many-page layouts cheaply.
    """
    shape = (n_layers, n_pages, n_kv_heads, page_size, head_dim)
    return PagedKVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


def paged_write_layer(
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    pos: jnp.ndarray,
    block_tables: jnp.ndarray,
    starts: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Write a [batch, chunk, n_kv, head_dim] chunk at sequence offset ``pos``.

    The paged sibling of cache.write_layer: operates on ONE layer's
    [n_pages, n_kv, page_size, head_dim] pool slice (the layer axis is scanned
    over in the model), scattering token ``pos + j`` of row ``b`` into physical
    page ``block_tables[b, (pos + j) // page_size]`` at offset
    ``(pos + j) % page_size``. UNMAPPED entries (and logical pages beyond the
    table) become out-of-bounds scatter indices and are dropped — the caller's
    allocator decides what holds storage, the write path cannot corrupt it.

    ``starts`` (optional [B] int32) drops row ``b``'s writes at slots below
    ``starts[b]`` even when those slots ARE mapped: a suffix prefill over a
    forked shared-prefix chain (runtime/prefix_cache.py) re-embeds prefix
    tokens inside its window but must never scribble the shared pages that
    already hold their KV.
    """
    n_pages, _, page_size, _ = k_pages.shape
    b, chunk = k_new.shape[0], k_new.shape[1]
    slots = pos + jnp.arange(chunk, dtype=jnp.int32)  # [chunk] absolute
    logical = jnp.broadcast_to(slots // page_size, (b, chunk))
    offs = jnp.broadcast_to(slots % page_size, (b, chunk))
    phys = jnp.take_along_axis(
        block_tables, logical, axis=1, mode="fill", fill_value=UNMAPPED
    )
    # UNMAPPED (-1) -> n_pages: out of bounds, dropped by the scatter.
    phys = jnp.where(phys < 0, n_pages, phys)
    if starts is not None:
        phys = jnp.where(slots[None, :] < starts[:, None], n_pages, phys)
    k_new = k_new.astype(k_pages.dtype)
    v_new = v_new.astype(v_pages.dtype)
    k_pages = k_pages.at[phys, :, offs, :].set(k_new, mode="drop")
    v_pages = v_pages.at[phys, :, offs, :].set(v_new, mode="drop")
    return k_pages, v_pages


def gather_pages(
    pages: jnp.ndarray, block_tables: jnp.ndarray
) -> jnp.ndarray:
    """Dense head-major view of each row's pages: [b, n_kv, n_p * ps, hd].

    The XLA fallback read path (interpret/CPU, and the numerical oracle the
    kernel is pinned against): gathering a row's pages in logical order
    reconstructs exactly the dense cache layout at every mapped slot; UNMAPPED
    pages read zeros, which the callers' position masks exclude anyway.
    """
    n_pages = pages.shape[0]
    bt = jnp.where(block_tables < 0, n_pages, block_tables)
    # [b, n_p, n_kv, ps, hd], OOB -> 0 fill
    g = jnp.take(pages, bt, axis=0, mode="fill", fill_value=0)
    b, n_p, n_kv, ps, hd = g.shape
    return jnp.moveaxis(g, 2, 1).reshape(b, n_kv, n_p * ps, hd)


def copy_pages(
    cache: PagedKVCache, src: jnp.ndarray, dst: jnp.ndarray
) -> PagedKVCache:
    """Copy physical pages ``src[i] -> dst[i]`` across every layer.

    The device half of copy-on-write: ``PageAllocator.make_private`` picks the
    (src, dst) pairs host-side; this moves the bytes so the forked lane's
    private page starts as an exact copy of the shared one.
    """
    src = jnp.asarray(src, jnp.int32)
    dst = jnp.asarray(dst, jnp.int32)
    return PagedKVCache(
        k=cache.k.at[:, dst].set(cache.k[:, src]),
        v=cache.v.at[:, dst].set(cache.v[:, src]),
    )


class PageExhausted(RuntimeError):
    """The pool has no free page for a required mapping."""


class PageAllocator:
    """Host-side page bookkeeping: free list, refcounts, per-lane block tables.

    All state is numpy/python — nothing here runs under jit. The serving
    engine consults it for admission (``can_admit``), maps pages as sequences
    grow (``map_range``), and returns them when streams finish (``release``).
    ``fork``/``make_private`` implement refcounted prefix sharing with
    copy-on-write (the device-side byte copy is ``copy_pages``).

    Pool gauges (``cake_kv_pages_total/free/shared``) and the allocation-
    failure counter update on every mutating call, so ``/metrics`` and
    ``cake-tpu stats`` always show the live pool.
    """

    def __init__(
        self,
        n_pages: int,
        page_size: int,
        batch: int,
        max_pages_per_seq: int,
        reserve_pages: int = 1,
    ):
        if n_pages <= 0 or page_size <= 0:
            raise ValueError("n_pages and page_size must be positive")
        self.n_pages = n_pages
        self.page_size = page_size
        self.max_pages_per_seq = max_pages_per_seq
        self.reserve_pages = max(0, reserve_pages)
        self.refcount = np.zeros(n_pages, np.int32)
        # LIFO free list: recently-freed pages are re-used first (their bytes
        # are likelier to still be resident in any cache hierarchy).
        self._free: list[int] = list(range(n_pages - 1, -1, -1))
        self.block_tables = np.full(
            (batch, max_pages_per_seq), UNMAPPED, np.int32
        )
        self._update_gauges()

    # ------------------------------------------------------------- accounting

    @property
    def pages_total(self) -> int:
        return self.n_pages

    @property
    def pages_free(self) -> int:
        return len(self._free)

    @property
    def pages_shared(self) -> int:
        return int((self.refcount > 1).sum())

    def pages_needed(self, n_tokens: int) -> int:
        return -(-max(0, n_tokens) // self.page_size)

    def can_admit(self, prompt_tokens: int) -> bool:
        """Admission rule: ceil(prompt / page_size) + reserve pages are free.

        ``reserve`` covers the page-boundary straddle of a left-padded layout
        (a prompt of N tokens can span pages_needed(N) + 1 physical pages) and
        gives the first decode tokens headroom.
        """
        return (
            self.pages_needed(prompt_tokens) + self.reserve_pages
            <= self.pages_free
        )

    def reset(self, batch: int) -> None:
        """Fresh epoch: every page free, every lane unmapped."""
        self.refcount[:] = 0
        self._free = list(range(self.n_pages - 1, -1, -1))
        self.block_tables = np.full(
            (batch, self.max_pages_per_seq), UNMAPPED, np.int32
        )
        self._update_gauges()

    def release_lanes(self, batch: int) -> None:
        """Unmap every lane, KEEPING non-lane references (the persistent
        prefix cache's chain refs, runtime/prefix_cache.py) alive.

        The persistent-pool epoch boundary: lane mappings drop (their pages
        free unless a cached chain still holds them) while the cache's pages
        — and the free-list identity of everything else — survive into the
        next epoch. ``reset`` by contrast zeroes ALL refcounts, which would
        silently orphan the cache's bookkeeping.
        """
        for lane in range(self.block_tables.shape[0]):
            self.release(lane)
        if batch != self.block_tables.shape[0]:
            self.block_tables = np.full(
                (batch, self.max_pages_per_seq), UNMAPPED, np.int32
            )
        self._update_gauges()

    # ------------------------------------------------------------- allocation

    def lane_mapped(self, lane: int) -> bool:
        return bool((self.block_tables[lane] >= 0).any())

    def lane_pages(self, lane: int) -> int:
        """Mapped logical pages of ``lane`` — the relief spilling it would
        yield (the continuous scheduler's preemption victim heuristic;
        shared pages count too: the lane's reference still blocks their
        reuse)."""
        return int((self.block_tables[lane] >= 0).sum())

    def map_range(self, lane: int, start_slot: int, end_slot: int) -> None:
        """Map pages so slots [start_slot, end_slot) of ``lane`` have storage.

        Already-mapped logical pages are kept (growth is incremental: decode
        calls this with a sliding [slot, slot + chunk) window and only page-
        boundary crossings allocate). Atomic: on exhaustion nothing is mapped
        and PageExhausted raises (the failure counter increments; the caller
        decides between truncating the stream and failing the epoch).
        """
        if end_slot <= start_slot:
            return
        first = start_slot // self.page_size
        last = -(-end_slot // self.page_size)  # exclusive
        if last > self.max_pages_per_seq:
            raise ValueError(
                f"slots [{start_slot}, {end_slot}) need logical page "
                f"{last - 1} but the table has {self.max_pages_per_seq}"
            )
        row = self.block_tables[lane]
        need = [p for p in range(first, last) if row[p] < 0]
        if len(need) > len(self._free):
            metrics.registry.counter(
                _C_FAIL, "Page allocations refused for an empty free list."
            ).inc()
            self._update_gauges()
            raise PageExhausted(
                f"lane {lane} needs {len(need)} page(s), "
                f"{len(self._free)} free of {self.n_pages}"
            )
        for p in need:
            phys = self._free.pop()
            self.refcount[phys] = 1
            row[p] = phys
        self._update_gauges()

    def release(self, lane: int) -> None:
        """Drop every mapping of ``lane``; pages reaching refcount 0 go free."""
        row = self.block_tables[lane]
        for p in np.flatnonzero(row >= 0):
            phys = int(row[p])
            self.refcount[phys] -= 1
            if self.refcount[phys] == 0:
                self._free.append(phys)
        row[:] = UNMAPPED
        self._update_gauges()

    # ----------------------------------------------- prefix sharing (CoW)

    def retain_pages(self, pages: list[int]) -> None:
        """Take one non-lane reference on each physical page of a chain.

        The prefix cache's ownership primitive (runtime/prefix_cache.py
        insert): a page referenced by the cache survives every lane release
        until the chain is evicted (``release_pages``). Pages must currently
        be live (refcount > 0) — a chain is always adopted from a mapped
        lane, never conjured from the free list.
        """
        for phys in pages:
            if self.refcount[phys] <= 0:
                raise ValueError(f"page {phys} is free; cannot retain it")
            self.refcount[phys] += 1
        self._update_gauges()

    def release_pages(self, pages: list[int]) -> None:
        """Drop one reference per page (cache eviction / clear); pages
        reaching refcount 0 return to the free list."""
        for phys in pages:
            if self.refcount[phys] <= 0:
                raise ValueError(f"page {phys} is already free")
            self.refcount[phys] -= 1
            if self.refcount[phys] == 0:
                self._free.append(phys)
        self._update_gauges()

    def fork_chain(
        self, lane: int, pages: list[int], first_logical: int
    ) -> None:
        """Map a cached page chain into ``lane`` at logical pages
        [first_logical, first_logical + len(pages)), sharing storage (+1 ref
        per page). The chain-level sibling of ``fork``: the source is a
        prefix-cache chain, not another lane. Target entries must be
        unmapped — splicing over live mappings would leak their pages.
        """
        if first_logical < 0 or (
            first_logical + len(pages) > self.max_pages_per_seq
        ):
            raise ValueError(
                f"chain of {len(pages)} page(s) at logical {first_logical} "
                f"overflows the {self.max_pages_per_seq}-page table"
            )
        row = self.block_tables[lane]
        for i, phys in enumerate(pages):
            if row[first_logical + i] >= 0:
                raise ValueError(
                    f"fork_chain target lane {lane} logical page "
                    f"{first_logical + i} is already mapped"
                )
            self.refcount[phys] += 1
            row[first_logical + i] = phys
        self._update_gauges()

    def unmap_page(self, lane: int, logical_page: int) -> None:
        """Drop one logical-page mapping of ``lane`` (refcount -1, free at
        0) — the degraded path when a copy-on-write split cannot get its
        fresh page: the lane gives the shared page back and recomputes those
        tokens instead."""
        phys = int(self.block_tables[lane, logical_page])
        if phys < 0:
            raise ValueError(f"lane {lane} has no page {logical_page} mapped")
        self.refcount[phys] -= 1
        if self.refcount[phys] == 0:
            self._free.append(phys)
        self.block_tables[lane, logical_page] = UNMAPPED
        self._update_gauges()

    def fork(self, src_lane: int, dst_lane: int) -> None:
        """Map ``dst_lane`` onto ``src_lane``'s physical pages (shared, +1 ref).

        The shared-prompt-prefix seam: a request whose prompt extends another
        request's prompt can fork its lane and pay storage only for the pages
        it later diverges on (``make_private``). ``dst_lane`` must be unmapped.
        """
        if self.lane_mapped(dst_lane):
            raise ValueError(f"fork target lane {dst_lane} is already mapped")
        src = self.block_tables[src_lane]
        for p in np.flatnonzero(src >= 0):
            self.refcount[int(src[p])] += 1
        self.block_tables[dst_lane] = src
        self._update_gauges()

    def make_private(
        self, lane: int, logical_page: int
    ) -> tuple[int, int] | None:
        """Copy-on-write split before ``lane`` writes ``logical_page``.

        Returns (src_phys, dst_phys) when the page was shared — the caller
        must then ``copy_pages(cache, [src], [dst])`` before writing — or
        None when the lane already owns the page exclusively.
        """
        phys = int(self.block_tables[lane, logical_page])
        if phys < 0:
            raise ValueError(f"lane {lane} has no page {logical_page} mapped")
        if self.refcount[phys] <= 1:
            return None
        if not self._free:
            metrics.registry.counter(
                _C_FAIL, "Page allocations refused for an empty free list."
            ).inc()
            self._update_gauges()
            raise PageExhausted("copy-on-write split needs a free page")
        fresh = self._free.pop()
        self.refcount[phys] -= 1
        self.refcount[fresh] = 1
        self.block_tables[lane, logical_page] = fresh
        self._update_gauges()
        return phys, fresh

    # ------------------------------------------------------------- telemetry

    def _update_gauges(self) -> None:
        reg = metrics.registry
        reg.gauge(_G_TOTAL, "Physical KV pages in the pool.").set(
            self.pages_total
        )
        reg.gauge(_G_FREE, "KV pages currently on the free list.").set(
            self.pages_free
        )
        reg.gauge(
            _G_SHARED, "KV pages mapped by more than one lane (CoW-shared)."
        ).set(self.pages_shared)
