"""Fused multi-token decode: N steps in ONE jitted ``lax.scan``.

The reference's decode loop pays a host round trip per token (llama.rs:271-335:
sample on host, re-enter forward). The per-step analogue here
(generator.LlamaGenerator.next_token) pays a device->host sync per token to pull
the sampled id out. This module removes that: the whole chain

    forward -> repeat penalty -> temperature/top-k/top-p sample -> feed token back

runs on-device for ``n_steps`` tokens per dispatch, carrying (token, KV cache,
position, PRNG key, penalty ring) through a ``lax.scan``. Sampling knobs are
static (compiled in), matching ops/sampling.py; the PRNG key is split once per
step exactly like the host loop, so for a given seed the fused and per-step
paths walk the SAME random stream and emit identical tokens.

EOS cannot early-exit a scan without degrading it to a ``while_loop`` (which
serializes compilation benefits and breaks donation); instead the caller decodes
in chunks, scans the returned ids for EOS on host, and discards the tail. Wasted
work is bounded by chunk_size - 1 steps; stale KV writes past EOS sit at
positions beyond the live length and are masked by the position-comparison
causal mask, then overwritten if the sequence continues.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.cache import KVCache
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.ops.sampling import apply_repeat_penalty, sample


def decode_scan(
    params: M.Params,
    kv: KVCache,
    last_token: jnp.ndarray,  # [batch] int32 — most recently sampled/known token
    pos: jnp.ndarray,  # scalar int32 — position of last_token in the sequence
    key: jax.Array,
    ring: jnp.ndarray,  # [batch, window] int32 recent tokens, -1 = empty slot
    ring_idx: jnp.ndarray,  # scalar int32 — next circular write slot
    config: LlamaConfig,
    *,
    n_steps: int,
    temperature: float,
    top_k: int | None,
    top_p: float | None,
    repeat_penalty: float,
) -> tuple[jnp.ndarray, KVCache, jax.Array, jnp.ndarray, jnp.ndarray]:
    """Decode ``n_steps`` tokens on-device.

    Returns (tokens [batch, n_steps], kv, key, ring, ring_idx) where ``tokens``
    are the newly sampled ids in order and the carries are ready for the next
    chunk (assuming no EOS; on EOS the caller re-seeds the ring from host state).
    """
    window = ring.shape[1]

    def body(carry, _):
        tok, kv, pos, key, ring, ring_idx = carry
        # tok sits at sequence position pos; its KV is written there and the
        # logits predict position pos + 1 (generator.next_token's decode branch
        # makes the same call shape: step([last], len(tokens) - 1, 1)).
        logits, kv = M.forward(params, tok[:, None], kv, pos, jnp.int32(1), config)
        logits = apply_repeat_penalty(logits, repeat_penalty, ring)
        key, sub = jax.random.split(key)
        nxt = sample(logits, sub, temperature, top_k, top_p).astype(jnp.int32)
        if window > 0:
            ring = ring.at[:, ring_idx].set(nxt, mode="drop")
            ring_idx = (ring_idx + 1) % window
        return (nxt, kv, pos + 1, key, ring, ring_idx), nxt

    (_, kv, _, key, ring, ring_idx), toks = jax.lax.scan(
        body,
        (last_token, kv, pos, key, ring, ring_idx),
        None,
        length=n_steps,
    )
    return jnp.moveaxis(toks, 0, 1), kv, key, ring, ring_idx


@functools.lru_cache(maxsize=32)
def build_decode_fn(
    config: LlamaConfig,
    n_steps: int,
    temperature: float,
    top_k: int | None,
    top_p: float | None,
    repeat_penalty: float,
):
    """One compiled fused-decode entry per (config, n_steps, sampling knobs)."""
    fn = functools.partial(
        decode_scan,
        config=config,
        n_steps=n_steps,
        temperature=temperature,
        top_k=top_k,
        top_p=top_p,
        repeat_penalty=repeat_penalty,
    )
    return jax.jit(fn, donate_argnums=(1,))
