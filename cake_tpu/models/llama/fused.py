"""Fused multi-token decode: N steps in ONE jitted ``lax.scan``.

The reference's decode loop pays a host round trip per token (llama.rs:271-335:
sample on host, re-enter forward). The per-step analogue here
(generator.LlamaGenerator.next_token) pays a device->host sync per token to pull
the sampled id out. This module removes that: the whole chain

    forward -> repeat penalty -> temperature/top-k/top-p sample -> feed token back

runs on-device for ``n_steps`` tokens per dispatch, carrying (token, KV cache,
position, PRNG key, penalty ring) through a ``lax.scan``. Sampling knobs are
static (compiled in), matching ops/sampling.py; the PRNG key is split once per
step exactly like the host loop, so for a given seed the fused and per-step
paths walk the SAME random stream and emit identical tokens.

EOS cannot early-exit a scan without degrading it to a ``while_loop`` (which
serializes compilation benefits and breaks donation); instead the caller decodes
in chunks, scans the returned ids for EOS on host, and discards the tail. Wasted
work is bounded by chunk_size - 1 steps; stale KV writes past EOS sit at
positions beyond the live length and are masked by the position-comparison
causal mask, then overwritten if the sequence continues.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.cache import KVCache
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.ops.sampling import apply_repeat_penalty, sample, sample_per_row


def sample_step(
    logits: jnp.ndarray,  # [b, vocab] f32
    key: jax.Array,  # [2] shared stream, or [b, 2] per-row streams
    ring: jnp.ndarray,  # [b, window] int32, -1 = empty
    ring_idx,  # scalar or [b] int32 next circular slot
    *,
    temperature: float,
    top_k: int | None,
    top_p: float | None,
    repeat_penalty: float,
    tail_impl: str | None = None,
):
    """ONE decode sampling step: penalty -> key split -> sample -> ring update.

    THE single definition of the arithmetic (the module's bit-exactness
    invariant): the fused scan below, the serving backends' serialized walks,
    and the 1F1B interleaved pipeline walk (runtime/batch_backend.py) all
    sample through here, so their token streams cannot drift.

    ``tail_impl`` (STATIC; None = unfused) routes the penalty/scale/top-k/
    draw chain through the fused sampling tail
    (ops/pallas/fused_sample_tail.py, "pallas" kernel or its "xla" twin).
    The key split happens HERE either way and the draw is the literal
    gumbel-argmax identity of jax.random.categorical, so the fused and
    unfused paths walk the SAME random stream and emit identical tokens
    (pinned in tests/test_fused_decode.py). top_p set falls back to the
    twin (the documented sort fallback).

    Returns (next_token [b] int32, advanced key(s), ring, ring_idx).
    """
    window = ring.shape[1]
    if tail_impl is not None:
        from cake_tpu.ops.pallas.fused_sample_tail import (
            fused_sample_tail,
            gumbel_noise,
            sample_tail_supported,
        )

        if tail_impl == "pallas" and not sample_tail_supported(
            logits.shape[-1], top_p
        ):
            # The serving-path downgrade for what the kernel cannot express
            # (top_p's sort; an untileable vocab) — the SAME rule the
            # backends' kernel-fallback note reads, so the flight event and
            # the dispatch agree. The low-level entry still refuses an
            # untiled vocab loudly for direct callers.
            tail_impl = "xla"
        if key.ndim == 2:
            pair = jax.vmap(jax.random.split)(key)  # [b, 2, 2]
            key, sub = pair[:, 0], pair[:, 1]
        else:
            key, sub = jax.random.split(key)
        noise = None
        if not (temperature is None or temperature <= 0.0):
            noise = gumbel_noise(sub, logits)
        nxt = fused_sample_tail(
            logits, ring, noise,
            temperature=temperature, top_k=top_k, top_p=top_p,
            repeat_penalty=repeat_penalty, impl=tail_impl,
        )
    elif key.ndim == 2:
        logits = apply_repeat_penalty(logits, repeat_penalty, ring)
        pair = jax.vmap(jax.random.split)(key)  # [b, 2, 2]
        key, sub = pair[:, 0], pair[:, 1]
        nxt = sample_per_row(logits, sub, temperature, top_k, top_p)
        nxt = nxt.astype(jnp.int32)
    else:
        logits = apply_repeat_penalty(logits, repeat_penalty, ring)
        key, sub = jax.random.split(key)
        nxt = sample(logits, sub, temperature, top_k, top_p).astype(jnp.int32)
    if window > 0:
        # ring_idx may be a scalar (single sequence) or [b] (per-row prompt
        # lengths — exact penalty windows); its rank is preserved.
        b = nxt.shape[0]
        idx = jnp.broadcast_to(ring_idx, (b,))
        ring = ring.at[jnp.arange(b), idx].set(nxt, mode="drop")
        ring_idx = (ring_idx + 1) % window
    return nxt, key, ring, ring_idx


def sampled_decode_scan(
    forward_one,
    kv,
    last_token: jnp.ndarray,  # [batch] int32 — most recently sampled/known token
    pos: jnp.ndarray,  # scalar int32 — position of last_token in the sequence
    key: jax.Array,
    ring: jnp.ndarray,  # [batch, window] int32 recent tokens, -1 = empty slot
    ring_idx: jnp.ndarray,  # scalar int32 — next circular write slot
    *,
    n_steps: int,
    temperature: float,
    top_k: int | None,
    top_p: float | None,
    repeat_penalty: float,
    tail_impl: str | None = None,
):
    """Step-agnostic fused decode: scan sampling around any one-token forward.

    ``forward_one(tok [b, 1], kv, pos) -> (logits [b, vocab] f32, kv)`` may be
    the plain local model, the shard_mapped pipeline step, or a tensor-parallel
    step — whatever closes over the params. Returns (tokens [batch, n_steps],
    kv, key, ring, ring_idx), carries ready for the next chunk (assuming no
    EOS; on EOS the caller re-seeds the ring from host state).

    ``key`` may be one PRNG key ([2], the whole batch shares a stream) or one
    key PER ROW ([batch, 2]): each row then splits/samples from its own stream,
    making row r's tokens bit-identical to a single-sequence run seeded with
    row r's key — the concurrent-serving reproducibility contract
    (runtime/serving.py).
    """
    def body(carry, _):
        tok, kv, pos, key, ring, ring_idx = carry
        # tok sits at sequence position pos; its KV is written there and the
        # logits predict position pos + 1 (generator.next_token's decode branch
        # makes the same call shape: step([last], len(tokens) - 1, 1)).
        logits, kv = forward_one(tok[:, None], kv, pos)
        nxt, key, ring, ring_idx = sample_step(
            logits, key, ring, ring_idx,
            temperature=temperature, top_k=top_k, top_p=top_p,
            repeat_penalty=repeat_penalty, tail_impl=tail_impl,
        )
        return (nxt, kv, pos + 1, key, ring, ring_idx), nxt

    (_, kv, _, key, ring, ring_idx), toks = jax.lax.scan(
        body,
        (last_token, kv, pos, key, ring, ring_idx),
        None,
        length=n_steps,
    )
    return jnp.moveaxis(toks, 0, 1), kv, key, ring, ring_idx


def decode_scan(
    params: M.Params,
    kv: KVCache,
    last_token: jnp.ndarray,
    pos: jnp.ndarray,
    key: jax.Array,
    ring: jnp.ndarray,
    ring_idx: jnp.ndarray,
    config: LlamaConfig,
    *,
    n_steps: int,
    temperature: float,
    top_k: int | None,
    top_p: float | None,
    repeat_penalty: float,
) -> tuple[jnp.ndarray, KVCache, jax.Array, jnp.ndarray, jnp.ndarray]:
    """Fused decode over the plain local model (see sampled_decode_scan)."""
    from cake_tpu.ops.fuse import resolve_fusion

    fusions, fimpl = resolve_fusion(config)

    def forward_one(tok, kv, pos):
        return M.forward(params, tok, kv, pos, jnp.int32(1), config)

    return sampled_decode_scan(
        forward_one,
        kv,
        last_token,
        pos,
        key,
        ring,
        ring_idx,
        n_steps=n_steps,
        temperature=temperature,
        top_k=top_k,
        top_p=top_p,
        repeat_penalty=repeat_penalty,
        tail_impl=fimpl if "tail" in fusions else None,
    )


class FusedDecodeCapability:
    """Mixin granting a ForwardStep the ``decode_chunk`` capability.

    The host class supplies ``_fused_forward_one()`` — returning a callable
    ``(tok [b, 1], kv, pos) -> (logits, kv)`` that closes over its params and
    execution machinery (plain model, shard_mapped pipeline, tensor-parallel
    step) — and keeps its KV state in ``self._kv``. The mixin jits one fused
    scan per (n_steps, sampling knobs); the generator only ever requests its
    construction-time knobs and a single chunk size, so the cache stays tiny.
    """

    def decode_chunk(
        self,
        last_token: np.ndarray,
        pos: int,
        n_steps: int,
        sampling,
        key: jax.Array,
        ring: np.ndarray,
        ring_idx: int,
    ) -> tuple[np.ndarray, jax.Array]:
        """Fused on-device decode of ``n_steps`` tokens.

        Returns (token ids [batch, n_steps], advanced PRNG key). The ring is a
        value argument — the caller reseeds it from its token history each
        call, so EOS truncation never leaves stale ring state behind.
        """
        cache = getattr(self, "_fused_decode_cache", None)
        if cache is None:
            cache = self._fused_decode_cache = {}
        knobs = (
            n_steps,
            sampling.temperature,
            sampling.top_k,
            sampling.top_p,
            sampling.repeat_penalty,
        )
        fn = cache.get(knobs)
        if fn is None:
            impl = functools.partial(
                sampled_decode_scan,
                self._fused_forward_one(),
                n_steps=n_steps,
                temperature=sampling.temperature,
                top_k=sampling.top_k,
                top_p=sampling.top_p,
                repeat_penalty=sampling.repeat_penalty,
            )
            fn = cache[knobs] = jax.jit(impl, donate_argnums=(0,))
        toks, self._kv, key, _, _ = fn(
            self._kv,
            jnp.asarray(last_token, jnp.int32),
            jnp.int32(pos),
            key,
            jnp.asarray(ring, jnp.int32),
            jnp.int32(ring_idx),
        )
        return np.asarray(toks), key


@functools.lru_cache(maxsize=32)
def build_decode_fn(
    config: LlamaConfig,
    n_steps: int,
    temperature: float,
    top_k: int | None,
    top_p: float | None,
    repeat_penalty: float,
):
    """One compiled fused-decode entry per (config, n_steps, sampling knobs)."""
    fn = functools.partial(
        decode_scan,
        config=config,
        n_steps=n_steps,
        temperature=temperature,
        top_k=top_k,
        top_p=top_p,
        repeat_penalty=repeat_penalty,
    )
    return jax.jit(fn, donate_argnums=(1,))
