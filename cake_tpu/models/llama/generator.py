"""Autoregressive generation loop.

Covers the reference's ``Generator`` trait and its ``LLama`` implementation
(cake-core/src/models/mod.rs:36-55, models/llama3/llama.rs:271-335): chat history,
prefill-then-decode with position bookkeeping, seeded sampling with repeat penalty,
incremental detokenization, EOS detection.

The pluggable seam is ``ForwardStep`` — the analogue of the reference's ``Forwarder``
trait (cake/mod.rs:104-146): the generator only needs `(tokens, pos, seq_len) ->
logits`; whether that runs locally, as a shard_map pipeline over a TPU mesh, or
through TCP workers is the step implementation's business. Tests script it.

TPU-first details:
  * Prefill pads the prompt to a power-of-two bucket so each bucket compiles once;
    decode is a single compiled shape (chunk=1) with traced ``pos``.
  * The KV cache is preallocated and donated back to the step, so decode is
    allocation-free.
  * The repeat-penalty window is a fixed-size ring (pad -1), keeping sampling jitted.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from pathlib import Path
from typing import Callable, Protocol

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.cache import init_cache
from cake_tpu.models.llama.chat import Message, encode_dialog
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.tokenizer import Tokenizer, load_tokenizer
from cake_tpu.ops.sampling import DEFAULT_SEED, apply_repeat_penalty, sample
from cake_tpu.utils import metrics

MODEL_NAME = "llama3"


@dataclasses.dataclass
class Token:
    """One generated token (models/mod.rs:11-18)."""

    id: int
    text: str
    is_end_of_stream: bool


@dataclasses.dataclass(frozen=True)
class SamplingConfig:
    """Sampling knobs, defaults matching the reference CLI (lib.rs:40-66)."""

    temperature: float = 1.0
    top_k: int | None = None
    top_p: float | None = None
    repeat_penalty: float = 1.1
    repeat_last_n: int = 128
    seed: int = DEFAULT_SEED

    def trace_knobs(self) -> tuple:
        """The fields compiled into a fused-decode trace (models/llama/fused.py).

        THE one definition of trace compatibility: configs sharing this tuple
        may share a compiled fused scan — and a lockstep serving batch
        (runtime/serving.py groups requests by it). The seed is excluded: PRNG
        keys are runtime arguments.
        """
        return (
            self.temperature,
            self.top_k,
            self.top_p,
            self.repeat_penalty,
            self.repeat_last_n,
        )


def decode_delta(
    tokenizer: Tokenizer, ids: list[int], decoded_len: int
) -> tuple[str, int]:
    """Incremental detokenization: (newly stabilized text, new stable length).

    Holds back a trailing replacement char — it may be a partial UTF-8
    sequence the next token completes. Shared by the generator and the
    batched serving rows so the hold-back rule exists once.
    """
    full = tokenizer.decode(ids)
    stable = len(full)
    if full.endswith("�"):
        stable -= 1
    return full[decoded_len:stable], stable


class StepConnectionError(RuntimeError):
    """A step's backing connection failed mid-call and was re-established.

    Raised by distributed ForwardStep implementations (runtime/master.py)
    AFTER reconnecting: the step's KV state is inconsistent/lost, and the
    generator recovers by resetting the step and replaying its token history
    (the reference has no recovery — errors tear the run down, SURVEY.md §5).
    """

    def __init__(self, node: str):
        super().__init__(f"connection to worker {node!r} was reset")
        self.node = node


class ForwardStep(Protocol):
    """One model step over a token chunk. Implementations own their KV state."""

    def __call__(
        self, tokens: np.ndarray, pos: int, seq_len: int
    ) -> np.ndarray:  # [batch, vocab] f32 logits at the last valid position
        ...

    def reset(self) -> None:
        """Drop cached sequence state (new dialog)."""
        ...

    @property
    def max_seq_len(self) -> int: ...


from cake_tpu.models.llama.fused import FusedDecodeCapability


class LocalForwardStep(FusedDecodeCapability):
    """Single-process step: full params resident, jitted prefill/decode.

    Fused multi-token decode comes from FusedDecodeCapability (decode_chunk)."""

    def __init__(
        self,
        config: LlamaConfig,
        params: M.Params,
        *,
        max_seq_len: int | None = None,
        batch_size: int = 1,
        cache_dtype: jnp.dtype = jnp.bfloat16,
        rolling_budget: int | None = None,
    ):
        from cake_tpu.ops.fuse import fuse_params
        from cake_tpu.ops.quant import apply_runtime_int4_repr

        self.config = config
        # Prep-time QKV / gate|up fusion (ops/fuse.py): fewer HBM-bound ops
        # per scanned layer; column-identical numerics, idempotent. The
        # optional native-s4 int4 conversion (CAKE_INT4_REPR=s4) happens
        # here too — the single-chip runtime prep site.
        self.params = apply_runtime_int4_repr(fuse_params(params))
        self._max_seq = int(max_seq_len or config.max_position_embeddings)
        self._batch = batch_size
        self._cache_dtype = cache_dtype
        # Rolling window cache (cache.py): for sliding-window models, bound
        # KV memory by window + largest chunk instead of max_seq_len.
        # ``rolling_budget`` is the caller's promise about the largest chunk
        # it will ever feed (its --prefill-chunk); enabled only when it
        # actually shrinks the allocation.
        self.rolling = False
        self._cache_len = self._max_seq
        win = config.sliding_window
        if config.alt_sliding_window or config.sliding_pattern is not None:
            # gemma2 alternating / gemma3 5:1 patterns: their full-attention
            # layers need EVERY key — a window-bounded ring would evict
            # history those layers must still attend.
            win = None
        if rolling_budget is not None and win is not None:
            from cake_tpu.models.llama.cache import SEQ_MULTIPLE

            budget = max(int(rolling_budget), 1)
            s_roll = -(-(win + budget) // SEQ_MULTIPLE) * SEQ_MULTIPLE
            s_dense = -(-self._max_seq // SEQ_MULTIPLE) * SEQ_MULTIPLE
            if s_roll < s_dense:
                self.rolling = True
                self._cache_len = s_roll
        from cake_tpu.obs.jitwatch import tracked_jit

        self._fwd = tracked_jit(
            M.forward,
            name="generator.forward",
            static_argnames=("config", "cached_prefill", "rolling", "rope_len"),
            donate_argnames=("kv",),
        )
        self.reset()

    @property
    def max_seq_len(self) -> int:
        return self._max_seq

    def reset(self) -> None:
        self._kv = init_cache(
            self.config.num_hidden_layers,
            self._batch,
            self._cache_len,
            self.config.num_key_value_heads,
            self.config.head_dim,
            self._cache_dtype,
        )

    def __call__(self, tokens: np.ndarray, pos: int, seq_len: int) -> np.ndarray:
        if self.rolling:
            room = self._kv.max_seq_len - self.config.sliding_window
            if tokens.shape[1] > room:
                raise ValueError(
                    f"chunk of {tokens.shape[1]} tokens exceeds the rolling "
                    f"cache budget {room}; lower --prefill-chunk or raise "
                    "rolling_budget"
                )
        logits, self._kv = self._fwd(
            self.params,
            jnp.asarray(tokens, jnp.int32),
            self._kv,
            jnp.int32(pos),
            jnp.int32(seq_len),
            self.config,
            cached_prefill=M.is_cached_prefill(pos, tokens.shape[1]),
            rolling=self.rolling,
            rope_len=self._max_seq if self.rolling else None,
        )
        return np.asarray(logits)

    def _fused_forward_one(self):
        params, config = self.params, self.config
        rolling, rope_len = self.rolling, self._max_seq if self.rolling else None

        def forward_one(tok, kv, pos):
            return M.forward(
                params, tok, kv, pos, jnp.int32(1), config,
                rolling=rolling, rope_len=rope_len,
            )

        return forward_one

    def verify_chunk(self, tokens: np.ndarray, pos: int) -> np.ndarray:
        """Speculative-verify: GREEDY ids at EVERY position of the fed chunk
        (models/llama/speculative.py), argmax'd on device. KV for the whole
        chunk is written at [pos, pos + width); rejected tail slots are dead
        until overwritten."""
        if self.rolling:
            raise RuntimeError(
                "speculative verify is not supported on a rolling cache; "
                "construct the step without rolling_budget"
            )
        from cake_tpu.models.llama.speculative import _verify_fn

        fn = _verify_fn(self.config, tokens.shape[1])
        ids, self._kv = fn(
            self.params, jnp.asarray(tokens, jnp.int32), self._kv, jnp.int32(pos)
        )
        return np.asarray(ids)

    def verify_chunk_sampled(
        self,
        tokens: np.ndarray,
        pos: int,
        draft: np.ndarray,
        n_draft: int,
        key: jax.Array,
        sampling,
    ) -> tuple[int, int, jax.Array]:
        """Sampled speculative verify: forward + rejection acceptance +
        residual/bonus sample entirely on device (speculative.sampled_accept);
        only (n_accepted, next_token) scalars come back."""
        if self.rolling:
            raise RuntimeError(
                "speculative verify is not supported on a rolling cache; "
                "construct the step without rolling_budget"
            )
        from cake_tpu.models.llama.speculative import _sampled_verify_fn

        fn = _sampled_verify_fn(
            self.config, tokens.shape[1],
            sampling.temperature, sampling.top_k, sampling.top_p,
        )
        n_acc, nxt, self._kv, key = fn(
            self.params, jnp.asarray(tokens, jnp.int32), self._kv,
            jnp.int32(pos), jnp.asarray(draft, jnp.int32),
            jnp.int32(n_draft), key,
        )
        return int(n_acc), int(nxt), key


def prefill_bucket(n: int, max_seq_len: int, minimum: int = 16) -> int:
    """Power-of-two padding bucket: one compile per bucket, not per prompt length."""
    b = minimum
    while b < n:
        b *= 2
    return min(b, max_seq_len)


class LlamaGenerator:
    """Chat-aware token generator (the reference's Generator contract)."""

    def __init__(
        self,
        config: LlamaConfig,
        step: ForwardStep,
        tokenizer: Tokenizer,
        sampling: SamplingConfig = SamplingConfig(),
        decode_chunk_size: int = 1,
        prefill_chunk: int | None = None,
        speculative_k: int = 0,
        prefix_cache: bool = False,
        proposer=None,
    ):
        self.config = config
        self.step = step
        self.tokenizer = tokenizer
        self.sampling = sampling
        # The drafting seam (models/llama/speculative.py): anything with
        # ``propose(tokens, k) -> list[int]``. None = prompt lookup (free);
        # a DraftModelProposer plugs a small model in for free-generation
        # text. Correctness never depends on the proposal — the verify
        # forward re-derives the exact stream/distribution either way.
        self.proposer = proposer
        # Reuse the KV prefix across reset() boundaries: a new dialog whose
        # token stream shares a prefix with the previous one (multi-turn chat
        # through the per-request-reset API, api/mod.rs:78) prefills only the
        # new suffix, at its offset, via the cached-prefix attention path.
        # Token streams are unchanged — the shared prefix's KV is identical to
        # what a fresh prefill would write (causal attention: a token's KV
        # depends only on tokens before it).
        self.prefix_cache = prefix_cache
        # > 0 enables prompt-lookup speculative decoding
        # (models/llama/speculative.py): K drafted tokens verified in one
        # chunked forward. Greedy streams stay byte-identical; temperature>0
        # streams keep the exact plain-decode distribution via rejection
        # sampling. Draft quality affects speed only. Needs
        # repeat_penalty == 1.0 (see _speculative_applicable).
        self.speculative_k = speculative_k
        # Long prompts prefill in chunks of at most this many tokens (None =
        # one shot): bounds compiled shapes and attention-score memory to
        # [prefill_chunk, max_seq] instead of [prompt, prompt].
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, got {prefill_chunk}")
        self.prefill_chunk = prefill_chunk
        # > 1 enables fused multi-token decode when the step supports it
        # (models/llama/fused.py): N tokens per device dispatch instead of a
        # host round trip per token. Streaming then emits in bursts of N.
        self.decode_chunk_size = decode_chunk_size
        # Fused decode compiles the FULL model scan per distinct sampling-knob
        # tuple — only the construction-time config may use it. Requests that
        # override sampling (the API path) fall back to per-step decode, whose
        # recompile unit is just the tiny sampler, so untrusted per-request
        # knobs can never trigger a whole-model recompile under the server lock.
        self._fused_knobs = sampling.trace_knobs()
        # One compiled sampler per distinct (temperature, top_k, top_p,
        # repeat_penalty): those are STATIC in the sampler (python branches), so
        # changing self.sampling (e.g. per-API-request overrides) must select a
        # different trace — a plain jit would silently reuse the first config's
        # constants. The seed is NOT part of the key: the PRNG key is a runtime
        # argument, and keying on seed would leak one compiled entry per seed.
        # Bounded LRU: untrusted per-request knobs (the API) must not grow the
        # compile cache without limit.
        self._sampler_cache: "collections.OrderedDict[tuple, Callable]" = (
            collections.OrderedDict()
        )
        self.last_finish_reason: str = "stop"
        self.reset()

    @classmethod
    def load(
        cls,
        model_dir: str | Path,
        *,
        dtype: jnp.dtype = jnp.bfloat16,
        max_seq_len: int | None = None,
        sampling: SamplingConfig = SamplingConfig(),
        step_factory: Callable[[LlamaConfig, M.Params], ForwardStep] | None = None,
        attention_impl: str | None = None,
        decode_chunk_size: int = 1,
        prefill_chunk: int | None = None,
        speculative_k: int = 0,
        quantize: str | None = None,
        draft_model_dir: str | Path | None = None,
        draft_quantize: str | None = None,
    ) -> "LlamaGenerator":
        """Load config + weights + tokenizer from a checkpoint dir (llama.rs:176-252).

        ``attention_impl`` overrides the kernel choice ("auto"/"pallas"/"xla",
        see LlamaConfig.attention_impl).
        """
        from cake_tpu.io.safetensors_io import load_params

        config = LlamaConfig.from_model_dir(model_dir, attention_impl=attention_impl)
        params = load_params(model_dir, config, dtype)
        if quantize is not None:
            if quantize not in ("int8", "int4"):
                raise ValueError(f"unknown quantize mode {quantize!r}")
            from cake_tpu.ops.quant import quantize_params

            params = quantize_params(params, quantize)
        if step_factory is None:
            step = LocalForwardStep(
                config, params, max_seq_len=max_seq_len, cache_dtype=dtype
            )
        else:
            step = step_factory(config, params)
        proposer = None
        if draft_model_dir is not None:
            from cake_tpu.models.llama.speculative import DraftModelProposer

            proposer = DraftModelProposer.load(
                draft_model_dir,
                dtype=dtype,
                max_seq_len=step.max_seq_len,
                quantize=draft_quantize,
            )
        return cls(
            config,
            step,
            load_tokenizer(model_dir),
            sampling,
            decode_chunk_size=decode_chunk_size,
            prefill_chunk=prefill_chunk,
            speculative_k=speculative_k,
            proposer=proposer,
        )

    # ------------------------------------------------------------- chat state

    def reset(self) -> None:
        """Clear dialog, KV cache, counters (llama.rs:261-268).

        With ``prefix_cache`` on, the step's KV survives the reset as a
        snapshot of the tokens it is valid for; the next dialog prefills only
        past the longest common prefix. The snapshot is bounded both by the
        last sampled token (never fed back, so its KV slot is unwritten; the
        same index bounds speculative decoding's rejected draft slots) and by
        ``_kv_high`` — the high-water mark of SUCCESSFUL step calls — so a
        prefill that failed partway (connection loss, OOM) can never poison
        the next request's reuse with slots that were never written.
        """
        if (
            self.prefix_cache
            and getattr(self, "_started", False)
            # A rolling cache cannot offer prefix reuse: truncating to a
            # common prefix would leave stale slots whose reconstructed
            # positions lie about data written past the prefix.
            and not getattr(self.step, "rolling", False)
        ):
            bound = min(self._kv_high, max(0, len(self._tokens) - 1))
            self._reusable = self._tokens[:bound]
        else:
            self._reusable = []
            if getattr(self, "step", None) is not None:
                self.step.reset()
        self._kv_high = 0
        self.messages: list[Message] = []
        self._tokens: list[int] = []  # full sequence: prompt + generated
        self._n_prompt = 0
        self._decoded_len = 0
        self._started = False
        self._prompt_cache: tuple[str, list[int]] | None = None
        self._key = jax.random.PRNGKey(self.sampling.seed)
        self.last_prefill_tokens = 0  # prefilled (non-reused) tokens, for tests/stats

    def add_message(self, message: Message) -> None:
        self.messages.append(message)

    @property
    def generated_count(self) -> int:
        return len(self._tokens) - self._n_prompt if self._started else 0

    @property
    def generated_token_ids(self) -> list[int]:
        return self._tokens[self._n_prompt :]

    def prompt_token_count(self) -> int:
        """Token count of the current dialog's rendered prompt (pre-generation).

        Lets servers reject over-length prompts with a client error before
        entering the decode path (which raises ValueError at next_token)."""
        return len(self._encode_prompt())

    def _encode_prompt(self) -> list[int]:
        """Encode the dialog, memoized on the rendered prompt string so the
        server's pre-validation and the first next_token share one tokenizer
        pass (rendering is cheap; BPE over a long prompt is not)."""
        prompt = encode_dialog(self.messages, self.config.dialog_template)
        if self._prompt_cache is None or self._prompt_cache[0] != prompt:
            self._prompt_cache = (prompt, self.tokenizer.encode(prompt))
        return self._prompt_cache[1]

    # ------------------------------------------------------------- sampling

    _SAMPLER_CACHE_MAX = 16

    def _sampler(self) -> Callable:
        s = self.sampling
        cache_key = (s.temperature, s.top_k, s.top_p, s.repeat_penalty)
        if cache_key in self._sampler_cache:
            self._sampler_cache.move_to_end(cache_key)
        else:

            def _impl(logits, key, window):
                out = apply_repeat_penalty(logits, s.repeat_penalty, window)
                return sample(
                    out, key, temperature=s.temperature, top_k=s.top_k, top_p=s.top_p
                )

            self._sampler_cache[cache_key] = jax.jit(_impl)
            while len(self._sampler_cache) > self._SAMPLER_CACHE_MAX:
                self._sampler_cache.popitem(last=False)
        return self._sampler_cache[cache_key]

    def _penalty_window(self) -> np.ndarray:
        n = self.sampling.repeat_last_n
        w = np.full((1, n), -1, np.int32)
        if n > 0 and self._tokens:
            recent = self._tokens[-n:]
            w[0, : len(recent)] = recent
        return w

    # ------------------------------------------------------------- decoding

    def _prefill(
        self, ids: list[int], cap: int | None = None, start: int = 0
    ) -> np.ndarray:
        """Run ``ids`` (which sit at positions [start, start+len)) through the
        step; returns logits at the last token.

        With a chunk cap set, a long prompt runs as full chunks of exactly
        that size (one compiled shape, cache-prefix attention) followed by one
        power-of-two-bucketed tail chunk; otherwise one shot at a power-of-two
        bucket (the reference prefills in one shot too, llama.rs:280-292).
        ``start`` > 0 is a continuation over an existing cache prefix (prefix
        reuse) and flows through the same cache-prefix attention path.

        Timing lands in the ``cake_prefill_seconds`` histogram — prefill and
        decode have opposite cost shapes (compute-bound vs HBM-bound), so
        serving telemetry keeps them separate distributions.
        """
        t0 = time.perf_counter()
        try:
            return self._prefill_inner(ids, cap, start)
        finally:
            metrics.registry.histogram(
                "cake_prefill_seconds",
                "Prompt prefill wall time per request (all chunks).",
            ).observe(time.perf_counter() - t0)

    def _prefill_inner(
        self, ids: list[int], cap: int | None = None, start: int = 0
    ) -> np.ndarray:
        if cap is None:
            cap = self.prefill_chunk
        off = start
        end = start + len(ids)
        if cap is not None and end - off > cap:
            n_full = (end - off - 1) // cap  # the tail chunk always remains
            if n_full >= 2 and hasattr(self.step, "prefill_chunks"):
                # Microbatched pipeline prefill: all full chunks in ONE
                # dispatch, overlapped across the mesh's stages
                # (parallel/pipeline.py prefill_chunks) — instead of walking
                # them serially with S-1 stages idle per chunk.
                span = np.asarray(
                    [ids[off - start : off - start + n_full * cap]], np.int32
                )
                self.step.prefill_chunks(span, off, cap)
                off += n_full * cap
                self._kv_high = max(self._kv_high, off)
            while end - off > cap:
                chunk = np.asarray([ids[off - start : off - start + cap]], np.int32)
                self.step(chunk, off, cap)  # logits discarded mid-prompt
                off += cap
                self._kv_high = max(self._kv_high, off)
        rem = ids[off - start :]
        bucket = prefill_bucket(len(rem), self.step.max_seq_len if cap is None else cap)
        # Clamp to the cache bounds: a pow2 bucket at offset `off` must not
        # write past max_seq_len — dynamic_update_slice would CLAMP the start
        # index and silently overwrite the tail of the prompt's KV prefix.
        bucket = min(bucket, self.step.max_seq_len - off)
        chunk = np.zeros((1, bucket), np.int32)
        chunk[0, : len(rem)] = rem
        logits = self.step(chunk, off, len(rem))
        self._kv_high = max(self._kv_high, off + len(rem))
        return logits

    def next_token(self) -> Token:
        """Generate one token (llama.rs:271-335)."""
        if not self._started:
            ids = self._encode_prompt()
            if len(ids) >= self.step.max_seq_len:
                raise ValueError(
                    f"prompt length {len(ids)} exceeds max_seq_len "
                    f"{self.step.max_seq_len}"
                )
            self._tokens = list(ids)
            self._n_prompt = len(ids)
            self._started = True
            # Prefix reuse: skip the tokens whose KV the step already holds.
            # At least the final prompt token is always fed — its logits are
            # needed — so lcp is capped at len(ids) - 1.
            lcp = 0
            if self._reusable:
                cap_lcp = min(len(ids) - 1, len(self._reusable))
                while lcp < cap_lcp and ids[lcp] == self._reusable[lcp]:
                    lcp += 1
                self._reusable = []
            self.last_prefill_tokens = len(ids) - lcp
            logits = self._prefill(ids[lcp:], start=lcp)
        else:
            pos = len(self._tokens) - 1
            if pos >= self.step.max_seq_len:
                # Without this, dynamic_update_slice would clamp the write index
                # and silently corrupt the tail of the cache.
                raise ValueError(
                    f"sequence length {pos + 1} exceeds max_seq_len "
                    f"{self.step.max_seq_len}"
                )
            chunk = np.array([[self._tokens[-1]]], np.int32)
            t0 = time.perf_counter()
            logits = self.step(chunk, pos, 1)
            metrics.registry.histogram(
                "cake_decode_step_seconds",
                "Decode dispatch wall time (mode: per-token step, fused "
                "chunk, or speculative verify).",
            ).observe(time.perf_counter() - t0, mode="step")
            self._kv_high = max(self._kv_high, pos + 1)

        self._key, sub = jax.random.split(self._key)
        next_id = int(
            self._sampler()(
                jnp.asarray(logits), sub, jnp.asarray(self._penalty_window())
            )[0]
        )
        return self._materialize(next_id)

    def _decode_delta(self) -> str:
        """Incremental detokenization: emit only the newly stabilized text."""
        delta, self._decoded_len = decode_delta(
            self.tokenizer, self.generated_token_ids, self._decoded_len
        )
        return delta

    def _materialize(self, tid: int) -> Token:
        """Append one accepted id and produce its Token — the ONE place the
        append/EOS/incremental-detokenize sequence lives (per-step, fused, and
        speculative paths all emit through here)."""
        self._tokens.append(tid)
        is_eos = tid in self.config.eos_token_ids
        text = "" if is_eos else self._decode_delta()
        return Token(id=tid, text=text, is_end_of_stream=is_eos)

    def _next_tokens_fused(self, n_steps: int) -> list[Token]:
        """Decode ``n_steps`` tokens in one fused device dispatch.

        Requires prefill to have run (self._started) and the step to expose
        ``decode_chunk``. The penalty ring is reseeded from the host-side token
        history each call, so chunks compose exactly with per-step decoding.
        Truncates at EOS (the scanned tail past EOS is discarded; its stale KV
        writes sit beyond the live length, masked and later overwritten).
        """
        window = self.sampling.repeat_last_n
        ring = self._penalty_window()
        ring_idx = min(len(self._tokens), window) % window if window > 0 else 0
        last = np.asarray([self._tokens[-1]], np.int32)
        pos = len(self._tokens) - 1
        t0 = time.perf_counter()
        toks, self._key = self.step.decode_chunk(  # type: ignore[attr-defined]
            last, pos, n_steps, self.sampling, self._key, ring, ring_idx
        )
        metrics.registry.histogram(
            "cake_decode_step_seconds",
            "Decode dispatch wall time (mode: per-token step, fused "
            "chunk, or speculative verify).",
        ).observe(time.perf_counter() - t0, mode="fused")
        # All n_steps fed positions were written; reset()'s len-1 clamp drops
        # any slots whose tokens an EOS truncation below discards.
        self._kv_high = max(self._kv_high, pos + n_steps)
        result: list[Token] = []
        for tid in toks[0].tolist():
            tok = self._materialize(int(tid))
            result.append(tok)
            if tok.is_end_of_stream:
                break
        return result

    def _next_tokens_speculative(
        self, draft: list[int], width: int, budget: int
    ) -> list[Token]:
        """Verify ``draft`` (padded to ``width``) in one chunked forward; emit
        the accepted prefix plus the corrected/bonus token, capped to budget.

        Pad drafts use token 0 — if 0 happens to BE the greedy continuation the
        "accepted pad" is still exactly the greedy token, so correctness never
        depends on the proposer.
        """
        from cake_tpu.models.llama.speculative import greedy_accept

        padded = list(draft) + [0] * (width - len(draft))
        chunk = np.asarray([[self._tokens[-1], *padded]], np.int32)
        pos = len(self._tokens) - 1
        s = self.sampling
        t0 = time.perf_counter()
        if s.temperature is not None and s.temperature > 0.0:
            # Sampled acceptance: the emitted marginal at every position is
            # exactly the plain-decode distribution (speculative.py); pads
            # never accept, so candidates past n_acc are just [nxt].
            n_acc, nxt, self._key = self.step.verify_chunk_sampled(  # type: ignore[attr-defined]
                chunk, pos, np.asarray(padded, np.int32), len(draft),
                self._key, s,
            )
        else:
            argm = self.step.verify_chunk(chunk, pos)[0]  # type: ignore[attr-defined]
            n_acc, nxt = greedy_accept(np.asarray(padded), argm)
        metrics.registry.histogram(
            "cake_decode_step_seconds",
            "Decode dispatch wall time (mode: per-token step, fused "
            "chunk, or speculative verify).",
        ).observe(time.perf_counter() - t0, mode="speculative")
        # Valid KV: the fed last token + accepted drafts; rejected-tail slots
        # beyond pos + n_acc hold wrong-token KV and stay unclaimed.
        self._kv_high = max(self._kv_high, pos + 1 + n_acc)
        candidates = padded[:n_acc] + [nxt]
        result: list[Token] = []
        for tid in candidates[:budget]:
            tok = self._materialize(int(tid))
            result.append(tok)
            if tok.is_end_of_stream:
                break
        return result

    def _speculative_applicable(self, budget: int) -> bool:
        s = self.sampling
        sampled = s.temperature is not None and s.temperature > 0.0
        return (
            self.speculative_k > 0
            and self._started
            # repeat_penalty would make the in-chunk target distribution
            # history-dependent; both acceptance modes gate on it.
            and s.repeat_penalty == 1.0
            and hasattr(
                self.step, "verify_chunk_sampled" if sampled else "verify_chunk"
            )
            and budget >= 2
            # Verify writes KV at slots [len-1, len-1+width]; stay in bounds.
            and len(self._tokens) + self.speculative_k <= self.step.max_seq_len
        )

    def _replay_history(self) -> None:
        """Elastic recovery: rebuild ALL step-side KV from the token history.

        After a StepConnectionError every cache (local and remote) is suspect;
        reset the step, then re-feed everything except the pending last token
        as a chunked prefill. The pending token is consumed by the next
        regular step, which resumes the stream exactly where it broke.
        """
        self.step.reset()
        self._kv_high = 0  # everything below re-earns its mark via _prefill
        ids = self._tokens[:-1]
        if not ids:
            return
        # Bound replay compiles even when normal prefill is one-shot.
        self._prefill(ids, cap=self.prefill_chunk or 256)

    def generate(
        self,
        max_new_tokens: int,
        on_token: Callable[[Token], None] | None = None,
        chunk_size: int | None = None,
    ) -> str:
        """Run the decode loop, streaming via callback (master.rs:54-97).

        Sets ``last_finish_reason``: "stop" if EOS ended the stream, "length" if
        the token budget or the context window did. ``chunk_size`` (default:
        self.decode_chunk_size) > 1 selects fused multi-token decode when the
        step supports it; the first token always goes through ``next_token``
        (prefill + host sample), and short tails fall back to per-step decode
        rather than compiling one fused variant per tail length.
        """
        chunk = self.decode_chunk_size if chunk_size is None else chunk_size
        out: list[str] = []
        self.last_finish_reason = "length"
        produced = 0

        def emit(tok: Token) -> bool:
            nonlocal produced
            produced += 1
            if on_token is not None:
                on_token(tok)
            if tok.is_end_of_stream:
                self.last_finish_reason = "stop"
                return False
            out.append(tok.text)
            return True

        recoveries = 0
        needs_replay = False
        produced_at_last_failure = 0
        while produced < max_new_tokens:
            # The budget bounds failures per INCIDENT, not per call: any tokens
            # emitted since the last failure prove the reconnect worked, so a
            # later, unrelated blip gets a fresh allowance. (Checked at the top
            # of the loop — every successful iteration path, including the
            # per-step and speculative branches, exits the try via continue,
            # which would skip a try/else clause.)
            if recoveries and produced > produced_at_last_failure:
                recoveries = 0
            if len(self._tokens) >= self.step.max_seq_len:
                break
            budget = min(
                max_new_tokens - produced,
                self.step.max_seq_len - len(self._tokens),
            )
            try:
                if needs_replay:
                    # Inside the try: a blip DURING replay consumes the same
                    # bounded recovery budget instead of escaping generate().
                    self._replay_history()
                    needs_replay = False
                if self._speculative_applicable(budget):
                    from cake_tpu.models.llama.speculative import propose_lookup

                    draft = (
                        self.proposer.propose(self._tokens, self.speculative_k)
                        if self.proposer is not None
                        else propose_lookup(self._tokens, self.speculative_k)
                    )
                    if draft:
                        stop = False
                        for tok in self._next_tokens_speculative(
                            draft, self.speculative_k, budget
                        ):
                            if not emit(tok):
                                stop = True
                                break
                        if stop:
                            return "".join(out)
                        continue
                if (
                    chunk < 2
                    or budget < chunk  # tail: per-step, single chunk size
                    or not self._started
                    or not hasattr(self.step, "decode_chunk")
                    or self.sampling.trace_knobs() != self._fused_knobs
                ):
                    if not emit(self.next_token()):
                        return "".join(out)
                    continue
                for tok in self._next_tokens_fused(chunk):
                    if not emit(tok):
                        return "".join(out)
            except StepConnectionError as e:
                # Elastic recovery (beyond the reference, which tears down,
                # SURVEY.md §5): the step reconnected; rebuild KV from the
                # token history and retry this iteration. Steps raise BEFORE
                # any token of the iteration materializes, so no emission is
                # lost or duplicated.
                recoveries += 1
                if recoveries > 2:
                    raise
                produced_at_last_failure = produced
                import logging

                logging.getLogger("cake_tpu.generator").warning(
                    "recovering from %s (replaying %d tokens)", e, len(self._tokens)
                )
                needs_replay = True
        return "".join(out)
