"""Llama-family decoder model as pure functions over a param pytree.

Covers the reference's model layer (cake-core/src/models/llama3/{llama,transformer,
attention,mlp}.rs) redesigned TPU-first, and widens it to the whole dense
Llama lineage: Qwen2 (QKV projection bias) and Mistral (sliding-window
attention, decoupled head_dim) run through the SAME block functions, selected
purely by config fields (models/llama/config.py).

  * Params are a pytree of arrays; per-layer weights are STACKED along a leading
    layer axis so a block range runs as one ``lax.scan`` — one compiled loop, not
    ``num_hidden_layers`` unrolled HLO copies (reference walks boxed blocks in a Rust
    loop, llama.rs:81-117).
  * A "block range" [lo, hi) is the unit of sharding, mirroring the reference's
    `Shardable = Transformer` design (llama.rs:171) — a pipeline stage holds the
    stacked params and KV cache for its contiguous range.
  * Decoder block is pre-norm: rms_1 -> GQA attention -> +residual -> rms_2 ->
    SwiGLU -> +residual (transformer.rs:48-70).
  * Prefill (chunk of tokens at offset 0) and decode (1 token at traced ``pos``)
    are two static shapes of the same functions; logits come out f32 at the last
    valid position only (llama.rs:119-137).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models.llama.cache import (
    KVCache,
    rolling_kv_positions,
    write_layer,
    write_layer_rolling,
)
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.ops.attention import gqa_attention, gqa_attention_hm
from cake_tpu.ops.fuse import resolve_fusion
from cake_tpu.ops.mlp import swiglu, swiglu_gu, swiglu_gu_from
from cake_tpu.ops.moe import moe_swiglu
from cake_tpu.ops.pallas.fused_norm_matmul import fused_norm_matmul
from cake_tpu.ops.quant import qmat, weight_out_dim
from cake_tpu.ops.norm import rms_norm
from cake_tpu.ops.pallas.chunk_prefill import chunk_prefill_attention
from cake_tpu.ops.pallas.decode_attention import decode_attention
from cake_tpu.ops.pallas.flash_attention import flash_attention
from cake_tpu.ops.rope import apply_rope, model_rope_tables


def resolve_attention_impl(impl: str) -> str:
    """Resolve "auto" to "pallas" on TPU, "xla" elsewhere (trace-time choice)."""
    if impl == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl not in ("pallas", "xla"):
        raise ValueError(f"unknown attention_impl {impl!r}")
    return impl

Params = dict[str, Any]

# Per-layer weight names. Linear weights are stored [in, out] (transposed from the
# HF/safetensors [out, in] layout) so application is a plain ``x @ w``.
LAYER_WEIGHTS = (
    "wq",       # [hidden, n_q * head_dim]
    "wk",       # [hidden, n_kv * head_dim]
    "wv",       # [hidden, n_kv * head_dim]
    "wo",       # [n_q * head_dim, hidden]
    "w_gate",   # [hidden, intermediate]
    "w_up",     # [hidden, intermediate]
    "w_down",   # [intermediate, hidden]
    "ln_attn",  # [hidden]   input_layernorm
    "ln_mlp",   # [hidden]   post_attention_layernorm
)

# Qwen2-family extras: QKV projection biases (o_proj has none). Present in the
# layer tree only when config.attention_bias is set.
LAYER_BIASES = (
    "bq",  # [n_q * head_dim]
    "bk",  # [n_kv * head_dim]
    "bv",  # [n_kv * head_dim]
)


def init_params(
    config: LlamaConfig,
    key: jax.Array,
    dtype: jnp.dtype = jnp.bfloat16,
) -> Params:
    """Random-init params (for tests and compile checks; real runs load safetensors)."""
    h, inter, v = config.hidden_size, config.intermediate_size, config.vocab_size
    hd, n_q, n_kv = config.head_dim, config.num_attention_heads, config.num_key_value_heads
    n = config.num_hidden_layers
    keys = iter(jax.random.split(key, 24))

    def w(k, *shape):
        fan_in = shape[-2] if len(shape) > 1 else shape[-1]
        return (jax.random.normal(k, shape, jnp.float32) * fan_in**-0.5).astype(dtype)

    n_e = config.num_local_experts
    if n_e:
        # MoE (Mixtral / Qwen2-MoE): expert weights stacked
        # [n_layers, n_experts, in, out]; the router stays full precision
        # like the norms (it is tiny and its softmax decides routing).
        e_inter = config.moe_intermediate_size or inter
        mlp_weights = {
            "router": w(next(keys), n, h, n_e),
            "w_gate": w(next(keys), n, n_e, h, e_inter),
            "w_up": w(next(keys), n, n_e, h, e_inter),
            "w_down": w(next(keys), n, n_e, e_inter, h),
        }
        if config.shared_expert_intermediate_size:
            s_i = config.shared_expert_intermediate_size
            mlp_weights.update(
                sh_gate=w(next(keys), n, h, s_i),
                sh_up=w(next(keys), n, h, s_i),
                sh_down=w(next(keys), n, s_i, h),
                se_gate=w(next(keys), n, h, 1),
            )
    else:
        mlp_weights = {
            "w_gate": w(next(keys), n, h, inter),
            "w_up": w(next(keys), n, h, inter),
            "w_down": w(next(keys), n, inter, h),
        }
    # Gemma stores norm weights zero-centered (applied as 1 + w) — identity
    # init is zeros there, ones elsewhere.
    norm_init = jnp.zeros if config.rmsnorm_offset else jnp.ones
    layers = {
        "wq": w(next(keys), n, h, n_q * hd),
        "wk": w(next(keys), n, h, n_kv * hd),
        "wv": w(next(keys), n, h, n_kv * hd),
        "wo": w(next(keys), n, n_q * hd, h),
        **mlp_weights,
        "ln_attn": norm_init((n, h), dtype),
        "ln_mlp": norm_init((n, h), dtype),
    }
    if config.post_block_norms:
        layers["ln_post_attn"] = norm_init((n, h), dtype)
        layers["ln_post_mlp"] = norm_init((n, h), dtype)
    if config.qk_norm:  # Qwen3 / Gemma-3: per-head q/k RMSNorm weights
        layers["q_norm"] = norm_init((n, hd), dtype)
        layers["k_norm"] = norm_init((n, hd), dtype)
    if config.sliding_pattern is not None:  # Gemma-3 5:1 local/global layers
        layers["win_flag"] = jnp.asarray(config.sliding_pattern)
    if config.rope_local_base_freq is not None:
        # Sliding layers rope at the LOCAL theta (plane 1 of the stacked
        # tables, ops/rope.model_rope_tables); full layers at the global.
        if config.sliding_pattern is None:
            raise ValueError(
                "rope_local_base_freq needs sliding_pattern (which layers "
                "take the local rope) — a dual-rope config without the "
                "pattern is underspecified"
            )
        layers["rope_sel"] = jnp.asarray(config.sliding_pattern, jnp.int32)
    if config.alt_sliding_window:
        layers["win_flag"] = (jnp.arange(n) % 2) == 0
    if config.attention_bias:
        layers["bq"] = w(next(keys), n, 1, n_q * hd)[:, 0]
        layers["bk"] = w(next(keys), n, 1, n_kv * hd)[:, 0]
        layers["bv"] = w(next(keys), n, 1, n_kv * hd)[:, 0]
    return {
        "embed": w(next(keys), v, h),
        "layers": layers,
        "ln_f": norm_init((h,), dtype),
        "lm_head": w(next(keys), h, v),
    }


def embed_tokens(
    tree: Params, tokens: jnp.ndarray, config: LlamaConfig
) -> jnp.ndarray:
    """Token embedding lookup — THE one entry for every execution backend.

    Gemma-family models scale embeddings by sqrt(hidden_size)
    (config.embedding_scale); the multiplier is cast to the embedding dtype
    first, matching the HF normalizer's rounding.
    """
    x = tree["embed"][tokens]
    if config.embedding_scale is not None:
        x = x * jnp.asarray(config.embedding_scale, x.dtype)
    return x


def is_cached_prefill(pos: int, width: int) -> bool:
    """The ONE predicate for selecting the cache-prefix attention variant: a
    multi-token chunk arriving at a nonzero offset (chunked prefill
    continuation). Every execution backend must use this, not its own copy —
    the static flag decides which attention path compiles."""
    return pos > 0 and width > 1


def slice_layers(layers: Params, lo: int, hi: int) -> Params:
    """Take the stacked-param shard for block range [lo, hi)."""
    return {k: w[lo:hi] for k, w in layers.items()}


def layer_head_counts(lp: Params, config: LlamaConfig) -> tuple[int, int]:
    """(n_q, n_kv) heads held by THIS layer tree — the one inference shared by
    every block body. Under tensor parallelism a shard holds heads/tp of each;
    with fused QKV (ops/fuse.py) the shard fraction is recovered from the
    fused output width via the global config head ratio (tp divides both head
    counts — parallel/tensor.validate_tp)."""
    hd = config.head_dim
    if "wqkv" in lp:
        out_sum = weight_out_dim(lp["wqkv"])
        unit = config.num_attention_heads + 2 * config.num_key_value_heads
        t = (unit * hd) // out_sum
        return config.num_attention_heads // t, config.num_key_value_heads // t
    return weight_out_dim(lp["wq"]) // hd, weight_out_dim(lp["wk"]) // hd


def block_qkv_flat(
    lp: Params,
    x: jnp.ndarray,
    config: LlamaConfig,
    fusion: tuple | None = None,
) -> jnp.ndarray:
    """rms_1 -> FUSED QKV projection -> +bias, UNSPLIT: [b, chunk, qkv_dim].

    The projection half of block_qkv for layer trees carrying the prep-time
    ``wqkv`` (ops/fuse.py). Factored out so the decode ingest fusion
    (ops/pallas/fused_ingest.py) can take the flat row straight into its
    split+rope+write kernel. ``fusion`` is a resolved (set, impl) pair from
    ops/fuse.resolve_fusion (None = resolve from the config): with "norm"
    enabled the input norm folds into the projection
    (ops/pallas/fused_norm_matmul.py) — bit-identical either way.
    """
    if fusion is None:
        fusion = resolve_fusion(config)
    fusions, fimpl = fusion
    if "norm" in fusions:
        qkv = fused_norm_matmul(
            x, lp["ln_attn"], lp["wqkv"],
            eps=config.rms_norm_eps, offset=config.rmsnorm_offset,
            impl=fimpl,
        )
    else:
        h = rms_norm(x, lp["ln_attn"], config.rms_norm_eps, config.rmsnorm_offset)
        qkv = qmat(h, lp["wqkv"])
    if "bqkv" in lp:
        qkv = qkv + lp["bqkv"].astype(qkv.dtype)
    return qkv


def block_qkv(
    lp: Params,
    x: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    positions: jnp.ndarray,
    config: LlamaConfig,
    k_positions: jnp.ndarray | None = None,
    fusion: tuple | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Shared head of every attention variant: rms_1 -> QKV projection ->
    RoPE on q/k (v un-roped). ONE copy — the local/pipeline/tp paths
    (block_forward), the sequence-parallel bodies (parallel/sequence.py), and
    batched generation (models/llama/batch.py) must not drift in block
    arithmetic.

    The layer tree may carry the prep-time FUSED projection ``wqkv``
    (ops/fuse.py) instead of wq/wk/wv: one matmul, split afterwards —
    column-identical numerics, one HBM-bound op instead of three.

    ``k_positions`` (default: ``positions``) lets left-padded batches rope keys
    with sentinel positions on pad slots (clamped table gather; the garbage
    values are mask-excluded as keys). ``cos``/``sin`` may be pre-gathered
    3-D rows (ops/rope.apply_rope) ONLY when q and k share ``positions``."""
    b, chunk, _ = x.shape
    hd = config.head_dim
    n_q, n_kv = layer_head_counts(lp, config)
    if "rope_sel" in lp:
        # Dual-rope families (Gemma-3): plane 0 = global rope, 1 = local.
        # The SAME leading-axis select serves stacked tables [2, seq, hd/2]
        # and stacked pre-gathered rows [2, b, s, hd/2], so both the
        # per-layer and once-per-step gather paths stay family-agnostic.
        cos = cos[lp["rope_sel"]]
        sin = sin[lp["rope_sel"]]
    assert not (cos.ndim == 3 and k_positions is not None), (
        "pre-gathered rope rows cannot serve distinct k_positions"
    )
    if "wqkv" in lp:
        # The "norm" fusion site (ops/pallas/fused_norm_matmul.py) lives
        # inside block_qkv_flat; unfused layer trees (no wqkv) keep the
        # plain path — serving backends always run fuse_params weights.
        qkv = block_qkv_flat(lp, x, config, fusion)
        qw, kw = n_q * hd, n_kv * hd
        q = qkv[..., :qw]
        k = qkv[..., qw : qw + kw]
        v = qkv[..., qw + kw :]
    else:
        h = rms_norm(x, lp["ln_attn"], config.rms_norm_eps, config.rmsnorm_offset)
        q, k, v = qmat(h, lp["wq"]), qmat(h, lp["wk"]), qmat(h, lp["wv"])
        if "bq" in lp:  # Qwen2-family QKV bias (config.attention_bias)
            q = q + lp["bq"].astype(q.dtype)
            k = k + lp["bk"].astype(k.dtype)
            v = v + lp["bv"].astype(v.dtype)
    q = q.reshape(b, chunk, n_q, hd)
    k = k.reshape(b, chunk, n_kv, hd)
    v = v.reshape(b, chunk, n_kv, hd)
    if "q_norm" in lp:
        # Qwen3 family: head_dim-wide RMSNorm on every q/k head AFTER the
        # projection, BEFORE RoPE (HF Qwen3Attention.forward — "only on the
        # head dim"). The weight is shared across heads, so tensor-parallel
        # head sharding replicates it untouched.
        q = rms_norm(q, lp["q_norm"], config.rms_norm_eps, config.rmsnorm_offset)
        k = rms_norm(k, lp["k_norm"], config.rms_norm_eps, config.rmsnorm_offset)
    return (
        apply_rope(q, cos, sin, positions),
        apply_rope(k, cos, sin, positions if k_positions is None else k_positions),
        v,
    )


def block_finish(
    lp: Params,
    x: jnp.ndarray,
    attn: jnp.ndarray,
    config: LlamaConfig,
    tp_axis: str | None = None,
    moe_valid: jnp.ndarray | None = None,
    moe_dispatch: str = "auto",
    fusion: tuple | None = None,
) -> jnp.ndarray:
    """Shared tail: out-projection + residual, rms_2 -> SwiGLU + residual,
    with the tensor-parallel psums at the two partial-sum points. A layer
    tree carrying a "router" runs the Mixtral MoE MLP instead of the dense
    SwiGLU (experts sharded over tp; same partial-sum + psum convention).
    ``moe_valid`` ([b, chunk] bool) marks pad slots whose routed assignments
    must not consume expert capacity (ops/moe.py capacity dispatch).
    ``fusion`` (resolved (set, impl), ops/fuse.resolve_fusion; None = from
    the config): "norm" folds rms_2 into the fused gate|up projection
    (ops/pallas/fused_norm_matmul.py) on the dense ``w_gu`` path —
    bit-identical either way."""
    b, chunk, _ = x.shape
    off = config.rmsnorm_offset
    if fusion is None:
        fusion = resolve_fusion(config)
    fusions, fimpl = fusion
    o = qmat(attn.reshape(b, chunk, -1), lp["wo"]).astype(x.dtype)
    if tp_axis is not None:
        o = jax.lax.psum(o, tp_axis)
    if "ln_post_attn" in lp:
        # Gemma-2 post-attention norm: applied to the branch output (after
        # the tp psum — norming a partial sum would be wrong) before the
        # residual add.
        o = rms_norm(o, lp["ln_post_attn"], config.rms_norm_eps, off)
    x = x + o
    if "norm" in fusions and "w_gu" in lp and "router" not in lp:
        # rms_2 folded into the gate|up matmul; the epilogue is the literal
        # swiglu_gu tail, so the branch is byte-identical to the unfused one.
        gu = fused_norm_matmul(
            x, lp["ln_mlp"], lp["w_gu"],
            eps=config.rms_norm_eps, offset=off, impl=fimpl,
        )
        mlp = swiglu_gu_from(
            gu, lp["w_down"], config.hidden_activation
        ).astype(x.dtype)
        if tp_axis is not None:
            mlp = jax.lax.psum(mlp, tp_axis)
        if "ln_post_mlp" in lp:
            mlp = rms_norm(mlp, lp["ln_post_mlp"], config.rms_norm_eps, off)
        return x + mlp
    h = rms_norm(x, lp["ln_mlp"], config.rms_norm_eps, off)
    if "router" in lp:
        mlp = moe_swiglu(
            h, lp["router"], lp["w_gate"], lp["w_up"], lp["w_down"],
            config.num_experts_per_tok, tp_axis=tp_axis,
            norm_topk=config.norm_topk_prob, valid=moe_valid,
            dispatch=moe_dispatch,
        ).astype(x.dtype)
        if "sh_gu" in lp or "sh_gate" in lp:
            # Qwen2-MoE always-on shared expert, scaled by a learned sigmoid
            # gate (computed identically on every tp shard; the product
            # distributes over the shared expert's partial sums).
            if "sh_gu" in lp:  # fused gate|up (ops/fuse.py)
                shared = swiglu_gu(h, lp["sh_gu"], lp["sh_down"])
            else:
                shared = swiglu(h, lp["sh_gate"], lp["sh_up"], lp["sh_down"])
            gate = jax.nn.sigmoid(qmat(h, lp["se_gate"]))
            mlp = mlp + (shared * gate).astype(x.dtype)
    elif "w_gu" in lp:  # fused gate|up (ops/fuse.py): one matmul, split after
        mlp = swiglu_gu(
            h, lp["w_gu"], lp["w_down"], activation=config.hidden_activation
        ).astype(x.dtype)
    else:
        mlp = swiglu(
            h, lp["w_gate"], lp["w_up"], lp["w_down"],
            activation=config.hidden_activation,
        ).astype(x.dtype)
    if tp_axis is not None:
        mlp = jax.lax.psum(mlp, tp_axis)
    if "ln_post_mlp" in lp:
        mlp = rms_norm(mlp, lp["ln_post_mlp"], config.rms_norm_eps, off)
    return x + mlp


def block_forward(
    lp: Params,
    x: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    positions: jnp.ndarray,
    pos: jnp.ndarray,
    config: LlamaConfig,
    tp_axis: str | None = None,
    cached_prefill: bool = False,
    rolling: bool = False,
    valid_len: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One decoder block over a token chunk.

    Args:
      lp: this layer's weights (unstacked). Head counts are inferred from the
        projection shapes, NOT the config — under tensor parallelism each shard
        holds num_heads/tp of them (parallel/tensor.py).
      x: [batch, chunk, hidden] activations.
      k_cache/v_cache: [batch, n_kv, max_seq, head_dim] this layer's KV store
        (head-major, models/llama/cache.py).
      cos/sin: rope tables.
      positions: [batch, chunk] absolute positions of the chunk tokens.
      pos: scalar write offset (== positions[:, 0]).
      tp_axis: mesh axis name for Megatron-style tensor parallelism: the
        attention out-projection and the MLP down-projection produce partial
        sums over the sharded head/intermediate dims, reduced here with psum
        before each residual add. None = single-shard weights, no collectives.
      rolling: STATIC — the cache is a rolling window buffer (slot = pos %
        cache_len, cache.py); requires config.sliding_window. Unifies the
        prefill/decode attention variants into one cache read with
        reconstructed slot positions.
      valid_len: scalar count of real (non-padded) tokens in the chunk —
        needed when rolling so padded bucket tails don't evict live keys.

    Returns (x_out, k_cache, v_cache).
    """
    b, chunk, _ = x.shape

    q, k, v = block_qkv(lp, x, cos, sin, positions, config)

    win = config.sliding_window
    # Gemma-family attention knobs: score scale decoupled from head_dim,
    # tanh soft-capping, and a per-layer window gate carried IN the layer
    # tree ("win_flag", set at load/init for the alternating local/global
    # pattern) so it rides layer slicing/stacking through every backend.
    attn_kw = dict(
        window=win,
        window_flag=lp.get("win_flag"),
        scale=config.attn_scale,
        softcap=config.attn_logit_softcap,
    )
    if rolling:
        # The rolling ring cache stays on the XLA path deliberately: its
        # buffer is already window-sized (reads are O(window) by
        # construction, the pruning a kernel would add), and slot positions
        # are permuted by the ring wrap, which breaks the contiguous-block
        # interval pruning the Pallas kernels are built on.
        assert win is not None, "rolling cache requires sliding_window"
        vl = jnp.int32(chunk) if valid_len is None else valid_len
        k_cache, v_cache = write_layer_rolling(k_cache, v_cache, k, v, pos, vl)
        kv_pos = rolling_kv_positions(k_cache.shape[2], pos, vl)
        kv_positions = jnp.broadcast_to(
            kv_pos[None, :], (b, k_cache.shape[2])
        )
        attn = gqa_attention_hm(
            q, k_cache, v_cache, positions, kv_positions, **attn_kw
        )
        x = block_finish(lp, x, attn, config, tp_axis=tp_axis)
        return x, k_cache, v_cache

    k_cache, v_cache = write_layer(k_cache, v_cache, k, v, pos)

    impl = resolve_attention_impl(config.attention_impl)
    # Per-family attention knobs threaded into the Pallas kernels: sliding
    # window (static, per-layer traced gate), scale override, tanh softcap.
    pallas_kw = dict(
        window=win,
        window_flag=lp.get("win_flag"),
        scale=config.attn_scale,
        softcap=config.attn_logit_softcap,
    )
    if chunk > 1 and cached_prefill:
        # Prefill CONTINUATION: a chunk at pos > 0 attends to the whole live
        # cache prefix (which already contains this chunk's keys, written
        # above). This is what lets long prompts prefill in bounded chunks
        # instead of one giant compile. The Pallas kernel streams only the
        # live, causally-needed cache blocks; the XLA fallback reads the full
        # cache and hides dead slots behind the position mask.
        if impl == "pallas":
            q_starts = jnp.broadcast_to(pos, (b,)).astype(jnp.int32)
            attn = chunk_prefill_attention(
                q, k_cache, v_cache, q_starts, q_starts + chunk, **pallas_kw
            )
        else:
            kv_positions = jnp.broadcast_to(
                jnp.arange(k_cache.shape[2], dtype=jnp.int32)[None, :],
                (b, k_cache.shape[2]),
            )
            attn = gqa_attention_hm(
                q, k_cache, v_cache, positions, kv_positions, **attn_kw
            )
    elif chunk > 1:
        # Prefill from offset 0 (callers pass pos=0 when cached_prefill is
        # False): the chunk attends only within itself — avoids materializing
        # [chunk, max_seq] score rows against an empty cache.
        if impl == "pallas":
            attn = flash_attention(q, k, v, **pallas_kw)
        else:
            attn = gqa_attention(q, k, v, positions, positions, **attn_kw)
    else:
        # Decode: attend over the live cache prefix. The Pallas kernel prunes
        # blocks past pos (and behind the window); the XLA path reads the
        # whole cache and hides dead slots behind the position mask.
        if impl == "pallas":
            lengths = jnp.broadcast_to(pos + 1, (b,)).astype(jnp.int32)
            attn = decode_attention(
                q, k_cache, v_cache, lengths, None, **pallas_kw
            )
        else:
            kv_positions = jnp.broadcast_to(
                jnp.arange(k_cache.shape[2], dtype=jnp.int32)[None, :],
                (b, k_cache.shape[2]),
            )
            attn = gqa_attention_hm(
                q, k_cache, v_cache, positions, kv_positions, **attn_kw
            )

    x = block_finish(lp, x, attn, config, tp_axis=tp_axis)
    return x, k_cache, v_cache


def blocks_forward(
    layers: Params,
    x: jnp.ndarray,
    kv: KVCache,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    pos: jnp.ndarray,
    config: LlamaConfig,
    valid: jnp.ndarray | None = None,
    tp_axis: str | None = None,
    cached_prefill: bool = False,
    rolling: bool = False,
    valid_len: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, KVCache]:
    """Run a stacked block range as one ``lax.scan`` over the layer axis.

    This is the unit a pipeline stage executes: the reference ships a contiguous
    layer run to a worker as one batch op (llama.rs:95-114, worker.rs:218-229);
    here the run is one compiled scan.

    ``valid`` (optional [n_layers] bool) gates each layer's contribution — used
    by ragged pipeline stages padded with inert layers (parallel/pipeline.py).
    ``tp_axis`` threads through to block_forward's tensor-parallel reductions.
    ``rolling``/``valid_len`` select the rolling-window cache layout
    (block_forward).
    """
    b, chunk, _ = x.shape
    positions = pos + jnp.broadcast_to(
        jnp.arange(chunk, dtype=jnp.int32)[None, :], (b, chunk)
    )
    # Positions are layer-invariant: gather the rope rows ONCE per step
    # instead of once per layer inside the scan (apply_rope's 3-D form).
    # (The rolling path's reconstructed ring positions feed only the
    # attention mask, never rope — q/k always rope at ``positions``.)
    # Stacked dual-rope tables gather BOTH planes; block_qkv selects.
    if cos.ndim == 3:
        cos, sin = cos[:, positions], sin[:, positions]
    else:
        cos, sin = cos[positions], sin[positions]

    def body(carry, per_layer):
        x = carry
        lp, k_c, v_c, ok = per_layer
        x_new, k_c, v_c = block_forward(
            lp, x, k_c, v_c, cos, sin, positions, pos, config,
            tp_axis=tp_axis, cached_prefill=cached_prefill,
            rolling=rolling, valid_len=valid_len,
        )
        x = x_new if valid is None else jnp.where(ok, x_new, x)
        return x, (k_c, v_c)

    ok = jnp.ones((kv.n_layers,), bool) if valid is None else valid
    x, (k_out, v_out) = jax.lax.scan(body, x, (layers, kv.k, kv.v, ok))
    return x, KVCache(k=k_out, v=v_out)


def _final_softcap(logits: jnp.ndarray, config: LlamaConfig) -> jnp.ndarray:
    """Gemma-2 final-logit soft-capping (no-op for every other family)."""
    cap = config.final_logit_softcap
    if cap is None:
        return logits
    return cap * jnp.tanh(logits / cap)


def head_forward(
    params: Params,
    x: jnp.ndarray,
    seq_len: jnp.ndarray,
    config: LlamaConfig,
    fusion: tuple | None = None,
) -> jnp.ndarray:
    """Final norm + LM head at the last valid position -> [batch, vocab] f32.

    Shared by the local and pipelined paths so their numerics can't diverge.
    Slices BEFORE ln_f/lm_head so the vocab projection runs on [batch, 1, hidden]
    (llama.rs:119-137 slices the last position the same way). ``fusion``
    ((set, impl) from ops/fuse.resolve_fusion; None = from the config):
    "norm" folds ln_f into the lm_head projection
    (ops/pallas/fused_norm_matmul.py) — tied embeddings keep the unfused
    path (the transposed weight would materialize a copy per call).
    """
    x_last = jax.lax.dynamic_slice_in_dim(x, seq_len - 1, 1, axis=1)
    if fusion is None:
        fusion = resolve_fusion(config)
    fusions, fimpl = fusion
    if "norm" in fusions and not config.tie_word_embeddings:
        logits = fused_norm_matmul(
            x_last, params["ln_f"], params["lm_head"],
            eps=config.rms_norm_eps, offset=config.rmsnorm_offset,
            impl=fimpl,
        )[:, 0, :].astype(jnp.float32)
        return _final_softcap(logits, config)
    x_last = rms_norm(
        x_last, params["ln_f"], config.rms_norm_eps, config.rmsnorm_offset
    )
    lm_head = params["embed"].T if config.tie_word_embeddings else params["lm_head"]
    logits = qmat(x_last[:, 0, :], lm_head).astype(jnp.float32)
    return _final_softcap(logits, config)


def head_forward_all(
    params: Params,
    x: jnp.ndarray,
    config: LlamaConfig,
) -> jnp.ndarray:
    """Final norm + LM head at EVERY chunk position -> [batch, chunk, vocab] f32.

    Used by speculative verification (models/llama/speculative.py): one chunked
    forward scores all draft positions at once. Same ln_f/lm_head weights as
    head_forward — numerics cannot diverge.
    """
    x = rms_norm(x, params["ln_f"], config.rms_norm_eps, config.rmsnorm_offset)
    lm_head = params["embed"].T if config.tie_word_embeddings else params["lm_head"]
    return _final_softcap(qmat(x, lm_head).astype(jnp.float32), config)


def forward_all_logits(
    params: Params,
    tokens: jnp.ndarray,
    kv: KVCache,
    pos: jnp.ndarray,
    config: LlamaConfig,
    cached_prefill: bool = True,
) -> tuple[jnp.ndarray, KVCache]:
    """Full-model forward returning logits at every chunk position.

    The speculative-verify primitive: feed [last_token, draft_0..draft_{K-1}]
    at offset ``pos`` and read each position's next-token distribution.
    """
    cos, sin = model_rope_tables(config, kv.max_seq_len)
    x = embed_tokens(params, tokens, config)
    x, kv = blocks_forward(
        params["layers"], x, kv, cos, sin, pos, config, cached_prefill=cached_prefill
    )
    return head_forward_all(params, x, config), kv


def forward(
    params: Params,
    tokens: jnp.ndarray,
    kv: KVCache,
    pos: jnp.ndarray,
    seq_len: jnp.ndarray,
    config: LlamaConfig,
    cached_prefill: bool = False,
    rolling: bool = False,
    rope_len: int | None = None,
) -> tuple[jnp.ndarray, KVCache]:
    """Full-model forward: embed -> blocks -> ln_f -> lm_head at last valid position.

    Args:
      tokens: [batch, chunk] int32 (chunk may be padded; see seq_len).
      kv: full-depth KVCache.
      pos: scalar offset of tokens[:, 0] in the sequence.
      seq_len: scalar count of VALID tokens in the chunk (logits taken at
        seq_len - 1, cf. llama.rs:119-137 last-position slice).
      cached_prefill: STATIC — chunk > 1 arriving at pos > 0 (a long prompt
        prefilling in bounded chunks); selects cache-prefix attention.
      rolling: STATIC — kv is a rolling window buffer smaller than the
        logical sequence bound (sliding-window models; cache.py).
      rope_len: STATIC — RoPE table length; REQUIRED when rolling (positions
        exceed the physical cache length, which otherwise sizes the table).

    Returns (logits [batch, vocab] f32, updated KVCache).
    """
    cos, sin = model_rope_tables(config, rope_len if rope_len is not None else kv.max_seq_len)
    x = embed_tokens(params, tokens, config)
    x, kv = blocks_forward(
        params["layers"], x, kv, cos, sin, pos, config,
        cached_prefill=cached_prefill, rolling=rolling, valid_len=seq_len,
    )
    return head_forward(params, x, seq_len, config), kv


def count_params(params: Params) -> int:
    return sum(int(np.prod(a.shape)) for a in jax.tree.leaves(params))
