"""Chat message types and the Llama-3 chat template.

Covers the reference's chat layer: ``MessageRole``/``Message``
(cake-core/src/models/chat.rs:4-63) and the ``History`` prompt encoder
(cake-core/src/models/llama3/history.rs:8-33), which renders

    <|begin_of_text|>
    <|start_header_id|>{role}<|end_header_id|>\n\n{content}<|eot_id|>   (per message)
    <|start_header_id|>assistant<|end_header_id|>\n\n                  (trailer)

The template is produced as TEXT with special-token markers; tokenizers encode the
markers as single special tokens (see tokenizer.py), matching Meta's reference
encoding that history.rs hand-ports.
"""

from __future__ import annotations

import dataclasses
from enum import Enum

BEGIN_OF_TEXT = "<|begin_of_text|>"
START_HEADER = "<|start_header_id|>"
END_HEADER = "<|end_header_id|>"
EOT = "<|eot_id|>"


class MessageRole(str, Enum):
    SYSTEM = "system"
    USER = "user"
    ASSISTANT = "assistant"


@dataclasses.dataclass
class Message:
    role: MessageRole
    content: str

    @classmethod
    def system(cls, content: str) -> "Message":
        return cls(MessageRole.SYSTEM, content)

    @classmethod
    def user(cls, content: str) -> "Message":
        return cls(MessageRole.USER, content)

    @classmethod
    def assistant(cls, content: str) -> "Message":
        return cls(MessageRole.ASSISTANT, content)

    def to_dict(self) -> dict[str, str]:
        return {"role": self.role.value, "content": self.content}

    @classmethod
    def from_dict(cls, d: dict[str, str]) -> "Message":
        return cls(MessageRole(d["role"]), d["content"])


def encode_header(role: str) -> str:
    return f"{START_HEADER}{role}{END_HEADER}\n\n"


def encode_message(msg: Message) -> str:
    # history.rs:14-20: header, stripped content, eot.
    return f"{encode_header(msg.role.value)}{msg.content.strip()}{EOT}"


def encode_dialog_to_prompt(messages: list[Message]) -> str:
    """Full dialog template with the trailing assistant header (history.rs:22-33)."""
    parts = [BEGIN_OF_TEXT]
    parts.extend(encode_message(m) for m in messages)
    parts.append(encode_header(MessageRole.ASSISTANT.value))
    return "".join(parts)
