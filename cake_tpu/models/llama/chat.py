"""Chat message types and the Llama-3 chat template.

Covers the reference's chat layer: ``MessageRole``/``Message``
(cake-core/src/models/chat.rs:4-63) and the ``History`` prompt encoder
(cake-core/src/models/llama3/history.rs:8-33), which renders

    <|begin_of_text|>
    <|start_header_id|>{role}<|end_header_id|>\n\n{content}<|eot_id|>   (per message)
    <|start_header_id|>assistant<|end_header_id|>\n\n                  (trailer)

The template is produced as TEXT with special-token markers; tokenizers encode the
markers as single special tokens (see tokenizer.py), matching Meta's reference
encoding that history.rs hand-ports.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from enum import Enum

logger = logging.getLogger(__name__)

BEGIN_OF_TEXT = "<|begin_of_text|>"
START_HEADER = "<|start_header_id|>"
END_HEADER = "<|end_header_id|>"
EOT = "<|eot_id|>"


class MessageRole(str, Enum):
    SYSTEM = "system"
    USER = "user"
    ASSISTANT = "assistant"


@dataclasses.dataclass
class Message:
    role: MessageRole
    content: str

    @classmethod
    def system(cls, content: str) -> "Message":
        return cls(MessageRole.SYSTEM, content)

    @classmethod
    def user(cls, content: str) -> "Message":
        return cls(MessageRole.USER, content)

    @classmethod
    def assistant(cls, content: str) -> "Message":
        return cls(MessageRole.ASSISTANT, content)

    def to_dict(self) -> dict[str, str]:
        return {"role": self.role.value, "content": self.content}

    @classmethod
    def from_dict(cls, d: dict[str, str]) -> "Message":
        return cls(MessageRole(d["role"]), d["content"])


def encode_header(role: str) -> str:
    return f"{START_HEADER}{role}{END_HEADER}\n\n"


def encode_message(msg: Message) -> str:
    # history.rs:14-20: header, stripped content, eot.
    return f"{encode_header(msg.role.value)}{msg.content.strip()}{EOT}"


def encode_dialog_to_prompt(messages: list[Message]) -> str:
    """Full dialog template with the trailing assistant header (history.rs:22-33)."""
    parts = [BEGIN_OF_TEXT]
    parts.extend(encode_message(m) for m in messages)
    parts.append(encode_header(MessageRole.ASSISTANT.value))
    return "".join(parts)


QWEN2_DEFAULT_SYSTEM = "You are a helpful assistant."

_warned_qwen2_default = False
_warn_lock = threading.Lock()


def _warn_qwen2_default_system_once() -> None:
    # Qwen2.5 shares model_type "qwen2" but brands a different default system
    # prompt; surface the silent divergence once per process so users of 2.5
    # checkpoints know to pass an explicit system message. Lock-guarded:
    # concurrent serving threads race the flag otherwise.
    global _warned_qwen2_default
    with _warn_lock:
        if _warned_qwen2_default:
            return
        _warned_qwen2_default = True
        logger.warning(
            "chatml template: injecting the Qwen2 default system prompt "
            "(%r); Qwen2.5 checkpoints brand a different default — pass an "
            "explicit system message for exact parity",
            QWEN2_DEFAULT_SYSTEM,
        )


def encode_dialog_chatml(messages: list[Message]) -> str:
    """Qwen2-family ChatML template with the trailing assistant header:

        <|im_start|>{role}\\n{content}<|im_end|>\\n   (per message)
        <|im_start|>assistant\\n                      (trailer)

    Matches Qwen2's tokenizer_config chat template (no BOS; <|im_end|> is the
    eos/stop token), including its default system prompt when the dialog does
    not begin with a system message. Caveat: Qwen2.5 checkpoints share
    model_type "qwen2" but brand their default system prompt ("You are
    Qwen, ...") — config.json cannot distinguish them, so systemless Qwen2.5
    dialogs get the Qwen2 default; pass an explicit system message (or ship
    the branded text in it) for exact Qwen2.5 template parity.
    """
    parts = []
    if not messages or messages[0].role is not MessageRole.SYSTEM:
        _warn_qwen2_default_system_once()
        parts.append(
            f"<|im_start|>system\n{QWEN2_DEFAULT_SYSTEM}<|im_end|>\n"
        )
    parts.extend(
        f"<|im_start|>{m.role.value}\n{m.content.strip()}<|im_end|>\n"
        for m in messages
    )
    parts.append("<|im_start|>assistant\n")
    return "".join(parts)


def encode_dialog_chatml_no_default_system(messages: list[Message]) -> str:
    """Qwen3's ChatML: identical turn structure but NO default system prompt
    (Qwen3's tokenizer_config template omits it; a systemless dialog starts
    straight at the first user turn). Thinking-mode tags are a sampling-time
    concern, not a template one — the base template emits none."""
    parts = [
        f"<|im_start|>{m.role.value}\n{m.content.strip()}<|im_end|>\n"
        for m in messages
    ]
    parts.append("<|im_start|>assistant\n")
    return "".join(parts)


def encode_dialog_mistral(messages: list[Message]) -> str:
    """Mistral instruct template:

        <s>[INST] {user} [/INST]{assistant}</s>[INST] {user2} [/INST]

    A leading system message is folded into the first user turn separated by
    a blank line (Mistral's reference template has no system role); a
    system-only dialog renders as a single instruction turn. A system message
    arriving after the first user turn would have to rewrite already-rendered
    history, so it is rejected.
    """
    system = ""
    turns: list[list] = []  # [user_text, assistant_text | None]
    for m in messages:
        if m.role is MessageRole.SYSTEM:
            if turns:
                raise ValueError(
                    "mistral template cannot place a system message after "
                    "the first user turn (no system role in the template)"
                )
            system = m.content.strip()
        elif m.role is MessageRole.USER:
            turns.append([m.content.strip(), None])
        else:
            if not turns:
                turns.append(["", None])
            turns[-1][1] = m.content.strip()
    if not turns and system:
        turns.append(["", None])  # system-only dialog: one instruction turn
    parts = ["<s>"]
    for i, (user, assistant) in enumerate(turns):
        if i == 0 and system:
            user = f"{system}\n\n{user}" if user else system
        parts.append(f"[INST] {user} [/INST]")
        if assistant is not None:
            parts.append(f"{assistant}</s>")
    return "".join(parts)


def encode_dialog_llama2(messages: list[Message]) -> str:
    """Llama-2-chat template (for Llama-2 checkpoints, whose config.json is
    indistinguishable from base Llama — select with ``--chat-template
    llama2``):

        <s>[INST] <<SYS>>\\n{system}\\n<</SYS>>\\n\\n{user} [/INST] {a} </s>...

    Same turn structure as Mistral with the <<SYS>> system block.
    """
    system = None
    turns: list[list] = []
    for m in messages:
        if m.role is MessageRole.SYSTEM:
            if turns:
                raise ValueError(
                    "llama2 template cannot place a system message after "
                    "the first user turn"
                )
            system = m.content.strip()
        elif m.role is MessageRole.USER:
            turns.append([m.content.strip(), None])
        else:
            if not turns:
                turns.append(["", None])
            turns[-1][1] = m.content.strip()
    if not turns and system is not None:
        turns.append(["", None])
    parts = []
    for i, (user, assistant) in enumerate(turns):
        if i == 0 and system is not None:
            user = f"<<SYS>>\n{system}\n<</SYS>>\n\n{user}"
        parts.append(f"<s>[INST] {user} [/INST]")
        if assistant is not None:
            parts.append(f" {assistant} </s>")
    return "".join(parts)


def encode_dialog_gemma(messages: list[Message]) -> str:
    """Gemma-family template:

        <bos><start_of_turn>{user|model}\\n{content}<end_of_turn>\\n ...
        <start_of_turn>model\\n                                (trailer)

    The assistant role is "model". HF's Gemma template REJECTS system
    messages; here a leading system message folds into the first user turn
    (friendlier for the OpenAI-style API; a mid-dialog system is an error).
    """
    system = ""
    parts = ["<bos>"]
    first_user_done = False
    for m in messages:
        if m.role is MessageRole.SYSTEM:
            if first_user_done:
                raise ValueError(
                    "gemma template cannot place a system message after "
                    "the first user turn"
                )
            system = m.content.strip()
            continue
        role = "model" if m.role is MessageRole.ASSISTANT else "user"
        content = m.content.strip()
        if role == "user" and not first_user_done:
            if system:
                content = f"{system}\n\n{content}"
            first_user_done = True
        parts.append(f"<start_of_turn>{role}\n{content}<end_of_turn>\n")
    if system and not first_user_done:
        parts.append(f"<start_of_turn>user\n{system}<end_of_turn>\n")
    parts.append("<start_of_turn>model\n")
    return "".join(parts)


def encode_dialog_phi3(messages: list[Message]) -> str:
    """Phi-3 template:

        <|system|>\n{sys}<|end|>\n<|user|>\n{u}<|end|>\n<|assistant|>\n...
    """
    parts = [
        f"<|{m.role.value}|>\n{m.content.strip()}<|end|>\n" for m in messages
    ]
    parts.append("<|assistant|>\n")
    return "".join(parts)


# Template key -> dialog encoder. The generator picks by
# config.dialog_template (the model family, or the --chat-template override);
# the Llama-3 encoder is the reference-parity surface (history.rs), the
# others are the family extensions.
DIALOG_ENCODERS = {
    "llama": encode_dialog_to_prompt,
    "llama3": encode_dialog_to_prompt,
    "llama2": encode_dialog_llama2,
    "qwen2": encode_dialog_chatml,
    "qwen2_moe": encode_dialog_chatml,
    "qwen3": encode_dialog_chatml_no_default_system,
    "qwen3_moe": encode_dialog_chatml_no_default_system,
    "chatml": encode_dialog_chatml,
    "mistral": encode_dialog_mistral,
    "mixtral": encode_dialog_mistral,  # Mixtral-Instruct uses the same template
    "gemma": encode_dialog_gemma,
    "gemma2": encode_dialog_gemma,
    "gemma3_text": encode_dialog_gemma,
    "phi3": encode_dialog_phi3,
}


def encode_dialog(messages: list[Message], model_type: str = "llama") -> str:
    try:
        enc = DIALOG_ENCODERS[model_type]
    except KeyError:
        raise ValueError(f"no chat template for model_type {model_type!r}")
    return enc(messages)
