"""Static-batch generation: B independent dialogs decoded in lockstep.

The reference serves strictly one request at a time (a global write lock,
api/mod.rs:76; batch dim always 1). The model stack here is batch-native, so
this module adds real throughput serving on top of it:

  * Prompts are **left-padded** to one 16-multiple bucket, so every row's last
    prompt token sits at the same slot and prefill/decode keep SCALAR slot
    offsets (one compiled shape per bucket, `write_layer` untouched).
  * Slot s of row r holds rope position ``s - pad_r``; pad slots rope/mask with
    a sentinel position so no query can ever attend a pad key (ops/attention.py
    masks by position comparison, which this composes with for free). Pad
    QUERY rows clamp to position 0 — they compute garbage nobody reads.
  * Decode runs the whole batch per step inside a fused ``lax.scan``
    (models/llama/fused.py pattern): forward -> per-row repeat penalty ->
    per-row sampling -> feed back, N tokens per dispatch. Rows that hit EOS
    keep computing (lockstep); the host truncates their streams — wasted work
    is bounded by the chunk size, and the batch ends early once every row is
    done.

Decode FLOPs per step grow ~linearly with B while HBM weight traffic stays
constant — on TPU, batched decode is nearly free throughput until the MXU
saturates, which is exactly why this exists beyond reference parity.

Attention dispatches like the single-row path (model.py): the Pallas decode
kernel takes per-row ``starts`` (= the left-pad counts), so each row reads
only its live [pad_r, slot] window — pad slots cost neither compute nor DMA.
Prefill runs the chunk kernel (ops/pallas/chunk_prefill.py) with
``k_starts=pads`` in slot space; the XLA einsum path (position-sentinel
masking) remains the CPU/debug fallback. Both carry the per-family window /
softcap / scale knobs.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.cache import KVCache, init_cache, write_layer
from cake_tpu.obs.jitwatch import tracked_jit as _tracked_jit
from cake_tpu.models.llama.paged_cache import (
    PagedKVCache,
    paged_write_layer,
)
from cake_tpu.models.llama.chat import Message, encode_dialog
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.fused import sampled_decode_scan
from cake_tpu.models.llama.generator import SamplingConfig
from cake_tpu.models.llama.tokenizer import Tokenizer
from cake_tpu.ops.attention import gqa_attention, gqa_attention_hm
from cake_tpu.ops.fuse import resolve_fusion
from cake_tpu.ops.pallas.chunk_prefill import chunk_prefill_attention
from cake_tpu.ops.pallas.decode_attention import decode_attention
from cake_tpu.ops.pallas.fused_ingest import fused_qkv_ingest
from cake_tpu.ops.pallas.paged_attention import (
    paged_decode_attention,
    paged_decode_attention_xla,
)
from cake_tpu.ops.pallas.paged_prefill import (
    paged_chunk_attention,
    paged_chunk_attention_xla,
    paged_kernel_supported,
)
from cake_tpu.ops.rope import model_rope_tables
from cake_tpu.ops.sampling import apply_repeat_penalty, sample, sample_per_row

# Far beyond any real position: a pad key's position compares greater than
# every query position, so the causal mask excludes it everywhere.
PAD_SENTINEL = np.int32(2**30)


@dataclasses.dataclass
class BatchResult:
    """One row's outcome."""

    text: str
    token_ids: list[int]
    finish_reason: str  # "stop" | "length"


BUCKET_MULTIPLE = 16


def prompt_bucket(longest: int, max_seq_len: int) -> int:
    """The shared left-pad bucket for a batch whose longest prompt is ``longest``.

    Rounds up to a 16-multiple, not a pow2: a pow2 bucket can burn up to
    longest-1 cache slots, collapsing the decode budget (max_seq_len - bucket)
    for prompts just past a boundary. One compile per distinct 16-multiple is
    acceptable for a batch entry point. Admission checks (serving.submit) must
    call this same helper so rejection agrees with the real layout.
    """
    return min(-(-longest // BUCKET_MULTIPLE) * BUCKET_MULTIPLE, max_seq_len)


def layout_prompts(
    ids_list: list[list[int]], max_seq_len: int
) -> tuple[np.ndarray, np.ndarray, int]:
    """Left-pad prompts into one shared bucket: (tokens [B, bucket], pads [B], bucket)."""
    longest = max(len(i) for i in ids_list)
    bucket = prompt_bucket(longest, max_seq_len)
    b = len(ids_list)
    tokens = np.zeros((b, bucket), np.int32)
    pads = np.zeros((b,), np.int32)
    for r, ids in enumerate(ids_list):
        pads[r] = bucket - len(ids)
        tokens[r, pads[r] :] = ids
    return tokens, pads, bucket


def first_sample(
    logits: jnp.ndarray,
    s,
    ring: np.ndarray,
    ring_idx: np.ndarray,
    row_keys: jax.Array | None,
):
    """Penalize + sample the FIRST post-prefill token and advance the rings.

    THE one definition of the first-token arithmetic (penalty, key split
    order, ring update) shared by lockstep_decode, the serving engine's epoch
    start, and its continuous-batching joins — so the bit-exactness oracle
    cannot drift between them. ``row_keys`` [B, 2] gives each row its own
    stream; None samples the batch from one stream seeded with ``s.seed``.

    Returns (first [B] np.int32, carried key(s), ring, ring_idx).
    """
    penalized = apply_repeat_penalty(logits, s.repeat_penalty, jnp.asarray(ring))
    if row_keys is None:
        key, sub = jax.random.split(jax.random.PRNGKey(s.seed))
        first = sample(penalized, sub, s.temperature, s.top_k, s.top_p)
    else:
        pair = jax.vmap(jax.random.split)(row_keys)
        key, sub = pair[:, 0], pair[:, 1]
        first = sample_per_row(penalized, sub, s.temperature, s.top_k, s.top_p)
    first = np.asarray(first).astype(np.int32)
    window = ring.shape[1]
    if window > 0:
        b = first.shape[0]
        ring[np.arange(b), ring_idx] = first
        ring_idx = (ring_idx + 1) % window
    return first, key, ring, ring_idx


def seed_rings(
    ids_list: list[list[int]], window: int
) -> tuple[np.ndarray, np.ndarray]:
    """Per-row repeat-penalty rings seeded from each prompt's tail.

    Returns (ring [B, window], ring_idx [B]) — each row's circular window
    behaves exactly like its single-sequence run (generator._penalty_window).
    """
    b = len(ids_list)
    ring = np.full((b, max(window, 0)), -1, np.int32)
    ring_idx = np.zeros((b,), np.int32)
    if window > 0:
        for r, ids in enumerate(ids_list):
            recent = ids[-window:]
            ring[r, : len(recent)] = recent
            ring_idx[r] = min(window, len(ids)) % window
    return ring, ring_idx


def _positions(slot_grid: jnp.ndarray, pads: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(q_positions, k_positions) for slots ``slot_grid`` with left-pads.

    q: pad slots clamp to 0 (finite garbage, unread). k: pad slots get the
    sentinel so they are never attended.
    """
    rel = slot_grid - pads[:, None]
    q_pos = jnp.maximum(rel, 0)
    k_pos = jnp.where(rel < 0, PAD_SENTINEL, rel)
    return q_pos, k_pos


def prefill_positions(
    width: int, pads: jnp.ndarray, ends: jnp.ndarray | None = None
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """The left-padded PREFILL position grids: (q_pos, k_pos) over [B, width].

    One definition of the slot-grid + pad + dead-tail (``ends`` for join
    windows) arithmetic, shared by the local prefill, the tp/pipeline
    shard_map bodies, and the distributed (TCP) worker ops — so the layout
    cannot drift between execution backends.
    """
    b = pads.shape[0]
    slot_grid = jnp.broadcast_to(
        jnp.arange(width, dtype=jnp.int32)[None, :], (b, width)
    )
    q_pos, k_pos = _positions(slot_grid, pads)
    if ends is not None:
        dead = slot_grid >= ends[:, None]
        k_pos = jnp.where(dead, PAD_SENTINEL, k_pos)
        q_pos = jnp.where(dead, 0, q_pos)
    return q_pos, k_pos


def decode_positions(
    slot: jnp.ndarray, pads: jnp.ndarray, max_seq: int
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """The left-padded DECODE position grids: (q_pos [B,1], k_pos [B,max_seq],
    lengths [B]) for one token at shared ``slot``. One definition shared by
    the local one-token closure, the tp/pipeline bodies, the 1F1B groups,
    and the distributed (TCP) worker op."""
    b = pads.shape[0]
    q_pos = (slot - pads)[:, None]  # slot >= bucket > pads: never a pad
    lengths = jnp.broadcast_to(slot + 1, (b,)).astype(jnp.int32)
    kv_slots = jnp.broadcast_to(
        jnp.arange(max_seq, dtype=jnp.int32)[None, :], (b, max_seq)
    )
    _, k_pos = _positions(kv_slots, pads)
    return q_pos, k_pos, lengths


def make_lockstep_range_ops(config: LlamaConfig, cos: jnp.ndarray, sin: jnp.ndarray):
    """(prefill, decode, join, verify) closures over a BARE stacked-layer range.

    The three lockstep ops a block-range executor needs — shared by the TCP
    worker's jits (runtime/worker.py) and the master's local-range jits
    (runtime/batch_backend.DistributedBatchBackend), so the two sides of the
    wire run literally the same code. Signatures:

      prefill(layers, x, kv, pads, ends)        -> (x, kv)   writes slot 0
      decode(layers, x, kv, pads, slot)         -> (x, kv)   one token at slot
      join(layers, x, kv, pads1, ends1, lane)   -> (x, kv)   single-row
          prefill into a fresh row cache, scattered wholesale into ``lane``
      verify(layers, x, kv, pads, slot)         -> (x, kv)   cached chunk at
          slot (speculative verify; MoE grouped path is exact without tp)
    """

    def bprefill(layers, x, kv, pads, ends):
        q_pos, k_pos = prefill_positions(x.shape[1], pads, ends)
        return batched_blocks_forward(
            layers, x, kv, cos, sin, q_pos, k_pos, config,
            decode=False, pads=pads, lengths=ends, write_pos=jnp.int32(0),
        )

    def bdecode(layers, x, kv, pads, slot):
        q_pos, k_pos, lengths = decode_positions(slot, pads, kv.k.shape[-2])
        return batched_blocks_forward(
            layers, x, kv, cos, sin, q_pos, k_pos, config,
            decode=True, pads=pads, lengths=lengths, write_pos=slot,
        )

    def bverify(layers, x, kv, pads, slot):
        q_pos, k_pos, lengths = verify_positions(
            x.shape[1], pads, slot, kv.k.shape[-2]
        )
        return batched_blocks_forward(
            layers, x, kv, cos, sin, q_pos, k_pos, config,
            decode=False, cached_chunk=True, pads=pads, lengths=lengths,
            write_pos=slot,
        )

    def bjoin(layers, x, kv, pads1, ends1, lane):
        kv_row = KVCache(
            k=jnp.zeros(kv.k.shape[:1] + (1,) + kv.k.shape[2:], kv.k.dtype),
            v=jnp.zeros(kv.v.shape[:1] + (1,) + kv.v.shape[2:], kv.v.dtype),
        )
        x, kv_row = bprefill(layers, x, kv_row, pads1, ends1)
        k = jax.lax.dynamic_update_slice(kv.k, kv_row.k, (0, lane, 0, 0, 0))
        v = jax.lax.dynamic_update_slice(kv.v, kv_row.v, (0, lane, 0, 0, 0))
        return x, KVCache(k=k, v=v)

    return bprefill, bdecode, bjoin, bverify


def verify_positions(
    width: int, pads: jnp.ndarray, slot: jnp.ndarray, max_seq: int
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Cached-chunk (speculative VERIFY) position grids: q_pos [B, width]
    at slots [slot, slot+width), mask-only full-grid k_pos, per-row lengths
    slot + width. One definition shared by batched_verify_logits, the
    pipeline verify walk, and the TCP worker verify op."""
    b = pads.shape[0]
    jgrid = slot + jnp.arange(width, dtype=jnp.int32)
    q_pos = jnp.broadcast_to(jgrid[None, :], (b, width)) - pads[:, None]
    _, k_pos, _ = decode_positions(slot, pads, max_seq)
    lengths = jnp.broadcast_to(slot + width, (b,)).astype(jnp.int32)
    return q_pos, k_pos, lengths


def batched_blocks_forward(
    layers: M.Params,
    x: jnp.ndarray,
    kv: KVCache,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    q_pos: jnp.ndarray,
    k_pos: jnp.ndarray,
    config: LlamaConfig,
    *,
    decode: bool,
    pads: jnp.ndarray,
    lengths: jnp.ndarray,
    write_pos: jnp.ndarray,
    valid: jnp.ndarray | None = None,
    tp_axis: str | None = None,
    allow_pallas: bool = True,
    row_offset: jnp.ndarray | None = None,
    cached_chunk: bool = False,
    moe_dispatch: str = "auto",
    block_tables: jnp.ndarray | None = None,
    write_starts: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, KVCache]:
    """THE pad-aware stacked-layer scan for left-padded batches.

    The batched counterpart of model.blocks_forward, shared by every batch
    execution backend — the local engine, the tensor-parallel shard_map body,
    and the pipelined stage bodies (runtime/batch_backend.py) — so the
    pad/mask/kernel dispatch arithmetic exists exactly once.

    Args:
      q_pos/k_pos: per-row RELATIVE rope/mask positions (_positions); pad
        slots carry the PAD_SENTINEL as keys and clamp to 0 as queries.
      decode: STATIC — one-token step at slot ``write_pos`` (True) vs
        full-width prefill writing at slot 0 (False).
      pads: [B] left-pad counts — the kernels' per-row front bound.
      lengths: [B] live-slot count per row (prefill: width or join ``ends``;
        decode: slot + 1) — the kernels' per-row pruning bound.
      valid: optional [n_layers] gate for ragged pipeline stages (inert
        padded layers), exactly like model.blocks_forward.
      tp_axis: mesh axis for the tensor-parallel partial-sum reductions.
      row_offset: optional TRACED start row — ``x`` then carries a WINDOW of
        ``b`` rows out of a wider cache (kv holds B_total >= b rows): reads
        slice the window per layer (the attention was going to read those
        rows anyway) and the new token's K/V writes land at the offset rows,
        so no block-sized write-back copy exists. This is what lets the 1F1B
        interleaved pipeline walk (runtime/batch_backend.py) run one
        microbatch GROUP per stage against the shared full-batch cache.
        Decode only; pads/q_pos/k_pos/lengths are already the window's rows.
      cached_chunk: STATIC — a multi-token chunk arriving at slot
        ``write_pos`` > 0 that must attend over the LIVE CACHE PREFIX (the
        batched analogue of model.forward's cached_prefill): speculative
        verify feeds [last, draft...] this way. Callers pass k_pos over the
        FULL cache grid and per-row ``lengths`` = write_pos + width.
      block_tables: optional [B, max_pages_per_seq] int32 — PAGED mode: ``kv``
        is then a PagedKVCache (models/llama/paged_cache.py) and every K/V
        write scatters through the table (unmapped entries drop). Decode reads
        dispatch to the ragged paged kernel (ops/pallas/paged_attention.py) or
        its gather fallback; fresh prefill attends over the FRESH chunk
        (identical arithmetic to the dense fresh-chunk path — prefill never
        re-reads the cache it just wrote, so no gather is needed); a paged
        CACHED chunk (``cached_chunk=True`` — the prefix-cache suffix prefill,
        runtime/prefix_cache.py) attends over the gathered pool view, the
        multi-query sibling of the paged decode XLA fallback. The
        position/mask grids are the SAME left-padded arithmetic as dense,
        sized to ``max_pages_per_seq * page_size`` slots. Speculative verify
        and the 1F1B row-window mode are dense-only.
      write_starts: optional [B] int32 (PAGED only) — row ``b``'s K/V writes
        at slots below ``write_starts[b]`` DROP even where pages are mapped:
        a suffix prefill's window re-embeds prefix tokens whose KV already
        lives in forked shared pages, and must never scribble them.
    """
    use_pallas = (
        allow_pallas and M.resolve_attention_impl(config.attention_impl) == "pallas"
    )
    # Decode hot-path op fusion (ops/fuse.py resolve_fusion): "norm" rides
    # inside block_qkv/block_finish, "ingest" replaces the decode branch's
    # split/rope/cache-write below; both gate their Pallas kernels on the
    # same allow_pallas knob as attention. Every fusion is bit-identical to
    # the unfused arithmetic (tests/test_fused_decode.py), so enabling one
    # never changes a stream — only which ops the step dispatches.
    fusion = resolve_fusion(config, allow_pallas)
    fusions, fimpl = fusion
    b = x.shape[0]
    if row_offset is not None:
        assert decode, "row-window execution is a decode-only mode"
    paged = block_tables is not None
    if paged:
        assert row_offset is None, "row-window decode is dense-only (paged)"
    else:
        assert write_starts is None, "write_starts is a paged-only mode"
    # Pad slots (sentinel key positions) must not consume MoE expert
    # capacity (ops/moe.py); decode/cached chunks carry no pads.
    moe_valid = None if (decode or cached_chunk) else (k_pos != PAD_SENTINEL)
    if cached_chunk and paged and write_starts is not None:
        # Suffix-prefill windows (runtime/prefix_cache.py, identified by
        # their write thresholds) CAN contain pad slots, unlike verify
        # windows (those sit past the bucket and keep the dense verify's
        # moe_valid=None so paged greedy speculation stays byte-identical
        # to paged plain decode): pad queries must not consume MoE expert
        # capacity, and their rope positions clamp to finite garbage
        # (outputs discarded, writes dropped by write_starts / unmapped
        # pages).
        moe_valid = q_pos >= 0
        q_pos = jnp.maximum(q_pos, 0)
    if decode:
        # Decode ropes q and its one new key at the same q_pos (k_pos only
        # feeds the XLA mask): gather the rope rows once per step, not once
        # per layer inside the scan (apply_rope's 3-D form). Prefill keeps
        # the tables — its keys rope at k_pos, distinct from q_pos.
        # Stacked dual-rope tables gather BOTH planes; block_qkv selects.
        if cos.ndim == 3:
            cos, sin = cos[:, q_pos], sin[:, q_pos]
        else:
            cos, sin = cos[q_pos], sin[q_pos]
    attn_kw = dict(
        window=config.sliding_window,
        scale=config.attn_scale,
        softcap=config.attn_logit_softcap,
    )
    # Cached chunks start their queries at the write slot (the kernel prunes
    # cache blocks causally from there); fresh prefills start at slot 0.
    q_starts = (
        jnp.broadcast_to(write_pos, (b,)).astype(jnp.int32)
        if cached_chunk
        else jnp.zeros((b,), jnp.int32)
    )

    def layer(carry, per_layer):
        x = carry
        lp, k_c, v_c, ok = per_layer
        use_ingest = (
            decode
            and "ingest" in fusions
            and "wqkv" in lp
            and "q_norm" not in lp
            and row_offset is None
        )
        if use_ingest:
            # Fused decode ingest (ops/pallas/fused_ingest.py): the flat
            # projection row goes through split + rope + cache write in one
            # kernel (dense slot DMA, or the paged variant with the block
            # table as scalar prefetch and paged_write_layer's UNMAPPED
            # drop). The decode rope rows are already pre-gathered above;
            # dual-rope layers select their plane here, exactly as
            # block_qkv would. q_norm layer trees (Qwen3 family) and the
            # 1F1B row-window mode keep the unfused path — bit-identical.
            qkv = M.block_qkv_flat(lp, x, config, fusion)
            cos_l = cos[lp["rope_sel"]] if "rope_sel" in lp else cos
            sin_l = sin[lp["rope_sel"]] if "rope_sel" in lp else sin
            n_q, n_kv = M.layer_head_counts(lp, config)
            q, k_c, v_c = fused_qkv_ingest(
                qkv, cos_l, sin_l, write_pos, k_c, v_c,
                n_q=n_q, n_kv=n_kv,
                block_tables=block_tables if paged else None,
                impl=fimpl,
            )
            k = v = None
        elif decode or cached_chunk:
            # The chunk's keys rope at the chunk's own positions (== q_pos);
            # the full-cache-grid k_pos is mask-only, exactly like decode.
            # Verify chunks never place a pad in [slot, slot+W), but the
            # batched draft ingest (speculative.BatchedDraftModelProposer)
            # DOES feed windows starting before some lanes' left pads:
            # those rows carry NEGATIVE q_pos, every key is masked for
            # them, and the all-masked-row guards in the attention paths
            # (ops/attention.gqa_attention_hm, the Pallas chunk kernel's
            # m_safe) zero the outputs — a LOAD-BEARING contract for that
            # caller; their sub-pad KV writes land at sub-pad slots that
            # stay sentinel-masked forever.
            q, k, v = M.block_qkv(lp, x, cos, sin, q_pos, config, fusion=fusion)
        else:
            q, k, v = M.block_qkv(
                lp, x, cos, sin, q_pos, config, k_positions=k_pos,
                fusion=fusion,
            )
        if paged:
            if not use_ingest:
                k_c, v_c = paged_write_layer(
                    k_c, v_c, k, v, write_pos, block_tables,
                    starts=write_starts,
                )
            # One eligibility rule for every paged kernel (decode AND the
            # chunk family): the page must be a whole number of lane tiles.
            # A backend that wanted pallas but lands here surfaces a
            # one-time `kernel-fallback` flight event host-side
            # (runtime/batch_backend.PagedLocalBackend._kernel_note).
            kernel_ok = use_pallas and paged_kernel_supported(k_c.shape[2])
            if decode:
                if kernel_ok:
                    attn = paged_decode_attention(
                        q, k_c, v_c, lengths, block_tables, pads,
                        lp.get("win_flag"), **attn_kw,
                    )
                else:
                    attn = paged_decode_attention_xla(
                        q, k_c, v_c, q_pos, k_pos, block_tables,
                        window_flag=lp.get("win_flag"), **attn_kw,
                    )
            elif cached_chunk:
                # Cached chunk at slot ``write_pos`` — the prefix-cache
                # suffix prefill AND the paged speculative verify: the
                # chunk's queries attend the LIVE POOL PREFIX (cached/
                # earlier pages plus the chunk's own writes just scattered
                # above). Pallas: the ragged page-resolving chunk kernel
                # (ops/pallas/paged_prefill.py) streams only live pages;
                # XLA: the gathered dense view, the multi-query form of
                # the paged decode fallback (bit-identical arithmetic).
                if kernel_ok:
                    attn = paged_chunk_attention(
                        q, k_c, v_c, q_starts, lengths, pads, block_tables,
                        lp.get("win_flag"), **attn_kw,
                    )
                else:
                    attn = paged_chunk_attention_xla(
                        q, k_c, v_c, q_pos, k_pos, block_tables,
                        window_flag=lp.get("win_flag"), **attn_kw,
                    )
            elif kernel_ok:
                # Fresh paged prefill under pallas: the chunk kernel reads
                # the pool prefix its own writes just produced (q_starts =
                # 0, so causal pruning touches exactly the live pages) —
                # no [chunk, chunk] score tensor, O(live) HBM bytes.
                attn = paged_chunk_attention(
                    q, k_c, v_c, q_starts, lengths, pads, block_tables,
                    lp.get("win_flag"), **attn_kw,
                )
            else:
                # Prefill attends over the chunk it just computed — the
                # dense fresh-chunk arithmetic, no cache read, no gather.
                attn = gqa_attention(
                    q, k, v, q_pos, k_pos,
                    window_flag=lp.get("win_flag"), **attn_kw,
                )
            x_new = M.block_finish(
                lp, x, attn, config, tp_axis=tp_axis, moe_valid=moe_valid,
                moe_dispatch=moe_dispatch, fusion=fusion,
            )
            x = x_new if valid is None else jnp.where(ok, x_new, x)
            return x, (k_c, v_c)
        if not use_ingest:
            k_c, v_c = write_layer(
                k_c, v_c, k, v, write_pos,
                row=0 if row_offset is None else row_offset,
            )
        if row_offset is not None:
            # Row-window mode: attention reads this group's rows only (the
            # same bytes the kernels were going to stream); writes above
            # already landed at the offset, so the full cache flows through
            # the scan untouched outside the window.
            k_att = jax.lax.dynamic_slice_in_dim(k_c, row_offset, b, axis=0)
            v_att = jax.lax.dynamic_slice_in_dim(v_c, row_offset, b, axis=0)
        else:
            k_att, v_att = k_c, v_c
        if use_pallas:
            # Kernel operands in SLOT space: left-padding shifts a row's
            # queries and keys equally, so causal/window comparisons are
            # pad-invariant; pad key slots are excluded via starts/k_starts
            # (mask + block pruning), dead tails via per-row lengths. Rope
            # still uses the relative positions above.
            if decode:
                attn = decode_attention(
                    q, k_att, v_att, lengths, pads, lp.get("win_flag"), **attn_kw
                )
            else:
                attn = chunk_prefill_attention(
                    q, k_att, v_att, q_starts, lengths, lp.get("win_flag"), pads,
                    **attn_kw,
                )
        elif decode or cached_chunk:
            # XLA fallback over the cache prefix: decode's one token, or a
            # cached chunk's width-many queries, both masked by the full-grid
            # k_pos the caller supplied.
            attn = gqa_attention_hm(
                q, k_att, v_att, q_pos, k_pos,
                window_flag=lp.get("win_flag"), **attn_kw,
            )
        else:
            attn = gqa_attention(
                q, k, v, q_pos, k_pos,
                window_flag=lp.get("win_flag"), **attn_kw,
            )
        x_new = M.block_finish(
            lp, x, attn, config, tp_axis=tp_axis, moe_valid=moe_valid,
            moe_dispatch=moe_dispatch, fusion=fusion,
        )
        x = x_new if valid is None else jnp.where(ok, x_new, x)
        return x, (k_c, v_c)

    ok = jnp.ones((kv.k.shape[0],), bool) if valid is None else valid
    x, (k_out, v_out) = jax.lax.scan(layer, x, (layers, kv.k, kv.v, ok))
    cls = PagedKVCache if paged else KVCache
    return x, cls(k=k_out, v=v_out)


def batched_prefill(
    params: M.Params,
    tokens: jnp.ndarray,  # [B, L] left-padded
    kv: KVCache,
    pads: jnp.ndarray,  # [B] left-pad counts
    config: LlamaConfig,
    ends: jnp.ndarray | None = None,  # [B] absolute end slot per row (< L ok)
    seq_len: jnp.ndarray | None = None,  # logits slot + 1; default L
    tp_axis: str | None = None,
) -> tuple[jnp.ndarray, KVCache]:
    """Prefill the padded batch at slots [0, L); logits at slot ``seq_len-1``.

    Row r's prompt occupies slots [pads[r], ends[r]); slots outside get the
    position sentinel so nothing ever attends them (trailing dead slots are
    overwritten by decode, the single-row convention). ``ends``/``seq_len``
    default to the full width L — the plain whole-batch prefill. A
    continuous-batching JOIN (runtime/serving.py) prefills one row whose
    prompt must END at the running batch's shared slot: its window is wider
    than the prompt, so ends < L and seq_len = ends. ``tp_axis`` makes the
    body shard_map-able (runtime/batch_backend.py TPBatchBackend).
    """
    b, l = tokens.shape
    cos, sin = model_rope_tables(config, kv.max_seq_len)
    x = M.embed_tokens(params, tokens, config)
    q_pos, k_pos = prefill_positions(l, pads, ends)
    if seq_len is None:
        seq_len = jnp.int32(l)
    lengths = jnp.broadcast_to(jnp.int32(l), (b,)) if ends is None else ends

    x, kv = batched_blocks_forward(
        params["layers"], x, kv, cos, sin, q_pos, k_pos, config,
        decode=False, pads=pads, lengths=lengths, write_pos=jnp.int32(0),
        tp_axis=tp_axis,
    )
    logits = M.head_forward(params, x, seq_len, config)
    return logits, kv


def batched_forward_one(
    params: M.Params,
    pads: jnp.ndarray,  # [B]
    config: LlamaConfig,
    max_seq: int,
    allow_pallas: bool = True,
    tp_axis: str | None = None,
):
    """Build the one-token batched forward closure for fused.sampled_decode_scan.

    The scan's carried ``pos`` is the SLOT of the fed token (shared across
    rows); per-row rope/mask positions are derived from the left-pads here.
    ``tp_axis`` makes the closure shard_map-able (TPBatchBackend).
    """
    cos, sin = model_rope_tables(config, max_seq)
    fusion = resolve_fusion(config, allow_pallas)

    def forward_one(tok, kv, slot):
        x = M.embed_tokens(params, tok, config)
        q_pos, k_pos, lengths = decode_positions(slot, pads, max_seq)
        x, kv = batched_blocks_forward(
            params["layers"], x, kv, cos, sin, q_pos, k_pos, config,
            decode=True, pads=pads, lengths=lengths, write_pos=slot,
            tp_axis=tp_axis, allow_pallas=allow_pallas,
        )
        logits = M.head_forward(params, x, jnp.int32(1), config, fusion=fusion)
        return logits, kv

    return forward_one


@functools.lru_cache(maxsize=16)
def _decode_fn(
    config: LlamaConfig,
    max_seq: int,
    n_steps: int,
    temperature: float,
    top_k,
    top_p,
    repeat_penalty: float,
    allow_pallas: bool = True,
):
    """Jit one fused batch-decode scan: the SAME step-agnostic harness as
    single-sequence fused decode (models/llama/fused.py) with the batched
    forward closure — sampling/ring/PRNG logic exists once. ``params`` and
    ``pads`` are traced arguments (NOT closure captures), so the compiled
    entry is reused across batches; batch-size changes retrace within it.
    The jit family name carries the fusion spec so tracked_jit attributes
    compile cost per fusion family (bench.py `fusion` section)."""
    fusions, fimpl = resolve_fusion(config, allow_pallas)
    tail_impl = fimpl if "tail" in fusions else None

    def run(params, kv, tok, slot, pads, key, ring, ring_idx):
        # kv.max_seq_len is the cache's PADDED length (SEQ_MULTIPLE rounding) —
        # the mask grid and rope table must size to it, not the user value.
        forward_one = batched_forward_one(
            params, pads, config, kv.max_seq_len, allow_pallas=allow_pallas
        )
        return sampled_decode_scan(
            forward_one,
            kv,
            tok,
            slot,
            key,
            ring,
            ring_idx,
            n_steps=n_steps,
            temperature=temperature,
            top_k=top_k,
            top_p=top_p,
            repeat_penalty=repeat_penalty,
            tail_impl=tail_impl,
        )

    fu = f",fu={config.fusion_impl}" if fusions else ""
    return _tracked_jit(
        run,
        name=(
            f"batch.decode[n={n_steps},t={temperature},k={top_k},"
            f"p={top_p},rp={repeat_penalty}{fu}]"
        ),
        donate_argnums=(1,),
    )


_prefill_jit = _tracked_jit(
    batched_prefill,
    name="batch.prefill",
    static_argnames=("config",),
    donate_argnames=("kv",),
)


# -------------------------------------------------------------------- paged
#
# The paged lockstep drivers: identical position/mask/sampling arithmetic to
# the dense entry points above (the dense-vs-paged bit-exactness oracle in
# tests/test_paged_serving.py depends on it) with KV routed through the page
# pool. The "sequence length" every grid sizes to is the table capacity
# ``max_pages_per_seq * page_size`` — the paged analogue of the dense cache's
# SEQ_MULTIPLE-padded max_seq.


def paged_seq_len(kv: PagedKVCache, block_tables: jnp.ndarray) -> int:
    """Slot capacity of a lane's block table: the paged ``max_seq``."""
    return int(block_tables.shape[1]) * kv.page_size


def paged_prefill(
    params: M.Params,
    tokens: jnp.ndarray,  # [B, L] left-padded
    kv: PagedKVCache,
    pads: jnp.ndarray,  # [B] left-pad counts
    block_tables: jnp.ndarray,  # [B, max_pages_per_seq] int32
    config: LlamaConfig,
    ends: jnp.ndarray | None = None,
    seq_len: jnp.ndarray | None = None,
    write_starts: jnp.ndarray | None = None,
    allow_pallas: bool = True,
) -> tuple[jnp.ndarray, PagedKVCache]:
    """batched_prefill through the page pool: row r's prompt KV lands in the
    pages its block-table row maps; writes outside the mapping drop (left-pad
    garbage costs no storage). ``ends``/``seq_len`` serve the continuous-
    batching join exactly as in the dense path. ``write_starts`` drops a
    row's sub-threshold writes — a prefix-cache warm row riding a cold
    epoch's full prefill recomputes its prefix in-window (same numerics as a
    cold row, so streams stay bit-identical) but must not scribble the
    shared pages already holding that prefix. ``allow_pallas`` (STATIC)
    force-disables the paged chunk kernel — attention_impl is honored
    uniformly with the decode twin paged_forward_one."""
    b, l = tokens.shape
    cos, sin = model_rope_tables(config, paged_seq_len(kv, block_tables))
    x = M.embed_tokens(params, tokens, config)
    q_pos, k_pos = prefill_positions(l, pads, ends)
    if seq_len is None:
        seq_len = jnp.int32(l)
    lengths = jnp.broadcast_to(jnp.int32(l), (b,)) if ends is None else ends

    x, kv = batched_blocks_forward(
        params["layers"], x, kv, cos, sin, q_pos, k_pos, config,
        decode=False, pads=pads, lengths=lengths, write_pos=jnp.int32(0),
        block_tables=block_tables, write_starts=write_starts,
        allow_pallas=allow_pallas,
    )
    logits = M.head_forward(params, x, seq_len, config)
    return logits, kv


def paged_forward_one(
    params: M.Params,
    pads: jnp.ndarray,
    block_tables: jnp.ndarray,
    config: LlamaConfig,
    padded_seq: int,
    allow_pallas: bool = True,
):
    """One-token paged forward closure for fused.sampled_decode_scan — the
    paged twin of batched_forward_one (same carried-slot convention)."""
    cos, sin = model_rope_tables(config, padded_seq)
    fusion = resolve_fusion(config, allow_pallas)

    def forward_one(tok, kv, slot):
        x = M.embed_tokens(params, tok, config)
        q_pos, k_pos, lengths = decode_positions(slot, pads, padded_seq)
        x, kv = batched_blocks_forward(
            params["layers"], x, kv, cos, sin, q_pos, k_pos, config,
            decode=True, pads=pads, lengths=lengths, write_pos=slot,
            allow_pallas=allow_pallas, block_tables=block_tables,
        )
        logits = M.head_forward(params, x, jnp.int32(1), config, fusion=fusion)
        return logits, kv

    return forward_one


@functools.lru_cache(maxsize=16)
def _paged_decode_fn(
    config: LlamaConfig,
    padded_seq: int,
    n_steps: int,
    temperature: float,
    top_k,
    top_p,
    repeat_penalty: float,
    allow_pallas: bool = True,
):
    """Jit one fused PAGED batch-decode scan: the _decode_fn harness with the
    block table as an extra traced operand (it changes at chunk boundaries —
    joins, page growth, releases — without retracing)."""
    fusions, fimpl = resolve_fusion(config, allow_pallas)
    tail_impl = fimpl if "tail" in fusions else None

    def run(params, kv, tok, slot, pads, block_tables, key, ring, ring_idx):
        forward_one = paged_forward_one(
            params, pads, block_tables, config, padded_seq,
            allow_pallas=allow_pallas,
        )
        return sampled_decode_scan(
            forward_one,
            kv,
            tok,
            slot,
            key,
            ring,
            ring_idx,
            n_steps=n_steps,
            temperature=temperature,
            top_k=top_k,
            top_p=top_p,
            repeat_penalty=repeat_penalty,
            tail_impl=tail_impl,
        )

    fu = f",fu={config.fusion_impl}" if fusions else ""
    return _tracked_jit(
        run,
        name=(
            f"batch.paged_decode[n={n_steps},t={temperature},k={top_k},"
            f"p={top_p},rp={repeat_penalty}{fu}]"
        ),
        donate_argnums=(1,),
    )


_paged_prefill_jit = _tracked_jit(
    paged_prefill,
    name="batch.paged_prefill",
    static_argnames=("config", "allow_pallas"),
    donate_argnames=("kv",),
)


def paged_suffix_prefill(
    params: M.Params,
    tokens: jnp.ndarray,  # [B, W] window covering slots [start, start + W)
    kv: PagedKVCache,
    pads: jnp.ndarray,  # [B] TRUE left pads (absolute; may lie outside window)
    write_starts: jnp.ndarray,  # [B] first slot each row may write
    block_tables: jnp.ndarray,
    config: LlamaConfig,
    start: jnp.ndarray,  # window's first absolute slot
    allow_pallas: bool = True,
) -> tuple[jnp.ndarray, PagedKVCache]:
    """Warm-path prefill: compute ONLY the window [start, start + W), with
    each row's prefix KV below ``write_starts[b]`` served from forked
    prefix-cache pages instead of recomputed (runtime/prefix_cache.py).

    The cached-chunk analogue of the speculative verify grids: queries carry
    their absolute-slot rope positions, keys are the FULL gathered pool view
    masked positionally, and writes below each row's fresh threshold drop so
    shared pages stay byte-stable. Window rows below a row's own fresh
    region recompute prefix-tail activations whose outputs are discarded
    (their writes drop) — correct by the same induction that makes the pool
    a valid oracle: the gathered prefix IS the values a full prefill would
    have produced. Logits land at the window's last slot (the epoch's shared
    ``bucket - 1``), exactly where the cold path reads them.
    """
    b, w = tokens.shape
    capacity = paged_seq_len(kv, block_tables)
    cos, sin = model_rope_tables(config, capacity)
    x = M.embed_tokens(params, tokens, config)
    q_pos, k_pos, lengths = verify_positions(w, pads, start, capacity)
    x, kv = batched_blocks_forward(
        params["layers"], x, kv, cos, sin, q_pos, k_pos, config,
        decode=False, cached_chunk=True, pads=pads, lengths=lengths,
        write_pos=start, block_tables=block_tables,
        write_starts=write_starts, allow_pallas=allow_pallas,
    )
    logits = M.head_forward(params, x, jnp.int32(w), config)
    return logits, kv


_paged_suffix_jit = _tracked_jit(
    paged_suffix_prefill,
    name="batch.paged_suffix",
    static_argnames=("config", "allow_pallas"),
    donate_argnames=("kv",),
)


def paged_verify_logits(
    params: M.Params,
    tokens: jnp.ndarray,  # [B, W] = [last_r, draft_r..., pad 0s]
    kv: PagedKVCache,
    pads: jnp.ndarray,
    slot: jnp.ndarray,
    block_tables: jnp.ndarray,
    config: LlamaConfig,
    allow_pallas: bool = True,
) -> tuple[jnp.ndarray, PagedKVCache]:
    """batched_verify_logits through the page pool: the SAME cached-chunk
    arithmetic as paged_suffix_prefill (verify grids, keys masked
    positionally over the pool view, writes through the block table at
    slots [slot, slot + W)) scoring every position: [B, W, vocab] f32.

    This is what enables speculative decoding under ``kv_mode="paged"``:
    greedy verify logits are bit-identical to the paged plain-decode path
    on CPU (the dense proof pattern), so accepted tokens byte-match the
    non-speculative stream. The engine must map pages for [slot, slot + W)
    BEFORE the round (runtime/serving.py extends at the chunk boundary) —
    an unmapped slot would silently drop the chunk's KV.
    """
    b, w = tokens.shape
    capacity = paged_seq_len(kv, block_tables)
    cos, sin = model_rope_tables(config, capacity)
    x = M.embed_tokens(params, tokens, config)
    q_pos, k_pos, lengths = verify_positions(w, pads, slot, capacity)
    x, kv = batched_blocks_forward(
        params["layers"], x, kv, cos, sin, q_pos, k_pos, config,
        decode=False, cached_chunk=True, pads=pads, lengths=lengths,
        write_pos=slot, block_tables=block_tables,
        allow_pallas=allow_pallas,
    )
    return M.head_forward_all(params, x, config), kv


@functools.lru_cache(maxsize=8)
def _paged_verify_greedy_fn(config: LlamaConfig, width: int, allow_pallas=True):
    """Jit one greedy PAGED batched verify per (config, width): the dense
    _verify_greedy_fn harness with the block table as a traced operand."""

    def run(params, tokens, kv, pads, slot, block_tables):
        logits, kv = paged_verify_logits(
            params, tokens, kv, pads, slot, block_tables, config,
            allow_pallas=allow_pallas,
        )
        return verify_greedy_ids(logits), kv

    return _tracked_jit(
        run, name=f"batch.paged_verify_greedy[w={width}]", donate_argnums=(2,)
    )


@functools.lru_cache(maxsize=8)
def _paged_verify_sampled_fn(
    config: LlamaConfig,
    width: int,
    temperature: float,
    top_k,
    top_p,
    allow_pallas=True,
):
    """Jit one sampled PAGED batched verify per (config, width, knobs)."""

    def run(params, tokens, kv, pads, slot, block_tables, drafts, n_drafts, keys):
        logits, kv = paged_verify_logits(
            params, tokens, kv, pads, slot, block_tables, config,
            allow_pallas=allow_pallas,
        )
        n_accs, nxts, keys = verify_sampled_accept(
            logits, drafts, n_drafts, keys, temperature, top_k, top_p
        )
        return n_accs, nxts, kv, keys

    return _tracked_jit(
        run,
        name=(
            f"batch.paged_verify_sampled[w={width},t={temperature},"
            f"k={top_k},p={top_p}]"
        ),
        donate_argnums=(2,),
    )


# ---------------------------------------------------------------- speculative
#
# Batched prompt-lookup speculative decoding for the serving engine
# (runtime/serving.py): every row verifies ITS OWN drafted chunk inside ONE
# shared forward over [B, K+1] tokens at the epoch's shared slot, then the
# batch advances by the MINIMUM accepted length across live rows — the
# left-padded lockstep invariants (shared slot, per-row front pads) all hold,
# rows' surplus accepted tokens are simply re-verified next round, and
# rejected-tail KV sits at future-masked slots until overwritten. Greedy rows
# stay byte-identical to plain decode; sampled rows keep the exact
# plain-decode distribution (speculative.sampled_accept per row — emitting a
# PREFIX of an exact process is exact).


def batched_verify_logits(
    params: M.Params,
    tokens: jnp.ndarray,  # [B, W] = [last_r, draft_r..., pad 0s]
    kv: KVCache,
    pads: jnp.ndarray,
    slot: jnp.ndarray,
    config: LlamaConfig,
    tp_axis: str | None = None,
) -> tuple[jnp.ndarray, KVCache]:
    """One cached-chunk forward scoring every position: [B, W, vocab] f32.

    KV for the whole chunk is written at slots [slot, slot + W); callers
    advance the shared slot by the accepted length and let later writes
    overwrite the rejected tail (the single-row convention, speculative.py).
    """
    b, w = tokens.shape
    cos, sin = model_rope_tables(config, kv.max_seq_len)
    x = M.embed_tokens(params, tokens, config)
    q_pos, k_pos, lengths = verify_positions(w, pads, slot, kv.max_seq_len)
    x, kv = batched_blocks_forward(
        params["layers"], x, kv, cos, sin, q_pos, k_pos, config,
        decode=False, cached_chunk=True, pads=pads, lengths=lengths,
        write_pos=slot, tp_axis=tp_axis,
        # Verify chunks must be drop-free: force the dense MoE combine
        # (greedy speculation promises byte-exact streams; ops/moe.py).
        moe_dispatch="dense" if tp_axis is not None else "auto",
    )
    return M.head_forward_all(params, x, config), kv


def verify_greedy_ids(logits: jnp.ndarray) -> jnp.ndarray:
    """Greedy acceptance input: argmax ids [B, W] on device (no logit ship).
    ONE definition shared by the local and tp verify builders."""
    return jnp.argmax(logits, -1).astype(jnp.int32)


def verify_sampled_accept(
    logits: jnp.ndarray,  # [B, W, vocab]
    drafts: jnp.ndarray,  # [B, K]
    n_drafts: jnp.ndarray,  # [B]
    keys: jax.Array,  # [B, 2]
    temperature: float,
    top_k,
    top_p,
):
    """Per-row rejection acceptance on device: vmaps
    speculative.sampled_accept over rows with per-row keys — the single-
    stream acceptance rule, so the per-position marginal stays exactly the
    plain-decode distribution for every row. ONE definition shared by the
    local and tp verify builders. Returns (n_accs [B], nxts [B], keys)."""
    from cake_tpu.models.llama.speculative import sampled_accept

    accept = jax.vmap(
        lambda lg, d, nd, k: sampled_accept(
            lg, d, nd, k, temperature, top_k, top_p
        )
    )
    return accept(logits, drafts, n_drafts, keys)


@functools.lru_cache(maxsize=8)
def _verify_greedy_fn(config: LlamaConfig, width: int):
    """Jit one greedy batched verify per (config, width)."""

    def run(params, tokens, kv, pads, slot):
        logits, kv = batched_verify_logits(
            params, tokens, kv, pads, slot, config
        )
        return verify_greedy_ids(logits), kv

    return _tracked_jit(
        run, name=f"batch.verify_greedy[w={width}]", donate_argnums=(2,)
    )


@functools.lru_cache(maxsize=8)
def _verify_sampled_fn(
    config: LlamaConfig,
    width: int,
    temperature: float,
    top_k,
    top_p,
):
    """Jit one sampled batched verify per (config, width, sampling knobs)."""

    def run(params, tokens, kv, pads, slot, drafts, n_drafts, keys):
        logits, kv = batched_verify_logits(
            params, tokens, kv, pads, slot, config
        )
        n_accs, nxts, keys = verify_sampled_accept(
            logits, drafts, n_drafts, keys, temperature, top_k, top_p
        )
        return n_accs, nxts, kv, keys

    return _tracked_jit(
        run,
        name=(
            f"batch.verify_sampled[w={width},t={temperature},"
            f"k={top_k},p={top_p}]"
        ),
        donate_argnums=(2,),
    )


def lockstep_decode(
    config: LlamaConfig,
    params: M.Params,
    ids_list: list[list[int]],
    s: SamplingConfig,
    *,
    max_seq_len: int,
    cache_dtype,
    decode_chunk_size: int,
    on_tokens,
    row_keys: jax.Array | None = None,
    mesh=None,
) -> None:
    """THE lockstep batch driver: prefill, first sample, chunked fused decode.

    Used by BatchGenerator (one-shot batches); the serving engine
    (runtime/serving.py) owns its own loop for continuous admission but
    shares the parity-critical pieces — layout_prompts, seed_rings,
    first_sample, _prefill_jit, _decode_fn — so the arithmetic exists once. After the first token ([B, 1]) and each
    decode chunk ([B, n]), ``on_tokens(toks)`` receives the raw sampled ids and
    returns True to continue; the driver itself stops only at the cache edge.
    Chunks are always full ``decode_chunk_size`` (host-side truncation handles
    budgets/EOS) — one fused trace, never one per tail length.

    ``row_keys`` = None samples the whole batch from one stream keyed by
    ``s.seed``; a [B, 2] array gives each row its OWN stream (serving's
    reproducibility contract — see ops/sampling.sample_per_row).

    ``mesh`` (a 1-D Mesh over a "dp" axis) shards the BATCH axis across
    devices — data-parallel lockstep decode: rows are independent, so every
    [B, ...] array (tokens, pads, KV cache, rings, keys) carries P("dp") and
    GSPMD partitions the whole prefill + decode with zero collectives.
    Params must already be replicated on the mesh by the caller.
    """
    b = len(ids_list)
    tokens, pads, bucket = layout_prompts(ids_list, max_seq_len)
    kv = init_cache(
        config.num_hidden_layers,
        b,
        max_seq_len,
        config.num_key_value_heads,
        config.head_dim,
        cache_dtype,
    )
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        def place(a, *axes):
            return jax.device_put(a, NamedSharding(mesh, P(*axes)))
    else:
        def place(a, *axes):
            return a

    pads_j = place(jnp.asarray(pads), "dp")
    tokens_j = place(jnp.asarray(tokens), "dp")
    kv = place(kv, None, "dp")
    if row_keys is not None:
        row_keys = place(row_keys, "dp")
    logits, kv = _prefill_jit(params, tokens_j, kv, pads_j, config)

    window = s.repeat_last_n
    ring, ring_idx = seed_rings(ids_list, window)
    first, key, ring, ring_idx = first_sample(logits, s, ring, ring_idx, row_keys)

    cap = max_seq_len - bucket  # cache slots available for generated tokens
    if not on_tokens(first[:, None]) or cap <= 1:
        return

    tok = place(jnp.asarray(first), "dp")
    slot = bucket  # slot of the most recent token
    ring_j = place(jnp.asarray(ring), "dp")
    produced = 1
    while produced < cap:
        n = min(decode_chunk_size, cap - produced)
        fn = _decode_fn(
            config,
            max_seq_len,
            n,
            s.temperature,
            s.top_k,
            s.top_p,
            s.repeat_penalty,
            # GSPMD cannot auto-partition a Mosaic custom call over the dp
            # mesh (only the shard_map backends hand-place kernels); the dp
            # path stays on the XLA decode attention.
            allow_pallas=mesh is None,
        )
        toks, kv, key, ring_j, ring_idx_j = fn(
            params,
            kv,
            tok,
            jnp.int32(slot),
            pads_j,
            key,
            ring_j,
            jnp.asarray(ring_idx),
        )
        ring_idx = np.asarray(ring_idx_j)
        cont = on_tokens(np.asarray(toks))
        tok = toks[:, -1]
        slot += n
        produced += n
        if not cont:
            return


class BatchGenerator:
    """Generate completions for B dialogs at once (single-process).

    One prefill + fused lockstep decode; per-row EOS truncation on host. Unlike
    LlamaGenerator this is stateless per call — each ``generate`` is a fresh
    batch with its own KV cache.
    """

    def __init__(
        self,
        config: LlamaConfig,
        params: M.Params,
        tokenizer: Tokenizer,
        sampling: SamplingConfig = SamplingConfig(),
        *,
        max_seq_len: int | None = None,
        cache_dtype: jnp.dtype = jnp.bfloat16,
        decode_chunk_size: int = 8,
        dp: int | None = None,
    ):
        from cake_tpu.ops.fuse import fuse_params

        self.config = config
        self.params = fuse_params(params)  # ops/fuse.py, column-identical
        self.tokenizer = tokenizer
        self.sampling = sampling
        self.max_seq_len = int(max_seq_len or config.max_position_embeddings)
        self.cache_dtype = cache_dtype
        self.decode_chunk_size = max(1, decode_chunk_size)
        # Data parallelism: rows sharded over a 1-D "dp" mesh — independent
        # sequences, so the lockstep decode partitions with zero collectives
        # (params replicated once here; batches must divide by dp).
        self.mesh = None
        if dp is not None and dp > 1:
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

            devs = jax.devices()
            if len(devs) < dp:
                raise ValueError(f"dp={dp} needs {dp} devices, have {len(devs)}")
            self.mesh = Mesh(np.array(devs[:dp]), ("dp",))
            self.params = jax.device_put(
                self.params, NamedSharding(self.mesh, P())
            )

    def generate(
        self, dialogs: list[list[Message]], max_new_tokens: int
    ) -> list[BatchResult]:
        if not dialogs or max_new_tokens <= 0:
            return [
                BatchResult(text="", token_ids=[], finish_reason="length")
                for _ in dialogs
            ]
        s = self.sampling
        ids_list = [
            self.tokenizer.encode(encode_dialog(d, self.config.dialog_template))
            for d in dialogs
        ]
        longest = max(len(i) for i in ids_list)
        if longest >= self.max_seq_len:
            raise ValueError(
                f"longest prompt ({longest} tokens) exceeds max_seq_len "
                f"{self.max_seq_len}"
            )
        b = len(ids_list)
        if self.mesh is not None and b % self.mesh.shape["dp"]:
            raise ValueError(
                f"batch of {b} rows does not divide over dp="
                f"{self.mesh.shape['dp']} (pad the batch or drop dp)"
            )
        eos = set(self.config.eos_token_ids)
        generated: list[list[int]] = [[] for _ in range(b)]
        done = np.zeros(b, bool)

        def on_tokens(toks: np.ndarray) -> bool:
            for r in range(b):
                if done[r]:
                    continue
                for t in toks[r]:
                    generated[r].append(int(t))
                    if int(t) in eos or len(generated[r]) >= max_new_tokens:
                        done[r] = True
                        break
            return not done.all()

        lockstep_decode(
            self.config,
            self.params,
            ids_list,
            s,
            max_seq_len=self.max_seq_len,
            cache_dtype=self.cache_dtype,
            decode_chunk_size=self.decode_chunk_size,
            on_tokens=on_tokens,
            mesh=self.mesh,
        )

        results = []
        for r in range(b):
            ids = generated[r]
            stopped = bool(ids and ids[-1] in eos)
            text_ids = ids[:-1] if stopped else ids
            results.append(
                BatchResult(
                    text=self.tokenizer.decode(text_ids),
                    token_ids=ids,
                    finish_reason="stop" if stopped else "length",
                )
            )
        return results
