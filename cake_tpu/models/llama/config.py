"""Llama model configuration.

Parses the HuggingFace ``config.json`` schema, covering the same field subset the
reference framework reads (reference: cake-core/src/models/llama3/config.rs:13-26,
45-58) plus the fields needed for Llama 3.1+ rope scaling.

Unlike the reference (which hard-caps MAX_SEQ_LEN at 4096, config.rs:6), the max
sequence length here is a runtime choice: ``max_position_embeddings`` from the
checkpoint is the default ceiling, and callers size their KV caches explicitly.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Any


@dataclasses.dataclass(frozen=True)
class RopeScaling:
    """Llama 3.1-style rope frequency scaling (absent => plain RoPE)."""

    factor: float = 8.0
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_position_embeddings: int = 8192
    rope_type: str = "llama3"


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    """Architecture hyperparameters for a Llama-family decoder-only model.

    "Family" is wider than the reference's Llama-3-only scope: the same
    decoder core (RMSNorm -> GQA+RoPE -> gated MLP) runs Llama 3.x, Qwen2/2.5
    (QKV bias), Mistral (sliding window, explicit head_dim), Mixtral and
    Qwen2-MoE (sparse MoE), Gemma and Gemma-2 (GeGLU, (1+w) norms, embedding
    scale, soft-caps, alternating window), and Phi-3 (fused checkpoint
    tensors), dispatched by HF ``model_type`` — each pinned against
    transformers (tests/test_model_families.py, test_moe.py, test_gemma.py).
    """

    hidden_size: int = 4096
    intermediate_size: int = 14336
    vocab_size: int = 128256
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    rms_norm_eps: float = 1e-5
    rope_theta: float = 500000.0
    max_position_embeddings: int = 8192
    bos_token_id: int = 128000
    eos_token_ids: tuple[int, ...] = (128001, 128009)
    tie_word_embeddings: bool = False
    rope_scaling: RopeScaling | None = None
    # HF model_type: "llama", "qwen2", or "mistral" — selects the chat
    # template (chat.py) and defaults; the decoder core is shared.
    model_type: str = "llama"
    # Qwen2: q/k/v projections carry a bias (o_proj does not).
    attention_bias: bool = False
    # Mistral: keys/values further than this behind the query are masked.
    # None = full causal. The preallocated cache still stores the whole
    # sequence (no rolling buffer); the window is enforced by masking.
    sliding_window: int | None = None
    # Mistral-Nemo style: head_dim decoupled from hidden_size // heads.
    head_dim_override: int | None = None
    # Qwen3 / Gemma-3: per-head RMSNorm on q and k after projection, before
    # RoPE (head_dim-wide weights q_norm/k_norm in every layer; Gemma-3's
    # use the (1+w) offset convention via rmsnorm_offset).
    qk_norm: bool = False
    # Gemma-3 dual rope: sliding layers rope at this theta (unscaled), full
    # layers at rope_theta (+ rope_scaling). None = single rope.
    rope_local_base_freq: float | None = None
    # Per-layer sliding flags (Gemma-3 layer_types 5:1 pattern). None = the
    # family default (gemma2's even/odd comes from alt_sliding_window).
    sliding_pattern: tuple[bool, ...] | None = None
    # Sparse MoE (Mixtral / Qwen2-MoE): 0 = dense MLP; > 0 = number of
    # experts, with num_experts_per_tok of them combined per token
    # (ops/moe.py).
    num_local_experts: int = 0
    num_experts_per_tok: int = 2
    # Renormalize the top-k routing probabilities to sum 1 (Mixtral always
    # does; Qwen2-MoE ships norm_topk_prob, usually false).
    norm_topk_prob: bool = True
    # Qwen2-MoE: experts use their own intermediate size (None = the dense
    # intermediate_size, as in Mixtral) and an always-on shared expert with
    # a learned sigmoid gate.
    moe_intermediate_size: int | None = None
    shared_expert_intermediate_size: int | None = None
    # Gemma family knobs. hidden_activation: the MLP gate activation ("silu"
    # = SwiGLU everywhere else, "gelu_tanh" = Gemma's GeGLU). rmsnorm_offset:
    # norm weights stored zero-centered, applied as (1 + w).
    # embedding_scale: embeddings multiplied by sqrt(hidden) after lookup.
    hidden_activation: str = "silu"
    rmsnorm_offset: bool = False
    embedding_scale: float | None = None
    # Gemma-2 extras: tanh soft-capping of attention scores / final logits,
    # an attention scale decoupled from head_dim (query_pre_attn_scalar),
    # post-attention/post-MLP norms, and the alternating local/global window
    # pattern (even layers sliding, odd global).
    attn_logit_softcap: float | None = None
    final_logit_softcap: float | None = None
    query_pre_attn_scalar: int | None = None
    post_block_norms: bool = False
    alt_sliding_window: bool = False
    # Attention kernel selection: "auto" uses the Pallas kernels
    # (ops/pallas/{flash,decode}_attention.py) on TPU and the XLA einsum path
    # elsewhere; "pallas"/"xla" force one (tests force both for parity checks).
    attention_impl: str = "auto"
    # Decode hot-path op fusion (ops/fuse.py parse_fusion_spec): "none", or
    # "<set>[@impl]" with set ⊆ {norm, ingest, tail} (or "all") selecting
    # which op fusions run, and impl ∈ {auto, pallas, xla} selecting the
    # kernels vs their XLA twins ("auto" = pallas on TPU). Every fusion is
    # bit-identical to the unfused path; like attention_impl this is a
    # runtime knob, never an HF field.
    fusion_impl: str = "none"
    # Chat-template override (--chat-template; not an HF field). None = pick
    # by model_type. Needed for Llama-2-chat checkpoints, whose config.json
    # is indistinguishable from base Llama (chat.DIALOG_ENCODERS keys).
    chat_template: str | None = None

    @property
    def dialog_template(self) -> str:
        return self.chat_template or self.model_type

    @property
    def attn_scale(self) -> float | None:
        """Score scale override (None = head_dim**-0.5): THE one mapping of
        Gemma-2's query_pre_attn_scalar, shared by every execution backend."""
        if self.query_pre_attn_scalar is None:
            return None
        return float(self.query_pre_attn_scalar) ** -0.5

    @property
    def head_dim(self) -> int:
        if self.head_dim_override is not None:
            return self.head_dim_override
        return self.hidden_size // self.num_attention_heads

    @property
    def num_query_groups(self) -> int:
        """Query heads per KV head (GQA group size)."""
        return self.num_attention_heads // self.num_key_value_heads

    def __post_init__(self) -> None:
        if self.head_dim_override is None and (
            self.hidden_size % self.num_attention_heads
        ):
            raise ValueError(
                f"hidden_size {self.hidden_size} not divisible by "
                f"num_attention_heads {self.num_attention_heads} "
                "(set head_dim explicitly in config.json to decouple them)"
            )
        if self.num_attention_heads % self.num_key_value_heads:
            raise ValueError(
                f"num_attention_heads {self.num_attention_heads} not divisible by "
                f"num_key_value_heads {self.num_key_value_heads}"
            )

    @classmethod
    def from_hf_dict(cls, d: dict[str, Any]) -> "LlamaConfig":
        """Build from a parsed HF ``config.json`` dict.

        Mirrors the normalization in the reference's ``LlamaConfig::into_config``
        (config.rs:45-58): missing ``num_key_value_heads`` falls back to MHA, rope
        theta defaults, and eos may be a scalar or a list.
        """
        eos = d.get("eos_token_id", 128001)
        if isinstance(eos, int):
            eos_ids: tuple[int, ...] = (eos,)
        else:
            eos_ids = tuple(int(e) for e in eos)
        heads = int(d.get("num_attention_heads", 32))
        rs = None
        raw_rs = d.get("rope_scaling")
        if raw_rs and raw_rs.get("rope_type", raw_rs.get("type")) == "linear":
            # Plain linear frequency scaling (Gemma-3 global rope).
            rs = RopeScaling(
                factor=float(raw_rs.get("factor", 8.0)), rope_type="linear"
            )
        if raw_rs and raw_rs.get("rope_type", raw_rs.get("type")) == "llama3":
            rs = RopeScaling(
                factor=float(raw_rs.get("factor", 8.0)),
                low_freq_factor=float(raw_rs.get("low_freq_factor", 1.0)),
                high_freq_factor=float(raw_rs.get("high_freq_factor", 4.0)),
                original_max_position_embeddings=int(
                    raw_rs.get("original_max_position_embeddings", 8192)
                ),
            )
        model_type = str(d.get("model_type", "llama"))
        if model_type not in (
            "llama", "qwen2", "mistral", "mixtral", "qwen2_moe",
            "gemma", "gemma2", "phi3", "qwen3", "qwen3_moe", "gemma3_text",
        ):
            if model_type == "gemma3":
                raise ValueError(
                    "model_type 'gemma3' is the MULTIMODAL wrapper config; "
                    "use a text-only checkpoint (model_type 'gemma3_text') — "
                    "its fields live under the wrapper's text_config"
                )
            raise ValueError(
                f"unsupported model_type {model_type!r} (supported: llama, "
                "qwen2, mistral, mixtral, qwen2_moe, gemma, gemma2, phi3, "
                "qwen3, qwen3_moe, gemma3_text)"
            )
        if model_type == "phi3" and d.get("rope_scaling"):
            # Phi-3 128k variants use longrope (per-dim su-scaled factors);
            # only the base-rope variants (4k/8k) are supported.
            raise ValueError(
                "phi3 rope_scaling (longrope) is not supported; use a "
                "base-context Phi-3 checkpoint"
            )
        if model_type in ("qwen2_moe", "qwen3_moe"):
            # Layers can individually opt out of MoE via these knobs; only
            # the uniform all-sparse shape (every shipped Qwen-MoE model)
            # is supported — mixed dense/sparse stacks are an explicit error.
            if int(d.get("decoder_sparse_step", 1)) != 1 or d.get(
                "mlp_only_layers"
            ):
                raise ValueError(
                    f"{model_type} with decoder_sparse_step != 1 or "
                    "mlp_only_layers needs per-layer dense/sparse mixing, "
                    "which this framework does not support"
                )
        n_layers = int(d.get("num_hidden_layers", 32))
        sliding_pattern = None
        if model_type == "gemma3_text":
            lt = d.get("layer_types")
            if lt is None:
                # Real checkpoints often ship only sliding_window_pattern
                # (default 6): every pattern-th layer is full attention.
                # Built over n_layers so the pattern length always matches
                # the actual stack depth.
                swp = int(d.get("sliding_window_pattern", 6))
                lt = [
                    "full_attention"
                    if swp > 0 and (i + 1) % swp == 0
                    else "sliding_attention"
                    for i in range(n_layers)
                ]
            sliding_pattern = tuple(t == "sliding_attention" for t in lt)
        head_dim = d.get("head_dim")
        if head_dim is None and model_type in ("qwen3", "qwen3_moe", "gemma3_text"):
            # HF class defaults regardless of hidden_size/heads (the
            # honor-the-class-default rule): Qwen3 128, Gemma3 256.
            head_dim = 256 if model_type == "gemma3_text" else 128
        hidden = int(d.get("hidden_size", 4096))
        if head_dim is not None and int(head_dim) * heads == hidden:
            head_dim = None  # redundant with the derived value
        sw = d.get("sliding_window")
        # Qwen2 ships sliding_window in config.json but gates it off with
        # use_sliding_window (default false) — honor the gate. When on,
        # transformers applies the window only to layers >= max_window_layers;
        # the common shipped shape (max_window_layers == num_hidden_layers)
        # means NO layer is windowed. Per-layer windows aren't supported here,
        # so the mixed shape is an explicit error rather than wrong numerics.
        if model_type in ("qwen2", "qwen2_moe", "qwen3", "qwen3_moe"):
            if not d.get("use_sliding_window", False):
                sw = None
            else:
                mwl = int(d.get("max_window_layers", n_layers))
                if mwl >= n_layers:
                    sw = None  # threshold never reached: full causal everywhere
                elif mwl > 0:
                    raise ValueError(
                        f"qwen2 max_window_layers={mwl} < num_hidden_layers="
                        f"{n_layers} needs per-layer sliding windows, which "
                        "this framework does not support"
                    )
        if model_type == "gemma3_text" and sw is None:
            sw = 4096  # HF Gemma3TextConfig class default
        # Explicit null is treated like absence (HF default 5632), but an
        # explicit 0 means "shared expert disabled" and must survive parsing
        # (model.py gates the shared-expert weights on truthiness).
        se_size = d.get("shared_expert_intermediate_size", 5632)
        se_size = 5632 if se_size is None else int(se_size)
        return cls(
            hidden_size=hidden,
            intermediate_size=int(d.get("intermediate_size", 14336)),
            vocab_size=int(d.get("vocab_size", 128256)),
            num_hidden_layers=int(d.get("num_hidden_layers", 32)),
            num_attention_heads=heads,
            num_key_value_heads=int(d.get("num_key_value_heads", heads)),
            rms_norm_eps=float(d.get("rms_norm_eps", 1e-5)),
            rope_theta=float(d.get("rope_theta", 10000.0)),
            max_position_embeddings=int(d.get("max_position_embeddings", 8192)),
            bos_token_id=int(d.get("bos_token_id", 128000)),
            eos_token_ids=eos_ids,
            tie_word_embeddings=bool(
                # Gemma ties embeddings BY DEFAULT, so its config.json omits
                # the field (it matches the HF base default of True).
                d.get(
                    "tie_word_embeddings",
                    model_type in ("gemma", "gemma2", "gemma3_text"),
                )
            ),
            rope_scaling=rs,
            model_type=model_type,
            attention_bias=bool(
                d.get("attention_bias", model_type in ("qwen2", "qwen2_moe"))
            ),
            sliding_window=None if sw is None else int(sw),
            head_dim_override=None if head_dim is None else int(head_dim),
            num_local_experts=(
                int(d.get("num_local_experts", 8))
                if model_type == "mixtral"
                else int(d.get("num_experts", 60))
                if model_type == "qwen2_moe"
                else int(d.get("num_experts", 128))
                if model_type == "qwen3_moe"
                else 0
            ),
            num_experts_per_tok=int(
                # HF defaults differ by family: Mixtral 2, Qwen2-MoE 4,
                # Qwen3-MoE 8.
                d.get(
                    "num_experts_per_tok",
                    {"qwen2_moe": 4, "qwen3_moe": 8}.get(model_type, 2),
                )
            ),
            norm_topk_prob=bool(
                # HF class defaults: Mixtral always renormalizes; BOTH Qwen
                # MoE configs default False (shipped Qwen3-MoE checkpoints
                # set True explicitly — honor the field, not the brand).
                d.get(
                    "norm_topk_prob",
                    model_type not in ("qwen2_moe", "qwen3_moe"),
                )
            ),
            moe_intermediate_size=(
                int(d["moe_intermediate_size"])
                if model_type in ("qwen2_moe", "qwen3_moe")
                and "moe_intermediate_size" in d
                else None
            ),
            qk_norm=model_type in ("qwen3", "qwen3_moe", "gemma3_text"),
            rope_local_base_freq=(
                float(d.get("rope_local_base_freq", 10000.0))
                if model_type == "gemma3_text"
                else None
            ),
            sliding_pattern=sliding_pattern,
            shared_expert_intermediate_size=(
                se_size if model_type == "qwen2_moe" else None
            ),
            hidden_activation=(
                "gelu_tanh"
                if model_type in ("gemma", "gemma2", "gemma3_text")
                else "silu"
            ),
            rmsnorm_offset=model_type in ("gemma", "gemma2", "gemma3_text"),
            embedding_scale=(
                float(hidden) ** 0.5
                if model_type in ("gemma", "gemma2", "gemma3_text")
                else None
            ),
            attn_logit_softcap=(
                float(d["attn_logit_softcapping"])
                if model_type == "gemma2"
                and d.get("attn_logit_softcapping") is not None
                else None
            ),
            final_logit_softcap=(
                float(d["final_logit_softcapping"])
                if model_type == "gemma2"
                and d.get("final_logit_softcapping") is not None
                else None
            ),
            query_pre_attn_scalar=(
                int(d.get("query_pre_attn_scalar") or 256)
                if model_type in ("gemma2", "gemma3_text")
                else None
            ),
            post_block_norms=model_type in ("gemma2", "gemma3_text"),
            alt_sliding_window=model_type == "gemma2",
        )

    @classmethod
    def from_model_dir(
        cls, model_dir: str | Path, *, attention_impl: str | None = None
    ) -> "LlamaConfig":
        """Load ``config.json`` from a model directory (config.rs:28-42).

        ``attention_impl`` overrides the kernel choice (not an HF field, so it
        never comes from the checkpoint; "auto"/None keeps the default).
        """
        path = Path(model_dir) / "config.json"
        with open(path) as f:
            config = cls.from_hf_dict(json.load(f))
        # Real instruct checkpoints carry their FULL stop-token list in
        # generation_config.json (Llama-3-Instruct: [128001, 128008, 128009]
        # there, while config.json says just 128001 — without the merge,
        # generation would run through <|eot_id|> instead of stopping, the
        # behavior transformers gets from GenerationConfig). Union, config
        # ids first. The reference reads config.json only (config.rs:13-26)
        # and so inherits exactly this bug on instruct checkpoints.
        gen_path = Path(model_dir) / "generation_config.json"
        if gen_path.exists():
            with open(gen_path) as f:
                gen_eos = json.load(f).get("eos_token_id")
            if gen_eos is not None:
                if isinstance(gen_eos, int):
                    gen_eos = [gen_eos]
                merged = list(config.eos_token_ids)
                merged += [int(e) for e in gen_eos if int(e) not in merged]
                config = dataclasses.replace(
                    config, eos_token_ids=tuple(merged)
                )
        if attention_impl not in (None, "auto"):
            if attention_impl not in ("pallas", "xla"):
                raise ValueError(f"unknown attention_impl {attention_impl!r}")
            config = dataclasses.replace(config, attention_impl=attention_impl)
        return config

    @classmethod
    def tiny(cls, **overrides: Any) -> "LlamaConfig":
        """A minuscule config for tests (random weights, CPU-friendly)."""
        kw: dict[str, Any] = dict(
            hidden_size=64,
            intermediate_size=128,
            vocab_size=512,
            num_hidden_layers=4,
            num_attention_heads=4,
            num_key_value_heads=2,
            rms_norm_eps=1e-5,
            rope_theta=10000.0,
            max_position_embeddings=256,
            # Special ids match tokenizer.ByteTokenizer (256 = begin_of_text,
            # 259 = eot, 260 = end_of_text).
            bos_token_id=256,
            eos_token_ids=(259, 260),
        )
        kw.update(overrides)
        return cls(**kw)

    def to_hf_dict(self) -> dict[str, Any]:
        arch = {
            "llama": "LlamaForCausalLM",
            "qwen2": "Qwen2ForCausalLM",
            "mistral": "MistralForCausalLM",
            "mixtral": "MixtralForCausalLM",
            "qwen2_moe": "Qwen2MoeForCausalLM",
            "gemma": "GemmaForCausalLM",
            "gemma2": "Gemma2ForCausalLM",
            "gemma3_text": "Gemma3ForCausalLM",
            "phi3": "Phi3ForCausalLM",
            "qwen3": "Qwen3ForCausalLM",
            "qwen3_moe": "Qwen3MoeForCausalLM",
        }[self.model_type]
        d: dict[str, Any] = {
            "architectures": [arch],
            "model_type": self.model_type,
            "hidden_size": self.hidden_size,
            "intermediate_size": self.intermediate_size,
            "vocab_size": self.vocab_size,
            "num_hidden_layers": self.num_hidden_layers,
            "num_attention_heads": self.num_attention_heads,
            "num_key_value_heads": self.num_key_value_heads,
            "rms_norm_eps": self.rms_norm_eps,
            "rope_theta": self.rope_theta,
            "max_position_embeddings": self.max_position_embeddings,
            "bos_token_id": self.bos_token_id,
            "eos_token_id": list(self.eos_token_ids)
            if len(self.eos_token_ids) > 1
            else self.eos_token_ids[0],
            "tie_word_embeddings": self.tie_word_embeddings,
        }
        # Emitted unconditionally: from_hf_dict defaults attention_bias by
        # family (True for qwen2), so omitting a False would flip on reload.
        d["attention_bias"] = self.attention_bias
        if self.sliding_window is not None:
            d["sliding_window"] = self.sliding_window
            if self.model_type in ("qwen2", "qwen2_moe", "qwen3", "qwen3_moe"):
                d["use_sliding_window"] = True
                # All layers windowed; without this, from_hf_dict's default
                # (max_window_layers = num_hidden_layers) gates the window off.
                d["max_window_layers"] = 0
        if self.head_dim_override is not None:
            d["head_dim"] = self.head_dim_override
        if self.num_local_experts:
            if self.model_type in ("qwen2_moe", "qwen3_moe"):
                d["num_experts"] = self.num_local_experts
                d["norm_topk_prob"] = self.norm_topk_prob
                if self.moe_intermediate_size is not None:
                    d["moe_intermediate_size"] = self.moe_intermediate_size
                if self.shared_expert_intermediate_size is not None:
                    d["shared_expert_intermediate_size"] = (
                        self.shared_expert_intermediate_size
                    )
            else:
                d["num_local_experts"] = self.num_local_experts
            d["num_experts_per_tok"] = self.num_experts_per_tok
        if self.model_type in ("gemma", "gemma2"):
            d["hidden_activation"] = "gelu_pytorch_tanh"
            d["head_dim"] = self.head_dim
        if self.model_type == "gemma2":
            d["attn_logit_softcapping"] = self.attn_logit_softcap
            d["final_logit_softcapping"] = self.final_logit_softcap
            d["query_pre_attn_scalar"] = self.query_pre_attn_scalar
        if self.model_type == "gemma3_text":
            d["rope_local_base_freq"] = self.rope_local_base_freq
            d["query_pre_attn_scalar"] = self.query_pre_attn_scalar
            d["head_dim"] = self.head_dim
            if self.sliding_pattern is not None:
                d["layer_types"] = [
                    "sliding_attention" if f else "full_attention"
                    for f in self.sliding_pattern
                ]
        if self.rope_scaling is not None and self.rope_scaling.rope_type == "linear":
            d["rope_scaling"] = {
                "rope_type": "linear",
                "factor": self.rope_scaling.factor,
            }
        elif self.rope_scaling is not None:
            d["rope_scaling"] = {
                "rope_type": "llama3",
                "factor": self.rope_scaling.factor,
                "low_freq_factor": self.rope_scaling.low_freq_factor,
                "high_freq_factor": self.rope_scaling.high_freq_factor,
                "original_max_position_embeddings": (
                    self.rope_scaling.original_max_position_embeddings
                ),
            }
        return d
