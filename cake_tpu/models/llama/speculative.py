"""Prompt-lookup speculative decoding (greedy).

Pure perf feature beyond the reference (it decodes strictly one token per
forward). Single-stream TPU decode is HBM-bound: one forward over K+1 tokens
reads the same weights as one token's forward, so if K drafted tokens verify,
the step produces K+1 tokens for ~one token's cost.

Drafts come from **prompt lookup** (no draft model): find the most recent
earlier occurrence of the current n-gram suffix in the token history and
propose the tokens that followed it. Repetitive spans — quoting the prompt,
code, structured output — verify at high rates; adversarial drafts cost one
wasted chunk and nothing else.

Verification feeds [last_token, draft_0..draft_{K-1}] through ONE chunked
forward (the cached-prefill attention variant) and reads logits at every
position (model.forward_all_logits). Greedy acceptance: the longest prefix
where argmax(logits[i]) == draft[i]; position of the first mismatch yields the
CORRECTED token from the same logits — so the emitted stream is exactly the
greedy stream, draft quality only affects speed. Rejected tail KV sits past
the live length (masked dead slots) and is overwritten as decoding proceeds.

Two acceptance modes share the one verify forward:

  * **Greedy** (temperature == 0): longest prefix where argmax(logits[i]) ==
    draft[i]; the emitted stream is byte-identical to plain greedy decode.
  * **Sampled** (temperature > 0): rejection sampling against the SAME
    filtered distribution plain decode samples from (ops/sampling._filter:
    temperature -> top-k -> top-p, then categorical). The prompt-lookup
    proposal is a point mass at the drafted token, so the Leviathan rule
    reduces to: accept d_i with probability p_i(d_i); on the first rejection
    sample the correction from p_i renormalized without d_i (the residual
    max(p - q, 0) of a point-mass q); after a full accept draw the bonus
    token from p_K. The marginal at every position is exactly p_i — draft
    quality affects only speed, never the distribution
    (tests/test_speculative.py pins this empirically).

Both keep repeat_penalty == 1.0 (a penalty makes the target history-dependent
within the chunk; the generator gates applicability).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.config import LlamaConfig


def propose_lookup(
    tokens: list[int], k: int, max_ngram: int = 3, min_ngram: int = 1
) -> list[int]:
    """Propose up to ``k`` draft tokens by prompt lookup.

    Finds the longest n-gram (max_ngram down to min_ngram) equal to the
    current suffix that also occurs earlier in ``tokens``, preferring the most
    recent occurrence, and returns the tokens that followed it. Empty list if
    no match — callers fall back to plain decode.
    """
    n = len(tokens)
    if n < min_ngram + 1 or k <= 0:
        return []
    arr = np.asarray(tokens, np.int32)
    for size in range(min(max_ngram, n - 1), min_ngram - 1, -1):
        suffix = arr[n - size :]
        # Vectorized most-recent-earlier-occurrence scan: windows over
        # arr[:-1] end at start n-1-size, so the suffix's own occurrence at
        # n-size is excluded by construction. O(n) in C per step.
        windows = np.lib.stride_tricks.sliding_window_view(arr[:-1], size)
        idxs = np.flatnonzero((windows == suffix).all(axis=1))
        for start in idxs[::-1]:
            follow = tokens[start + size : start + size + k]
            if follow:
                return follow
    return []


@functools.lru_cache(maxsize=8)
def _verify_fn(config: LlamaConfig, width: int):
    """Jit one chunked verify forward per (config, draft width).

    Returns GREEDY ids [b, width] (argmax on device) — shipping the full
    [b, width, vocab] f32 logits to host would cost ~width * vocab * 4 bytes
    per step against the very overhead speculation removes."""

    def run(params, tokens, kv, pos):
        logits, kv = M.forward_all_logits(
            params, tokens, kv, pos, config, cached_prefill=True
        )
        return jnp.argmax(logits, -1).astype(jnp.int32), kv

    return jax.jit(run, donate_argnums=(2,))


def sampled_accept(
    logits: jnp.ndarray,
    draft: jnp.ndarray,
    n_draft: jnp.ndarray,
    key: jax.Array,
    temperature: float,
    top_k: int | None,
    top_p: float | None,
) -> tuple[jnp.ndarray, jnp.ndarray, jax.Array]:
    """Rejection-sample a verify chunk against the target distribution.

    Args:
      logits: [width, vocab] RAW f32 logits — logits[i] is the target
        distribution for the token AFTER chunk position i (width = K + 1).
      draft: [K] int32 drafted ids (pad slots arbitrary).
      n_draft: traced scalar count of REAL drafts (pads never accept — a pad
        is not a proposal, so the chain stops there with a plain sample).
      key: PRNG key; consumed and re-split (returned).
      temperature/top_k/top_p: STATIC sampling knobs — must be the ones plain
        decode uses so the target distribution is identical.

    Returns (n_accepted, next_token, new_key): emit draft[:n_accepted] then
    next_token (the residual-sampled correction at the first rejection, or
    the bonus/plain sample when the whole real draft accepted).
    """
    from cake_tpu.ops.sampling import _filter

    k = draft.shape[0]
    filtered = _filter(logits.astype(jnp.float32), temperature, top_k, top_p)
    probs = jax.nn.softmax(filtered, axis=-1)
    key, k_u, k_cat = jax.random.split(key, 3)
    u = jax.random.uniform(k_u, (k,))
    p_d = probs[jnp.arange(k), draft]
    acc = (u < p_d) & (jnp.arange(k) < n_draft)
    n_acc = jnp.where(jnp.all(acc), jnp.int32(k), jnp.argmin(acc).astype(jnp.int32))
    row = filtered[n_acc]
    # A REAL rejection samples the residual (target minus the point-mass
    # proposal): zero the rejected id and let categorical renormalize. A
    # pad-stop or full accept samples the target itself. Rejection implies
    # p(d) < 1, so the residual is never empty.
    rejected_id = draft[jnp.minimum(n_acc, k - 1)]
    residual = row.at[rejected_id].set(-jnp.inf)
    row = jnp.where(n_acc < n_draft, residual, row)
    nxt = jax.random.categorical(k_cat, row).astype(jnp.int32)
    return n_acc, nxt, key


@functools.lru_cache(maxsize=8)
def _sampled_verify_fn(
    config: LlamaConfig,
    width: int,
    temperature: float,
    top_k: int | None,
    top_p: float | None,
):
    """Jit one chunked sampled-verify per (config, width, sampling knobs):
    forward + filter + accept + residual/bonus sample, all on device — only
    two scalars and the carried key come back to the host."""

    def run(params, tokens, kv, pos, draft, n_draft, key):
        logits, kv = M.forward_all_logits(
            params, tokens, kv, pos, config, cached_prefill=True
        )
        n_acc, nxt, key = sampled_accept(
            logits[0], draft, n_draft, key, temperature, top_k, top_p
        )
        return n_acc, nxt, kv, key

    return jax.jit(run, donate_argnums=(2,))


@functools.lru_cache(maxsize=8)
def _sampled_head_fn(
    config: LlamaConfig,
    temperature: float,
    top_k: int | None,
    top_p: float | None,
):
    """Head-side sampled accept for the distributed master (runtime/master.py):
    the stage walk produces activations; this jit finishes head_forward_all +
    acceptance on the master's device."""

    def run(head, x, draft, n_draft, key):
        logits = M.head_forward_all(head, x, config)
        return sampled_accept(
            logits[0], draft, n_draft, key, temperature, top_k, top_p
        )

    return jax.jit(run)


def greedy_accept(draft: np.ndarray, argmaxes: np.ndarray) -> tuple[int, int]:
    """Longest accepted prefix + the corrected/next token.

    argmaxes[i] is the greedy continuation AFTER position i of the fed chunk
    [last, d_0, .., d_{K-1}]; draft[i] == argmaxes[i] accepts d_i. Returns
    (n_accepted, next_token) where next_token is argmaxes[n_accepted] — the
    correction at the first mismatch, or the bonus token after a full accept.
    """
    n = 0
    while n < len(draft) and int(draft[n]) == int(argmaxes[n]):
        n += 1
    return n, int(argmaxes[n])
