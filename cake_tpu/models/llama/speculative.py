"""Prompt-lookup speculative decoding (greedy).

Pure perf feature beyond the reference (it decodes strictly one token per
forward). Single-stream TPU decode is HBM-bound: one forward over K+1 tokens
reads the same weights as one token's forward, so if K drafted tokens verify,
the step produces K+1 tokens for ~one token's cost.

Drafts come from **prompt lookup** (no draft model): find the most recent
earlier occurrence of the current n-gram suffix in the token history and
propose the tokens that followed it. Repetitive spans — quoting the prompt,
code, structured output — verify at high rates; adversarial drafts cost one
wasted chunk and nothing else.

Verification feeds [last_token, draft_0..draft_{K-1}] through ONE chunked
forward (the cached-prefill attention variant) and reads logits at every
position (model.forward_all_logits). Greedy acceptance: the longest prefix
where argmax(logits[i]) == draft[i]; position of the first mismatch yields the
CORRECTED token from the same logits — so the emitted stream is exactly the
greedy stream, draft quality only affects speed. Rejected tail KV sits past
the live length (masked dead slots) and is overwritten as decoding proceeds.

Two acceptance modes share the one verify forward:

  * **Greedy** (temperature == 0): longest prefix where argmax(logits[i]) ==
    draft[i]; the emitted stream is byte-identical to plain greedy decode.
  * **Sampled** (temperature > 0): rejection sampling against the SAME
    filtered distribution plain decode samples from (ops/sampling._filter:
    temperature -> top-k -> top-p, then categorical). The prompt-lookup
    proposal is a point mass at the drafted token, so the Leviathan rule
    reduces to: accept d_i with probability p_i(d_i); on the first rejection
    sample the correction from p_i renormalized without d_i (the residual
    max(p - q, 0) of a point-mass q); after a full accept draw the bonus
    token from p_K. The marginal at every position is exactly p_i — draft
    quality affects only speed, never the distribution
    (tests/test_speculative.py pins this empirically).

Both keep repeat_penalty == 1.0 (a penalty makes the target history-dependent
within the chunk; the generator gates applicability).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.config import LlamaConfig


def propose_lookup(
    tokens: list[int], k: int, max_ngram: int = 3, min_ngram: int = 1
) -> list[int]:
    """Propose up to ``k`` draft tokens by prompt lookup.

    Finds the longest n-gram (max_ngram down to min_ngram) equal to the
    current suffix that also occurs earlier in ``tokens``, preferring the most
    recent occurrence, and returns the tokens that followed it. Empty list if
    no match — callers fall back to plain decode.
    """
    n = len(tokens)
    if n < min_ngram + 1 or k <= 0:
        return []
    arr = np.asarray(tokens, np.int32)
    for size in range(min(max_ngram, n - 1), min_ngram - 1, -1):
        suffix = arr[n - size :]
        # Vectorized most-recent-earlier-occurrence scan: windows over
        # arr[:-1] end at start n-1-size, so the suffix's own occurrence at
        # n-size is excluded by construction. O(n) in C per step.
        windows = np.lib.stride_tricks.sliding_window_view(arr[:-1], size)
        idxs = np.flatnonzero((windows == suffix).all(axis=1))
        for start in idxs[::-1]:
            follow = tokens[start + size : start + size + k]
            if follow:
                return follow
    return []


@functools.lru_cache(maxsize=8)
def _verify_fn(config: LlamaConfig, width: int):
    """Jit one chunked verify forward per (config, draft width).

    Returns GREEDY ids [b, width] (argmax on device) — shipping the full
    [b, width, vocab] f32 logits to host would cost ~width * vocab * 4 bytes
    per step against the very overhead speculation removes."""

    def run(params, tokens, kv, pos):
        logits, kv = M.forward_all_logits(
            params, tokens, kv, pos, config, cached_prefill=True
        )
        return jnp.argmax(logits, -1).astype(jnp.int32), kv

    from cake_tpu.obs.jitwatch import tracked_jit

    return tracked_jit(
        run, name=f"spec.verify[w={width}]", donate_argnums=(2,)
    )


def sampled_accept(
    logits: jnp.ndarray,
    draft: jnp.ndarray,
    n_draft: jnp.ndarray,
    key: jax.Array,
    temperature: float,
    top_k: int | None,
    top_p: float | None,
) -> tuple[jnp.ndarray, jnp.ndarray, jax.Array]:
    """Rejection-sample a verify chunk against the target distribution.

    Args:
      logits: [width, vocab] RAW f32 logits — logits[i] is the target
        distribution for the token AFTER chunk position i (width = K + 1).
      draft: [K] int32 drafted ids (pad slots arbitrary).
      n_draft: traced scalar count of REAL drafts (pads never accept — a pad
        is not a proposal, so the chain stops there with a plain sample).
      key: PRNG key; consumed and re-split (returned).
      temperature/top_k/top_p: STATIC sampling knobs — must be the ones plain
        decode uses so the target distribution is identical.

    Returns (n_accepted, next_token, new_key): emit draft[:n_accepted] then
    next_token (the residual-sampled correction at the first rejection, or
    the bonus/plain sample when the whole real draft accepted).
    """
    from cake_tpu.ops.sampling import _filter

    k = draft.shape[0]
    filtered = _filter(logits.astype(jnp.float32), temperature, top_k, top_p)
    probs = jax.nn.softmax(filtered, axis=-1)
    key, k_u, k_cat = jax.random.split(key, 3)
    u = jax.random.uniform(k_u, (k,))
    p_d = probs[jnp.arange(k), draft]
    acc = (u < p_d) & (jnp.arange(k) < n_draft)
    n_acc = jnp.where(jnp.all(acc), jnp.int32(k), jnp.argmin(acc).astype(jnp.int32))
    row = filtered[n_acc]
    # A REAL rejection samples the residual (target minus the point-mass
    # proposal): zero the rejected id and let categorical renormalize. A
    # pad-stop or full accept samples the target itself. Rejection implies
    # p(d) < 1, so the residual is never empty.
    rejected_id = draft[jnp.minimum(n_acc, k - 1)]
    residual = row.at[rejected_id].set(-jnp.inf)
    row = jnp.where(n_acc < n_draft, residual, row)
    nxt = jax.random.categorical(k_cat, row).astype(jnp.int32)
    return n_acc, nxt, key


@functools.lru_cache(maxsize=8)
def _sampled_verify_fn(
    config: LlamaConfig,
    width: int,
    temperature: float,
    top_k: int | None,
    top_p: float | None,
):
    """Jit one chunked sampled-verify per (config, width, sampling knobs):
    forward + filter + accept + residual/bonus sample, all on device — only
    two scalars and the carried key come back to the host."""

    def run(params, tokens, kv, pos, draft, n_draft, key):
        logits, kv = M.forward_all_logits(
            params, tokens, kv, pos, config, cached_prefill=True
        )
        n_acc, nxt, key = sampled_accept(
            logits[0], draft, n_draft, key, temperature, top_k, top_p
        )
        return n_acc, nxt, kv, key

    from cake_tpu.obs.jitwatch import tracked_jit

    return tracked_jit(
        run,
        name=(
            f"spec.verify_sampled[w={width},t={temperature},"
            f"k={top_k},p={top_p}]"
        ),
        donate_argnums=(2,),
    )


@functools.lru_cache(maxsize=8)
def _sampled_head_fn(
    config: LlamaConfig,
    temperature: float,
    top_k: int | None,
    top_p: float | None,
):
    """Head-side sampled accept for the distributed master (runtime/master.py):
    the stage walk produces activations; this jit finishes head_forward_all +
    acceptance on the master's device."""

    def run(head, x, draft, n_draft, key):
        logits = M.head_forward_all(head, x, config)
        return sampled_accept(
            logits[0], draft, n_draft, key, temperature, top_k, top_p
        )

    return jax.jit(run)


def greedy_accept(draft: np.ndarray, argmaxes: np.ndarray) -> tuple[int, int]:
    """Longest accepted prefix + the corrected/next token.

    argmaxes[i] is the greedy continuation AFTER position i of the fed chunk
    [last, d_0, .., d_{K-1}]; draft[i] == argmaxes[i] accepts d_i. Returns
    (n_accepted, next_token) where next_token is argmaxes[n_accepted] — the
    correction at the first mismatch, or the bonus token after a full accept.
    """
    n = 0
    while n < len(draft) and int(draft[n]) == int(argmaxes[n]):
        n += 1
    return n, int(argmaxes[n])


# ------------------------------------------------------------------ proposers
#
# The drafting seam: anything with ``propose(tokens, k) -> list[int]`` can
# feed the verify machinery — correctness NEVER depends on the proposal
# (greedy acceptance re-derives the exact stream; sampled acceptance keeps
# the exact distribution), so proposers trade only speed. ``propose_lookup``
# (above) is the zero-cost default; ``DraftModelProposer`` runs a small
# model for free-generation text where the history has no n-gram signal.


class LookupProposer:
    """Prompt-lookup drafting (the stateless default)."""

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram

    def propose(self, tokens: list[int], k: int) -> list[int]:
        return propose_lookup(tokens, k, self.max_ngram, self.min_ngram)


class DraftModelProposer:
    """Two-model speculative drafting: a small decoder proposes K tokens.

    TPU shape: each round is TWO device dispatches — one chunked cached-
    prefill ingesting the tokens accepted since the last round (bucketed
    widths bound the compile count), one fused greedy scan proposing the
    remaining K-1 drafts. The draft keeps its own preallocated KV cache and
    resyncs to ANY token stream by longest-common-prefix (causal attention:
    a slot's KV depends only on preceding tokens, so rewinding is just
    overwriting) — generator resets, engine lane joins, and recovery replays
    all land on the same resync path, no invalidation protocol needed.

    Drafts are proposals only: garbage KV past the live prefix (tail pads of
    a bucketed ingest, rejected drafts) is future-masked and overwritten by
    the next ingest, and the TARGET's verify forward is what the emitted
    stream comes from.
    """

    def __init__(
        self,
        config: LlamaConfig,
        params,
        *,
        max_seq_len: int,
        cache_dtype=None,
    ):
        from cake_tpu.models.llama.cache import init_cache

        self.config = config
        self.params = params
        self.max_seq_len = int(max_seq_len)
        self._kv = init_cache(
            config.num_hidden_layers,
            1,
            self.max_seq_len,
            config.num_key_value_heads,
            config.head_dim,
            cache_dtype if cache_dtype is not None else jnp.bfloat16,
        )
        self._hist: list[int] = []

    @classmethod
    def load(
        cls,
        model_dir,
        *,
        dtype=jnp.bfloat16,
        max_seq_len: int,
        quantize: str | None = None,
        cache_dtype=None,
    ) -> "DraftModelProposer":
        """Load a draft checkpoint directory (same formats the generator
        loads — quantized drafts halve the draft stream too)."""
        config, params = _load_draft_checkpoint(model_dir, dtype, quantize)
        return cls(
            config, params, max_seq_len=max_seq_len, cache_dtype=cache_dtype
        )

    def can_propose(self, n_tokens: int, k: int) -> bool:
        """Cheap applicability guard — the engine checks EVERY lane with
        this before paying ANY lane's draft dispatches, because one
        draftless lane aborts the whole batched round."""
        return k > 0 and n_tokens > 0 and n_tokens + k < self.max_seq_len

    def propose(self, tokens: list[int], k: int) -> list[int]:
        n = len(tokens)
        if not self.can_propose(n, k):
            return []
        # Longest common prefix with what the cache already holds — the one
        # resync rule (fresh stream: cp=0; pure extension: cp=len(hist)).
        h = self._hist
        m = min(len(h), n)
        cp = next((i for i in range(m) if h[i] != tokens[i]), m)
        delta = tokens[cp:]
        if not delta:
            return []  # stream didn't advance; nothing new to condition on
        # Bucket the ingest width (compile count ~ log2 of the longest
        # prompt, not one per delta length).
        bucket = 8
        while bucket < len(delta):
            bucket *= 2
        if cp + bucket > self.max_seq_len:
            bucket = len(delta)  # exact-fit tail: never write out of range
        padded = delta + [0] * (bucket - len(delta))
        logits, self._kv = _draft_ingest_fn(self.config)(
            self.params,
            jnp.asarray([padded], jnp.int32),
            self._kv,
            jnp.int32(cp),
        )
        draft0 = int(jnp.argmax(logits[0, len(delta) - 1]))
        drafts = [draft0]
        if k > 1:
            toks, self._kv, _, _, _ = _draft_decode_fn(self.config, k - 1)(
                self.params,
                self._kv,
                jnp.asarray([draft0], jnp.int32),
                jnp.int32(n),
                jax.random.PRNGKey(0),
                jnp.full((1, 0), -1, jnp.int32),
                jnp.int32(0),
            )
            drafts.extend(int(t) for t in np.asarray(toks)[0])
        # The decode scan already WROTE KV for drafts[:-1] (positions
        # n..n+k-2); recording them in _hist means the accepted prefix of
        # next round's stream common-prefixes straight through them, so high
        # acceptance re-ingests only the corrected/bonus tail, not its own
        # drafts.
        self._hist = list(tokens) + drafts[:-1]
        return drafts


@functools.lru_cache(maxsize=8)
def _draft_ingest_fn(config: LlamaConfig):
    """One compiled draft-ingest per CONFIG (not per proposer): engine lanes
    each own a DraftModelProposer sharing the same draft weights, and
    per-instance jits would recompile the identical program once per lane."""
    return jax.jit(
        functools.partial(
            M.forward_all_logits, config=config, cached_prefill=True
        ),
        donate_argnums=(2,),
    )


@functools.lru_cache(maxsize=8)
def _draft_decode_fn(config: LlamaConfig, n_steps: int):
    """One fused greedy draft scan per (config, width), shared across lanes."""
    from cake_tpu.models.llama.fused import build_decode_fn

    return build_decode_fn(config, n_steps, 0.0, None, None, 1.0)


def _load_draft_checkpoint(model_dir, dtype, quantize: str | None):
    """One draft-checkpoint loader shared by both proposer classes."""
    from cake_tpu.io.safetensors_io import load_params

    config = LlamaConfig.from_model_dir(model_dir)
    params = load_params(model_dir, config, dtype)
    if quantize is not None:
        from cake_tpu.ops.quant import quantize_params

        params = quantize_params(params, quantize)
    return config, params


class BatchedDraftModelProposer:
    """Engine-wide draft-model drafting: ONE pad-aware ingest + ONE fused
    greedy scan per round for ALL lanes.

    The per-lane DraftModelProposer costs 2 dispatches PER LANE per round;
    at engine width B that is 2B small launches whose dispatch overhead is
    exactly what batching exists to amortize. This proposer mirrors the
    engine's left-padded lockstep layout (shared slot, per-lane front pads
    recovered from the histories: slot = max row length, pad = slot - len)
    and drafts every lane in two batched dispatches via the same primitives
    the engine's own verify path uses (models/llama/batch.py).

    Lane churn needs no protocol: a joined/realigned lane's pad changes, so
    its mirror prefix mismatches and the shared ingest window simply starts
    early enough to (re)feed it — re-fed tokens rewrite identical KV, pad
    positions are masked by the batched forward, and bucket-tail garbage
    beyond the slot is overwritten by the draft scan or later windows.
    Drafts are proposals only; the target's verify forward owns the stream.
    """

    def __init__(
        self,
        config: LlamaConfig,
        params,
        *,
        max_seq_len: int,
        cache_dtype=None,
    ):
        self.config = config
        self.params = params
        self.max_seq_len = int(max_seq_len)
        self.cache_dtype = (
            cache_dtype if cache_dtype is not None else jnp.bfloat16
        )
        self._kv = None  # sized at first call (engine width is fixed)
        self._hist: list[list[int] | None] = []
        self._pads: list[int] = []

    @classmethod
    def load(
        cls,
        model_dir,
        *,
        dtype=jnp.bfloat16,
        max_seq_len: int,
        quantize: str | None = None,
        cache_dtype=None,
    ) -> "BatchedDraftModelProposer":
        config, params = _load_draft_checkpoint(model_dir, dtype, quantize)
        return cls(
            config, params, max_seq_len=max_seq_len, cache_dtype=cache_dtype
        )

    def can_propose(self, n_tokens: int, k: int) -> bool:
        return k > 0 and n_tokens > 0 and n_tokens + k < self.max_seq_len

    def propose_batch(
        self, histories: list, k: int
    ) -> "list[list[int] | None]":
        from cake_tpu.models.llama.cache import init_cache

        B = len(histories)
        live = [i for i, h in enumerate(histories) if h]
        none = [None] * B
        if not live or k <= 0:
            return none
        # Dead lanes lose their mirrors NOW: every batched ingest writes the
        # full [w0, w0+bucket) window on ALL rows, so a dead lane's KV is
        # overwritten with pad-token garbage while it idles — a later rejoin
        # that happened to share a prefix AND a pad with the stale mirror
        # would otherwise skip re-feeding the corrupted region (invisible
        # throughput loss: the target still verifies, drafts just go bad).
        for i in range(len(self._hist)):
            if i not in live:
                self._hist[i] = None
        slot = max(len(histories[i]) for i in live)
        if slot + k >= self.max_seq_len:
            return none
        if self._kv is None or self._kv.batch_size != B:
            cfg = self.config
            self._kv = init_cache(
                cfg.num_hidden_layers, B, self.max_seq_len,
                cfg.num_key_value_heads, cfg.head_dim, self.cache_dtype,
            )
            self._hist = [None] * B
            self._pads = [0] * B
        # Per-lane ingest need: a lane whose pad is unchanged and whose
        # history extends its mirror needs only the tail past the common
        # prefix; anything else (join, realigned epoch, divergence) re-feeds
        # from its own start. The shared window starts at the earliest need.
        pads = list(self._pads)
        starts = []
        for i in live:
            h = histories[i]
            pad = slot - len(h)
            m = self._hist[i]
            if m is not None and pad == self._pads[i]:
                lim = min(len(m), len(h))
                cp = next(
                    (j for j in range(lim) if m[j] != h[j]), lim
                )
            else:
                cp = 0
            pads[i] = pad
            starts.append(pad + cp)
        w0 = min(starts)
        if w0 >= slot:
            w0 = slot - 1  # nothing new anywhere: re-feed the last token
        width = slot - w0
        bucket = 8
        while bucket < width:
            bucket *= 2
        bucket = min(bucket, self.max_seq_len - w0)
        tokens = np.zeros((B, bucket), np.int32)
        for i in live:
            h, pad = histories[i], pads[i]
            lo = max(w0, pad)
            tokens[i, lo - w0 : slot - w0] = h[lo - pad : slot - pad]
        logits, self._kv = _batched_draft_ingest_fn(self.config, bucket)(
            self.params,
            jnp.asarray(tokens),
            self._kv,
            jnp.asarray(pads, jnp.int32),
            jnp.int32(w0),
        )
        draft0 = jnp.argmax(logits[:, width - 1], -1).astype(jnp.int32)
        if k > 1:
            toks, self._kv, _, _, _ = _batched_draft_decode_fn(
                self.config, k - 1
            )(
                self.params,
                self._kv,
                draft0,
                jnp.int32(slot),
                jnp.asarray(pads, jnp.int32),
                jax.random.PRNGKey(0),
                jnp.full((B, 0), -1, jnp.int32),
                jnp.zeros((B,), jnp.int32),
            )
            drafts = np.concatenate(
                [np.asarray(draft0)[:, None], np.asarray(toks)], axis=1
            )
        else:
            drafts = np.asarray(draft0)[:, None]
        out: list = list(none)
        for i in live:
            d = drafts[i].tolist()
            out[i] = d
            self._hist[i] = list(histories[i]) + d[:-1]
        self._pads = pads
        return out


@functools.lru_cache(maxsize=16)
def _batched_draft_ingest_fn(config: LlamaConfig, width: int):
    """One jitted pad-aware batched ingest per (config, bucketed width)."""
    from cake_tpu.models.llama.batch import batched_verify_logits

    def run(params, tokens, kv, pads, slot):
        return batched_verify_logits(params, tokens, kv, pads, slot, config)

    return jax.jit(run, donate_argnums=(2,))


@functools.lru_cache(maxsize=8)
def _batched_draft_decode_fn(config: LlamaConfig, n_steps: int):
    """One fused greedy batched draft scan per (config, width)."""
    from cake_tpu.models.llama.batch import _decode_fn

    return _decode_fn(config, 0, n_steps, 0.0, None, None, 1.0)
