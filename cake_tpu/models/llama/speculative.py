"""Prompt-lookup speculative decoding (greedy).

Pure perf feature beyond the reference (it decodes strictly one token per
forward). Single-stream TPU decode is HBM-bound: one forward over K+1 tokens
reads the same weights as one token's forward, so if K drafted tokens verify,
the step produces K+1 tokens for ~one token's cost.

Drafts come from **prompt lookup** (no draft model): find the most recent
earlier occurrence of the current n-gram suffix in the token history and
propose the tokens that followed it. Repetitive spans — quoting the prompt,
code, structured output — verify at high rates; adversarial drafts cost one
wasted chunk and nothing else.

Verification feeds [last_token, draft_0..draft_{K-1}] through ONE chunked
forward (the cached-prefill attention variant) and reads logits at every
position (model.forward_all_logits). Greedy acceptance: the longest prefix
where argmax(logits[i]) == draft[i]; position of the first mismatch yields the
CORRECTED token from the same logits — so the emitted stream is exactly the
greedy stream, draft quality only affects speed. Rejected tail KV sits past
the live length (masked dead slots) and is overwritten as decoding proceeds.

Greedy only (temperature == 0, repeat_penalty == 1.0): exactness of acceptance
is what makes the oracle trivially hold; sampled speculative (rejection
sampling) is future work.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.config import LlamaConfig


def propose_lookup(
    tokens: list[int], k: int, max_ngram: int = 3, min_ngram: int = 1
) -> list[int]:
    """Propose up to ``k`` draft tokens by prompt lookup.

    Finds the longest n-gram (max_ngram down to min_ngram) equal to the
    current suffix that also occurs earlier in ``tokens``, preferring the most
    recent occurrence, and returns the tokens that followed it. Empty list if
    no match — callers fall back to plain decode.
    """
    n = len(tokens)
    if n < min_ngram + 1 or k <= 0:
        return []
    arr = np.asarray(tokens, np.int32)
    for size in range(min(max_ngram, n - 1), min_ngram - 1, -1):
        suffix = arr[n - size :]
        # Vectorized most-recent-earlier-occurrence scan: windows over
        # arr[:-1] end at start n-1-size, so the suffix's own occurrence at
        # n-size is excluded by construction. O(n) in C per step.
        windows = np.lib.stride_tricks.sliding_window_view(arr[:-1], size)
        idxs = np.flatnonzero((windows == suffix).all(axis=1))
        for start in idxs[::-1]:
            follow = tokens[start + size : start + size + k]
            if follow:
                return follow
    return []


@functools.lru_cache(maxsize=8)
def _verify_fn(config: LlamaConfig, width: int):
    """Jit one chunked verify forward per (config, draft width).

    Returns GREEDY ids [b, width] (argmax on device) — shipping the full
    [b, width, vocab] f32 logits to host would cost ~width * vocab * 4 bytes
    per step against the very overhead speculation removes."""

    def run(params, tokens, kv, pos):
        logits, kv = M.forward_all_logits(
            params, tokens, kv, pos, config, cached_prefill=True
        )
        return jnp.argmax(logits, -1).astype(jnp.int32), kv

    return jax.jit(run, donate_argnums=(2,))


def greedy_accept(draft: np.ndarray, argmaxes: np.ndarray) -> tuple[int, int]:
    """Longest accepted prefix + the corrected/next token.

    argmaxes[i] is the greedy continuation AFTER position i of the fed chunk
    [last, d_0, .., d_{K-1}]; draft[i] == argmaxes[i] accepts d_i. Returns
    (n_accepted, next_token) where next_token is argmaxes[n_accepted] — the
    correction at the first mismatch, or the bonus token after a full accept.
    """
    n = 0
    while n < len(draft) and int(draft[n]) == int(argmaxes[n]):
        n += 1
    return n, int(argmaxes[n])
