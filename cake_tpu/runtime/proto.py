"""Wire protocol: framed TCP tensor transport.

Plays the role of the reference's custom protocol (cake-core/src/cake/proto/):
magic + u32 length framing with a size cap (proto/mod.rs:4-7), Hello/WorkerInfo
handshake, batched ops over one connection, raw-bytes tensor encoding
(message.rs:10-76). It is a fresh design, not the reference's bitcode encoding:

  Frame:   [magic u32][frame_len u32][type u8][header_len u32][header JSON][payload]

  * The tensor payload is a FLAT TAIL, never embedded in a serializer — decode is
    a memoryview slice straight into numpy (zero-copy up to the device upload),
    and encode is two writev-style sends. bf16 travels as raw 2-byte words.
  * Ops are expressed as block RANGES [lo, hi) + (pos, seq_len), matching how
    this framework executes contiguous runs as one lax.scan — the same
    one-round-trip-per-contiguous-span semantics as the reference's Batch
    (llama.rs:95-114) with SingleOp as the hi == lo+1 special case.
  * RESET and ERROR are first-class (the reference can only drop a connection).

A C++ codec (cake_tpu/native) takes over the socket pumping when built — one
GIL-released recv per frame, writev sends with zero payload copies, an internal
poll loop honoring socket timeouts; this module remains the always-available
pure-Python implementation of the same format, selected call-by-call.
"""

from __future__ import annotations

import dataclasses
import json
import platform
import socket
import struct
from enum import IntEnum
from typing import Any

import numpy as np

from cake_tpu import __version__
from cake_tpu import native

MAGIC = 0x74707563  # "tpuc"
MAX_FRAME_SIZE = 512 * 1024 * 1024  # same cap as the reference (proto/mod.rs:7)
_HDR = struct.Struct("<IIBI")  # magic, frame_len, type, header_len


class MsgType(IntEnum):
    HELLO = 1
    WORKER_INFO = 2
    FORWARD = 3      # header: {ranges: [[lo,hi],...], pos}; payload: x
    # The header carries NO per-chunk validity field: chunks may arrive with
    # padded tails (the master's pow2 prefill buckets), and pad-tail KV is
    # safe by construction — pad keys are written at FUTURE positions, so the
    # causal mask hides them from every query until real tokens overwrite
    # those slots (the master slices its own logits at the valid length).
    # The receiver consumes the whole header; tests pin this contract
    # (test_runtime.test_frame_roundtrip_with_payload, test_padded_tail_kv).
    TENSOR = 4       # payload: result tensor
    RESET = 5        # new sequence: drop this connection's KV state
    ERROR = 6        # header: {error: str}
    PING = 7         # health check; answered with PING (+ worker wall clock)
    STATS = 8        # pull one node's telemetry snapshot (header-only both
    # ways: request carries tail caps, reply carries the node's metric dump,
    # flight-event tail, and timeline slice — obs/cluster.py merges them)


# Wire dtype tags <-> numpy. bf16 has no numpy dtype; it travels as uint16 words
# and is re-viewed on the JAX side.
_DTYPE_TO_TAG = {
    "float32": "f32",
    "float16": "f16",
    "bfloat16": "bf16",
    "int32": "i32",
    "int8": "i8",
    "uint16": "bf16",  # bf16 backing store
}
_TAG_TO_NP = {
    "f32": np.float32,
    "f16": np.float16,
    "bf16": np.uint16,
    "i32": np.int32,
    "i8": np.int8,
}


@dataclasses.dataclass
class WireTensor:
    """Raw-bytes tensor (role of RawTensor, message.rs:10-33)."""

    data: bytes | memoryview
    dtype: str  # wire tag: f32 / f16 / bf16 / i32 / i8
    shape: tuple[int, ...]

    @classmethod
    def from_numpy(cls, arr: np.ndarray, dtype_tag: str | None = None) -> "WireTensor":
        tag = dtype_tag or _DTYPE_TO_TAG[arr.dtype.name]
        return cls(data=arr.tobytes(), dtype=tag, shape=tuple(arr.shape))

    def to_numpy(self) -> np.ndarray:
        np_dtype = _TAG_TO_NP[self.dtype]
        return np.frombuffer(self.data, dtype=np_dtype).reshape(self.shape)

    def header(self) -> dict[str, Any]:
        return {"dtype": self.dtype, "shape": list(self.shape)}


@dataclasses.dataclass
class WorkerInfo:
    """Worker handshake diagnostics (role of message.rs:37-53)."""

    version: str = __version__
    dtype: str = "bf16"
    os: str = dataclasses.field(default_factory=platform.system)
    arch: str = dataclasses.field(default_factory=platform.machine)
    device: str = "unknown"
    device_count: int = 1
    latency_ms: float = 0.0
    ranges: list[list[int]] = dataclasses.field(default_factory=list)
    # Capabilities: this worker understands the FORWARD ``batch`` header
    # (lockstep continuous batching) / the ``verify`` batch kind (batched
    # speculative verify). Both default False so an OLD worker's handshake —
    # which omits the fields — is detected by the master before it would
    # silently ignore pads or reject verify frames mid-epoch
    # (DistributedBatchBackend checks both at init).
    batch_ops: bool = False
    verify_ops: bool = False
    # This worker answers STATS pulls (federated telemetry) and stamps its
    # wall clock into PING replies (clock-offset estimation). Defaults False
    # so an OLD worker's handshake tells the master not to send STATS frames
    # it would answer with ERROR.
    stats_ops: bool = False

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "WorkerInfo":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


@dataclasses.dataclass
class Frame:
    type: MsgType
    header: dict[str, Any]
    payload: bytes | memoryview = b""

    def tensor(self) -> WireTensor:
        t = self.header["tensor"]
        return WireTensor(
            data=self.payload, dtype=t["dtype"], shape=tuple(t["shape"])
        )


def _frame_head(frame: Frame) -> tuple[bytes, int]:
    """Serialize prefix + header JSON; the single owner of the wire prefix
    format and the size-cap check (shared by encode_frame and write_frame)."""
    header_bytes = json.dumps(frame.header, separators=(",", ":")).encode()
    frame_len = _HDR.size + len(header_bytes) + len(frame.payload)
    if frame_len > MAX_FRAME_SIZE:
        raise ValueError(f"frame of {frame_len} B exceeds cap {MAX_FRAME_SIZE}")
    head = _HDR.pack(MAGIC, frame_len, int(frame.type), len(header_bytes))
    return head + header_bytes, frame_len


def encode_frame(frame: Frame) -> bytes:
    head, _ = _frame_head(frame)
    return b"".join((head, frame.payload))


def decode_frame(buf: memoryview) -> Frame:
    magic, frame_len, mtype, header_len = _HDR.unpack_from(buf, 0)
    if magic != MAGIC:
        raise ValueError(f"bad magic {magic:#x}")
    if frame_len != len(buf):
        raise ValueError(f"frame length mismatch: {frame_len} != {len(buf)}")
    header_end = _HDR.size + header_len
    header = json.loads(bytes(buf[_HDR.size : header_end]))
    return Frame(
        type=MsgType(mtype), header=header, payload=buf[header_end:]
    )


# ------------------------------------------------------------------ socket IO


def _recv_exact(sock: socket.socket, n: int) -> memoryview:
    buf = bytearray(n)
    if native.available():
        # One GIL-released C call with an internal poll loop (native/codec.cpp)
        # instead of a Python recv_into loop.
        native.recv_exact_into(sock, buf, n)
        return memoryview(buf)
    view = memoryview(buf)
    got = 0
    while got < n:
        try:
            # Caller-owned socket: every entry point configures the deadline
            # (client sets op_deadline_s, worker sets io_timeout_s) — the
            # unbounded-socket-op rule enforces that at those call sites.
            r = sock.recv_into(view[got:], n - got)  # cake-lint: disable=unbounded-socket-op
        except TimeoutError:
            if got == 0:
                # Nothing read yet: a clean timeout the caller can retry
                # (the deadline covers a whole frame, not each recv).
                # Mid-frame, the stream is torn — re-reading would desync
                # on the partial bytes, so it becomes a ConnectionError.
                raise
            raise ConnectionError(
                f"peer stalled mid-frame ({got}/{n} bytes)"
            ) from None
        if r == 0:
            raise ConnectionError("peer closed connection")
        got += r
    return memoryview(buf)


def read_frame(sock: socket.socket) -> Frame:
    head = _recv_exact(sock, _HDR.size)
    magic, frame_len, mtype, header_len = _HDR.unpack_from(head, 0)
    if magic != MAGIC:
        raise ValueError(f"bad magic {magic:#x}")
    if frame_len > MAX_FRAME_SIZE:
        raise ValueError(f"frame of {frame_len} B exceeds cap {MAX_FRAME_SIZE}")
    # Single receive buffer; the payload is a zero-copy slice of it.
    rest = _recv_exact(sock, frame_len - _HDR.size)
    header = json.loads(bytes(rest[:header_len]))
    return Frame(type=MsgType(mtype), header=header, payload=rest[header_len:])


def write_frame(sock: socket.socket, frame: Frame) -> int:
    head, frame_len = _frame_head(frame)
    if native.available():
        # writev: prefix+header as one small buffer, tensor payload straight
        # from its owner (no megabyte-scale concatenation copy).
        native.send2(sock, head, frame.payload)
    else:
        # One sendall (not two): keeps the frame in a single segment run even
        # with Nagle enabled; join accepts the payload memoryview directly.
        # Caller-owned socket: deadlines are configured at every entry point
        # (see _recv_exact).
        sock.sendall(b"".join((head, frame.payload)))  # cake-lint: disable=unbounded-socket-op
    return frame_len


# ------------------------------------------------------------------ builders


def hello_frame() -> Frame:
    return Frame(MsgType.HELLO, {"version": __version__})


def worker_info_frame(info: WorkerInfo) -> Frame:
    return Frame(MsgType.WORKER_INFO, {"info": info.to_dict()})


def forward_frame(
    x: WireTensor,
    ranges: list[tuple[int, int]],
    pos: int,
    batch: dict | None = None,
    trace: str | None = None,
    flow: int | None = None,
    sid: str | None = None,
    seq: int | None = None,
) -> Frame:
    """One round trip for one contiguous span (or several on the same worker).

    ``batch`` selects the left-padded LOCKSTEP layout (models/llama/batch.py)
    for continuous batching over the wire (runtime/batch_backend.py
    DistributedBatchBackend):
      {"kind": "prefill", "pads": [B], "ends": [B]}          pos == 0
      {"kind": "decode",  "pads": [B]}                        pos == slot
      {"kind": "join",    "pads": [1], "ends": [1], "lane": l} pos == 0
    Absent (None) = the single-position-stream layout (pad-free equal rows),
    the reference-parity path.

    ``trace`` (optional) is the request/trace id for per-hop attribution
    (utils/metrics.py): the worker labels its per-op telemetry with it and
    echoes it in the TENSOR reply. ``flow`` (optional) is the per-hop flow id
    for the timeline profiler (cake_tpu/obs/timeline.py): the sender marks a
    flow start ("s") under this id when the frame leaves, the worker marks
    the flow end ("f") inside its op span, and merged Perfetto exports render
    the hop as an arrow connecting the two nodes' tracks. Absent = untraced
    (old masters/workers interoperate unchanged — unknown header keys are
    ignored).

    ``sid``/``seq`` (optional, travel together) are the epoch-scoped session
    id and the op's monotonic sequence number within it. A worker keys its KV
    state by ``sid`` instead of by connection (runtime/worker.py sessions),
    so a reconnect can RESEND the same (sid, seq) frame and get an
    idempotent outcome: the op executes if the worker never saw it, or the
    cached reply returns if only the reply was lost. Absent = the legacy
    per-connection-cache contract (old peers interoperate unchanged).
    """
    header = {
        "ranges": [list(r) for r in ranges],
        "pos": int(pos),
        "tensor": x.header(),
    }
    if batch is not None:
        header["batch"] = batch
    if trace is not None:
        header["trace"] = str(trace)
    if flow is not None:
        header["flow"] = int(flow)
    if sid is not None:
        header["sid"] = str(sid)
        header["seq"] = int(seq or 0)
    return Frame(MsgType.FORWARD, header, payload=x.data)


def tensor_frame(x: WireTensor, trace: str | None = None) -> Frame:
    header: dict[str, Any] = {"tensor": x.header()}
    if trace is not None:
        # Echo the request's trace id so the master can attribute the reply
        # to the hop that produced it even over pipelined connections.
        header["trace"] = str(trace)
    return Frame(MsgType.TENSOR, header, payload=x.data)


def reset_frame(sid: str | None = None) -> Frame:
    """New sequence. With ``sid``: drop that session's state (the worker may
    be holding it for replay); without: drop this connection's KV (legacy)."""
    if sid is None:
        return Frame(MsgType.RESET, {})
    return Frame(MsgType.RESET, {"sid": str(sid)})


# Machine-readable ERROR codes (the ``code`` header field). Free-form errors
# (exceptions stringified by the worker) carry no code; these two drive the
# client's retry decision — retrying them cannot succeed, so the client
# escalates to session-lost recovery instead of burning its retry budget.
ERR_UNKNOWN_SESSION = "unknown-session"  # worker restarted / session evicted
ERR_BAD_SEQ = "bad-seq"                  # sequence gap: state diverged


def error_frame(message: str, code: str | None = None) -> Frame:
    if code is None:
        return Frame(MsgType.ERROR, {"error": message})
    return Frame(MsgType.ERROR, {"error": message, "code": code})


def ping_frame(t: float | None = None) -> Frame:
    """Health probe. A replying worker stamps its wall clock into ``t`` so
    the prober can estimate the worker's clock offset NTP-style from the
    round-trip midpoint (obs/cluster.py ``ClockOffsetEstimator``); requests
    — and old workers' replies — omit it, and the probe degrades to a pure
    liveness check."""
    if t is None:
        return Frame(MsgType.PING, {})
    return Frame(MsgType.PING, {"t": round(float(t), 6)})


def stats_request_frame(events: int = 256, timeline: int = 4096) -> Frame:
    """Master -> worker: pull this node's telemetry snapshot.

    ``events``/``timeline`` cap the flight-event and timeline tails the
    reply may carry (the reply header is JSON — the caps bound its size,
    and a pull cadence of seconds only needs the tail since the last pull).
    """
    return Frame(
        MsgType.STATS,
        {"events": int(events), "timeline": int(timeline)},
    )


def stats_reply_frame(report: dict) -> Frame:
    """Worker -> master: the node's snapshot — ``{node, wall, metrics,
    events, timeline}`` (runtime/worker.py ``_stats_report`` builds it,
    obs/cluster.py consumes it)."""
    return Frame(MsgType.STATS, {"report": report})
