"""OpenAI-compatible REST API.

Covers the reference's API layer (cake-core/src/cake/api/mod.rs): a single
``POST /api/v1/chat/completions`` route (api/mod.rs:123) whose response carries
``{id, object: "chat.completion", created, model, choices:[{index, message}]}``
(api/mod.rs:26-62), resetting the model per request (api/mod.rs:78).

Beyond reference parity (its quirks are documented, not contracts — SURVEY.md §2.6):
  * SSE streaming (``"stream": true`` -> ``chat.completion.chunk`` events) — the
    reference is non-streaming only.
  * ``usage`` token counts in the response.
  * Per-request sampling overrides (temperature, top_p, max_tokens, seed).
  * A ``GET /health`` probe and an observability surface: ``GET /stats``
    (span timers + host/device memory + metric percentiles — what the
    ``cake-tpu stats`` CLI renders), ``GET /metrics`` (full Prometheus text
    exposition: latency histograms with cumulative buckets, counters, gauges,
    build info + uptime — utils/metrics.py), ``GET /events`` (the flight
    recorder's ring of request lifecycle events, filterable by request id;
    ``events_jsonl`` additionally streams every event to a JSONL file), and
    ``GET /trace`` (the timeline profiler's span-tree ring rendered as
    Perfetto-loadable Chrome trace-event JSON, filterable by request id;
    ``trace_jsonl`` streams the raw events — cake_tpu/obs/timeline.py), and
    ``GET /slo`` (per-tenant rolling SLIs + error-budget burn rates —
    cake_tpu/obs/slo.py), and ``GET /explain?request_id=`` (per-request
    critical-path latency attribution: queue / prefill / decode / convoy /
    stall / wire phase decomposition — cake_tpu/obs/critpath.py). On a TCP
    cluster with worker telemetry reports
    (obs/cluster.py), /metrics becomes ONE merged exposition with every
    node's series under a ``node`` label, /events interleaves cluster-wide
    events by clock-aligned time, and ``/trace?cluster=1`` exports ONE
    merged Perfetto trace with worker spans aligned onto the master clock.

Concurrency: with a ``BatchEngine`` (runtime/serving.py, ``--api-batch``),
requests are queued and decoded in lockstep batches — N concurrent clients
stream simultaneously at near-single-request speed each. Without an engine,
requests serialize behind a lock around the single generator (the reference
holds a global write lock the same way, api/mod.rs:76). Streaming sends tokens
as they decode, and a per-write socket timeout (``stream_write_timeout``) aborts
the stream if the client stops reading, so one stalled consumer can't wedge the
server for everyone. Built on http.server's ThreadingHTTPServer: the framework
runs with zero third-party server dependencies.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import math
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.generator import LlamaGenerator, SamplingConfig, Token
from cake_tpu.runtime import faults

log = logging.getLogger("cake_tpu.api")

CHAT_ROUTE = "/api/v1/chat/completions"
CANCEL_ROUTE = "/api/v1/cancel"

# Tenant ids key metric labels, quota buckets, and fair-queue subqueues;
# bounding their length keeps a hostile header from being a label-
# cardinality / memory vector (runtime/admission.py bounds the COUNT via
# MAX_TENANTS the same way).
MAX_TENANT_ID_LEN = 64


@dataclasses.dataclass
class ApiServer:
    generator: LlamaGenerator
    model_name: str = "llama3"
    default_max_tokens: int = 256
    # Max seconds a single SSE write may block on a non-reading client before
    # the stream is aborted (the generator lock is held while streaming).
    stream_write_timeout: float = 30.0
    # Optional concurrent-serving engine (runtime/serving.py). When set, chat
    # requests bypass the generator lock entirely: they queue into the engine
    # and decode as lockstep batches, streaming concurrently.
    engine: "object | None" = None
    # Flight-recorder JSONL dump hook: when set, every lifecycle event
    # (utils/metrics.py FlightRecorder) is appended to this path as one JSON
    # line — the durable counterpart of the bounded GET /events ring.
    events_jsonl: "str | None" = None
    # Timeline JSONL stream (--trace-jsonl): every profiling event
    # (cake_tpu/obs/timeline.py — spans, instants, counters, flow arrows) is
    # appended as one JSON line; ``cake_tpu.obs.load_jsonl`` +
    # ``export_events`` turn the file into a Perfetto-loadable trace, and the
    # bounded ring stays live at GET /trace either way.
    trace_jsonl: "str | None" = None
    # Request-log JSONL sink (--request-log): every per-request completion
    # record (obs/requestlog.py — tenant, token counts, timing ladder,
    # finish/SLO verdict, phase digest, decision causes) is appended as one
    # JSON line; the bounded ring stays live at GET /requests either way,
    # and the file IS the loadgen replay trace (cake_tpu/loadgen/replay.py).
    request_log: "str | None" = None

    def __post_init__(self) -> None:
        self._lock = threading.Lock()
        self._started = int(time.time())
        if self.events_jsonl:
            from cake_tpu.utils import metrics

            metrics.flight.attach_jsonl(self.events_jsonl)
        if self.trace_jsonl:
            from cake_tpu.obs.timeline import timeline

            timeline.attach_jsonl(self.trace_jsonl)
        if self.request_log:
            reqlog = getattr(self.engine, "requestlog", None)
            if reqlog is not None:
                reqlog.attach_jsonl(self.request_log)
            else:
                log.warning(
                    "--request-log needs the batch engine (--api-batch "
                    "> 1); no request records will be written"
                )
        if self.engine is not None:
            self.engine.start()

    # ------------------------------------------------------------- handlers

    def handle_chat(self, body: dict, handler: BaseHTTPRequestHandler) -> dict | None:
        """Run one chat completion; returns a JSON response, or None if the
        reply was streamed directly to ``handler``. The whole request — including
        streaming — runs under the generator lock."""
        def opt(key, default, cast):
            """Request field with JSON-null treated as unset; bad types -> 400."""
            v = body.get(key)
            if v is None:
                return default
            try:
                return cast(v)
            except (TypeError, ValueError) as e:
                raise ApiError(400, f"invalid {key!r}: {e}") from e

        raw_messages = body.get("messages", [])
        if not isinstance(raw_messages, list) or not raw_messages:
            raise ApiError(400, "messages must be a non-empty list")
        try:
            messages = [Message.from_dict(m) for m in raw_messages]
        except (KeyError, ValueError, TypeError, AttributeError) as e:
            raise ApiError(400, f"invalid message: {e}") from e
        max_tokens = opt("max_tokens", None, int)
        if max_tokens is None:
            max_tokens = opt("max_completion_tokens", None, int)
        if max_tokens is None:
            max_tokens = self.default_max_tokens
        elif max_tokens < 1:
            raise ApiError(400, f"max_tokens must be >= 1, got {max_tokens}")
        stream = bool(body.get("stream", False))
        # OpenAI stream_options: {"include_usage": true} appends one final
        # usage chunk (empty choices) after the finish chunk, before
        # [DONE] — the only way a streaming client gets exact token
        # counts (tokens with empty text emit no content chunk, so
        # client-side chunk counting undercounts).
        stream_options = body.get("stream_options")
        if stream_options is not None and not isinstance(stream_options, dict):
            raise ApiError(400, "stream_options must be an object")
        include_usage = bool((stream_options or {}).get("include_usage"))

        if self.engine is not None:
            return self._handle_chat_batched(
                body, messages, max_tokens, stream, include_usage, opt, handler
            )

        from cake_tpu.utils import metrics

        with self._lock:
            gen = self.generator
            base = gen.sampling
            # Per-request sampling overrides; generator-level defaults otherwise.
            gen.sampling = self._request_sampling(opt, base)
            try:
                gen.reset()  # per-request reset, api/mod.rs:78
                for m in messages:
                    gen.add_message(m)
                n_prompt = gen.prompt_token_count()
                if n_prompt >= gen.step.max_seq_len:
                    # Context-length overflow is a client error (4xx), caught
                    # BEFORE streaming headers go out.
                    raise ApiError(
                        400,
                        f"prompt is {n_prompt} tokens but the context window "
                        f"is {gen.step.max_seq_len}",
                    )
                rid = f"chatcmpl-{uuid.uuid4()}"
                created = int(time.time())
                # Request-scoped wire attribution: distributed steps stamp
                # this id on every FORWARD frame (runtime/master.py).
                if hasattr(gen.step, "trace_id"):
                    gen.step.trace_id = rid
                metrics.flight.record(
                    "submitted", rid, prompt_tokens=n_prompt, path="serialized"
                )
                if stream:

                    def produce(on_token) -> str:
                        gen.generate(max_tokens, on_token=on_token)
                        return gen.last_finish_reason

                    _SseStream(
                        self, produce, rid, created,
                        usage_fn=(
                            (lambda: (gen._n_prompt, gen.generated_count))
                            if include_usage else None
                        ),
                    ).run(handler)
                    metrics.flight.record(
                        "finished", rid,
                        finish_reason=gen.last_finish_reason,
                        completion_tokens=gen.generated_count,
                    )
                    return None
                text = gen.generate(max_tokens)
                metrics.flight.record(
                    "finished", rid,
                    finish_reason=gen.last_finish_reason,
                    completion_tokens=gen.generated_count,
                )
                return self._completion_response(
                    rid,
                    created,
                    text,
                    gen.last_finish_reason,
                    gen._n_prompt,
                    gen.generated_count,
                )
            finally:
                gen.sampling = base
                if hasattr(gen.step, "trace_id"):
                    gen.step.trace_id = None

    def _handle_chat_batched(
        self, body, messages, max_tokens: int, stream: bool,
        include_usage: bool, opt, handler
    ) -> dict | None:
        """Engine path: no generator lock — submit and consume a stream handle.

        Requests admitted together decode as one lockstep batch; per-request
        sampling/seed stay exact (per-row PRNG keys, runtime/serving.py).
        """
        from cake_tpu.runtime.admission import QuotaExceeded
        from cake_tpu.runtime.serving import EngineOverloaded

        sampling = self._request_sampling(opt, self.generator.sampling)
        # Priority class (0 low / 1 normal / 2 high; engine default
        # otherwise): scales the load-shedding gates and the 503
        # Retry-After — low-priority traffic degrades first under overload.
        priority = opt("priority", None, int)
        if priority is not None and priority not in (0, 1, 2):
            raise ApiError(400, f"priority must be 0, 1 or 2, got {priority}")
        # Tenant identity (README "Admission control & SLOs"): the explicit
        # body field wins over the X-Cake-Tenant header; absent both, the
        # engine books everything to the default tenant. Keys the
        # per-tenant quota gates (429s) and the fair queue's subqueues.
        tenant = body.get("tenant")
        if tenant is not None and (
            not isinstance(tenant, str) or not tenant.strip()
        ):
            raise ApiError(400, "tenant must be a non-empty string")
        if tenant is None:
            tenant = handler.headers.get("X-Cake-Tenant") or None
        if tenant is not None and len(tenant) > MAX_TENANT_ID_LEN:
            # Tenant ids become metric labels and queue keys; an
            # attacker-chosen unbounded string is a cardinality/memory
            # vector, so the length is a hard 400 — not a truncation,
            # which would silently merge distinct tenants' quotas.
            raise ApiError(
                400,
                f"tenant id longer than {MAX_TENANT_ID_LEN} characters",
            )
        # End-to-end deadline in seconds (submit -> last token). Queued
        # past it the request expires unadmitted; running past it the
        # stream finishes with finish_reason="deadline".
        deadline_s = opt("deadline_s", None, float)
        if deadline_s is not None and deadline_s <= 0:
            raise ApiError(
                400, f"deadline_s must be > 0 seconds, got {deadline_s}"
            )
        rid = f"chatcmpl-{uuid.uuid4()}"
        try:
            # The response id doubles as the request/trace id: the engine's
            # flight-recorder lifecycle and wire-frame attribution use the
            # same string the client sees, so GET /events?request_id=<id>
            # resolves straight from a client-side response.
            h = self.engine.submit(
                messages, max_tokens, sampling, request_id=rid,
                priority=priority, tenant=tenant, deadline_s=deadline_s,
            )
        except QuotaExceeded as e:
            # Per-tenant quota refusal: 429 (the CALLER is over budget; the
            # hint is their own bucket arithmetic) — deliberately distinct
            # from the 503 below, which means the SERVER is saturated.
            raise ApiError(
                429, str(e),
                headers={"Retry-After": str(max(1, math.ceil(e.retry_after_s)))},
            ) from e
        except EngineOverloaded as e:
            # Load shedding: an honest 503 with a retry hint beats queueing
            # the request into a client-side timeout.
            raise ApiError(
                503, str(e),
                headers={"Retry-After": str(max(1, int(e.retry_after_s)))},
            ) from e
        except ValueError as e:  # over-length prompt — 4xx before any headers
            raise ApiError(400, str(e)) from e
        created = int(time.time())
        if stream:

            def produce(on_token) -> str:
                for tok in h.tokens():
                    on_token(tok)
                return h.finish_reason

            _SseStream(
                self, produce, rid, created,
                usage_fn=(
                    (lambda: (h.prompt_tokens, h.completion_tokens))
                    if include_usage else None
                ),
            ).run(handler)
            return None
        text = h.text()
        return self._completion_response(
            rid, created, text, h.finish_reason, h.prompt_tokens, h.completion_tokens
        )

    def _refresh_cluster(self) -> None:
        """Keep the cluster observability plane fresh for a merged surface
        read (/metrics, /events, /trace?cluster=1, /stats cluster block).

        With heartbeat probing on, the monitor's STATS pulls feed the
        observer continuously and this is a no-op; without it, a TCP
        master pulls on demand (runtime/master.py ``pull_cluster_stats``)
        — rate-limited to one refresh per few seconds so a burst of
        scrapes (or a worker whose connect must time out) costs one pull,
        not one per request."""
        monitor = getattr(self.engine, "monitor", None)
        if monitor is not None:
            return  # probe threads keep the observer live
        step = getattr(self.generator, "step", None)
        pull = getattr(step, "pull_cluster_stats", None)
        if pull is None:
            return
        now = time.monotonic()
        last = getattr(self, "_cluster_last_pull", 0.0)
        if now - last < 5.0:
            return  # fresh enough: serve the cached reports
        self._cluster_last_pull = now
        try:
            pull()
        except Exception:  # noqa: BLE001 — a scrape must not 500
            log.exception("cluster stats pull failed")

    def _client_gone(self, rid: str) -> None:
        """Client-disconnect/stall hook (the SSE error path): with a batch
        engine, cancel the abandoned request so its lane stops decoding and
        its pages free up; always leave a flight-recorder breadcrumb."""
        from cake_tpu.utils import metrics

        cancelled = False
        if self.engine is not None:
            try:
                cancelled = bool(self.engine.cancel(rid))
            except Exception:  # noqa: BLE001 — a dying stream must not 500
                log.exception("cancel-on-disconnect failed for %s", rid)
        metrics.flight.record("client-gone", rid, cancelled=cancelled)

    @staticmethod
    def _request_sampling(opt, base: SamplingConfig) -> SamplingConfig:
        """Per-request overrides over the server's base sampling — the ONE
        list of knobs the API exposes, shared by both serving paths."""
        return SamplingConfig(
            temperature=opt("temperature", base.temperature, float),
            top_k=opt("top_k", base.top_k, int),
            top_p=opt("top_p", base.top_p, float),
            repeat_penalty=base.repeat_penalty,
            repeat_last_n=base.repeat_last_n,
            seed=opt("seed", base.seed, int),
        )

    def _completion_response(
        self, rid, created, text, finish_reason, n_prompt, n_generated
    ) -> dict:
        """The reference's response shape (api/mod.rs:26-62) + usage."""
        return {
            "id": rid,
            "object": "chat.completion",
            "created": created,
            "model": self.model_name,
            "choices": [
                {
                    "index": 0,
                    "message": {"role": "assistant", "content": text},
                    "finish_reason": finish_reason,
                }
            ],
            "usage": {
                "prompt_tokens": n_prompt,
                "completion_tokens": n_generated,
                "total_tokens": n_prompt + n_generated,
            },
        }

    # ------------------------------------------------------------- serving

    def make_server(self, host: str, port: int) -> ThreadingHTTPServer:
        api = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # route through logging
                log.debug("%s " + fmt, self.client_address[0], *args)

            def _json(self, code: int, obj: dict,
                      headers: dict[str, str] | None = None) -> None:
                data = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                from urllib.parse import parse_qs, urlparse

                parsed = urlparse(self.path)
                route, query = parsed.path, parse_qs(parsed.query)
                if route == "/health":
                    self._json(200, {"status": "ok", "model": api.model_name})
                elif route == "/metrics":
                    # Prometheus text exposition: the metrics registry
                    # (histograms with cumulative buckets, counters, gauges —
                    # utils/metrics.py) plus span timers as count/total pairs
                    # (the standard summary shape) and the batch engine's
                    # admission counters. # HELP lines ride along so scrapes
                    # are self-describing. Scrapers point at the serving port.
                    from cake_tpu import __version__
                    from cake_tpu.utils import metrics, trace

                    # Refreshed at scrape time (not construction): a registry
                    # clear() between test modules must not lose them.
                    metrics.registry.gauge(
                        "cake_build_info",
                        "Constant 1; the labels carry model and version.",
                    ).set(1, model=api.model_name, version=__version__)
                    if hasattr(api.engine, "slo"):
                        # cake_slo_* gauges reflect the live rolling
                        # windows; set at scrape time, not per observation.
                        api.engine.slo.refresh_metrics()
                    if hasattr(api.engine, "efficiency"):
                        # cake_goodput_frac / cake_mfu / cake_mbu follow
                        # the same scrape-time gauge pattern.
                        api.engine.efficiency.refresh_metrics()
                    metrics.registry.gauge(
                        "cake_uptime_seconds",
                        "Seconds since the API server started.",
                    ).set(round(time.time() - api._started, 3))
                    lines = [
                        "# HELP cake_span_seconds Accumulated span timers "
                        "(utils/trace.py), as count/sum pairs.",
                        "# TYPE cake_span_seconds summary",
                    ]
                    for name, d in sorted(trace.spans.snapshot().items()):
                        # Prometheus label-value escaping (\ " and newline):
                        # dropped characters would silently collide series,
                        # and a raw newline fails the whole scrape.
                        label = metrics.escape_label_value(name)
                        lines.append(
                            f'cake_span_seconds_count{{span="{label}"}} '
                            f"{d['count']}"
                        )
                        lines.append(
                            f'cake_span_seconds_sum{{span="{label}"}} '
                            f"{d['total_s']:.6f}"
                        )
                    if api.engine is not None:
                        # High-water marks are gauges — rate()/increase()
                        # over a non-monotonic stat is meaningless, and the
                        # wrong TYPE hint poisons the scraper's view.
                        _GAUGES = {"max_rows"}
                        _HELP = {
                            "batches": "Lockstep decode batches started.",
                            "rows": "Rows ever admitted (initial + joins).",
                            "max_rows": "High-water mark of rows per batch.",
                            "joins": "Continuous-batching joins.",
                            "spec_rounds": "Batched speculative rounds.",
                            "spec_tokens": "Tokens advanced speculatively.",
                            "page_truncations": "Streams force-finished "
                            "at page exhaustion.",
                            "stream_errors": "Streams finished "
                            "finish_reason=error (worker failure).",
                            "cancelled": "Requests cancelled.",
                            "shed": "Submissions refused by load shedding.",
                            "quota_refusals": "Submissions refused by "
                            "per-tenant quotas (HTTP 429).",
                            "deadline_expired": "Requests past their "
                            "end-to-end deadline (queued or running).",
                            "epoch_stalls": "Backend dispatches abandoned "
                            "by the stuck-epoch watchdog.",
                            "prefix_hits": "Admissions/joins served a "
                            "cached prefix chain (--prefix-cache).",
                            "prefix_misses": "Admissions/joins with no "
                            "usable cached prefix (--prefix-cache).",
                        }
                        for k, v in sorted(api.engine.stats.items()):
                            kind = "gauge" if k in _GAUGES else "counter"
                            lines.append(
                                f"# HELP cake_engine_{k} "
                                f"{_HELP.get(k, 'Engine counter.')}"
                            )
                            lines.append(f"# TYPE cake_engine_{k} {kind}")
                            lines.append(f"cake_engine_{k} {v}")
                    # Cluster federation (obs/cluster.py): when workers have
                    # reported telemetry, the registry block becomes ONE
                    # merged exposition — every node's series under a
                    # ``node`` label (the master's own injected as
                    # node="master"). Single-process servers expose the
                    # local registry exactly as before.
                    from cake_tpu.obs.cluster import cluster

                    api._refresh_cluster()
                    if cluster.nodes():
                        registry_text = cluster.merged_exposition(
                            metrics.registry.dump()
                        )
                    else:
                        registry_text = metrics.registry.expose()
                    body = (
                        "\n".join(lines) + "\n" + registry_text
                    ).encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/plain; version=0.0.4"
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif route == "/events":
                    # Flight recorder: the bounded ring of request lifecycle
                    # events (submitted/admitted/joined/first-token/finished/
                    # worker-reconnect). ?request_id=<id> filters to one
                    # request's timeline — the id is the chat response id.
                    from cake_tpu.obs.cluster import cluster
                    from cake_tpu.utils import metrics

                    rid = query.get("request_id", [None])[0]
                    api._refresh_cluster()
                    if cluster.nodes():
                        # Cluster-wide interleave by ALIGNED time: worker
                        # event timestamps are shifted onto the master
                        # clock by each node's estimated offset.
                        events = cluster.merged_events(
                            metrics.flight.snapshot()
                        )
                        if rid is not None:
                            events = [
                                e for e in events
                                if e.get("request_id") == rid
                            ]
                    else:
                        events = metrics.flight.snapshot(request_id=rid)
                    self._json(
                        200,
                        {
                            "events": events,
                            "count": len(events),
                            "capacity": metrics.flight.capacity,
                            "cluster": cluster.nodes(),
                        },
                    )
                elif route == "/trace":
                    # Timeline profiler: the bounded span-tree ring rendered
                    # as Chrome trace-event JSON — save the body to a file
                    # and load it in Perfetto / chrome://tracing (lane
                    # tracks, engine spans, flow arrows, HBM counters).
                    # ?request_id=chatcmpl-... narrows to one request's
                    # spans; `cake-tpu trace --out t.json` wraps this route.
                    from cake_tpu.obs.timeline import timeline

                    rid = query.get("request_id", [None])[0]
                    if query.get("cluster", ["0"])[0] in ("1", "true"):
                        # ONE merged export: every reporting worker's
                        # timeline slice, clock-shifted onto the master's
                        # wall, so op spans nest inside the wire.<node>
                        # spans that caused them and flow arrows connect
                        # across process tracks (obs/cluster.py;
                        # `cake-tpu trace --cluster` wraps this).
                        from cake_tpu.obs.cluster import cluster

                        api._refresh_cluster()
                        self._json(
                            200,
                            cluster.merged_trace(
                                timeline.snapshot(rid), request_id=rid
                            ),
                        )
                    else:
                        self._json(200, timeline.export(rid))
                elif route == "/explain":
                    # Critical-path attribution (obs/critpath.py): where
                    # did this request's latency go — queue / prefill /
                    # decode / convoy / stall / wire — straight from the
                    # timeline ring. 400 without a request_id, 404 when
                    # the id has no spans left in the ring (evicted, shed
                    # before admission, or never existed);
                    # `cake-tpu explain` wraps this route.
                    from cake_tpu.obs import critpath
                    from cake_tpu.obs.timeline import timeline

                    rid = query.get("request_id", [None])[0]
                    if not rid:
                        self._json(
                            400,
                            {"error": "explain needs a request_id query "
                             "parameter (the chatcmpl-... response id)"},
                        )
                    else:
                        res = critpath.explain(timeline.snapshot(), rid)
                        if res is None:
                            self._json(
                                404,
                                {"error": f"no timeline spans for request "
                                 f"{rid!r}: evicted from the ring, refused "
                                 "before admission, or unknown"},
                            )
                        else:
                            audit = getattr(api.engine, "audit", None)
                            if audit is not None:
                                # Scheduler decision audit (obs/
                                # efficiency.py): WHY the scheduler
                                # queued/deferred/preempted this request,
                                # next to critpath's "how long".
                                res["decisions"] = audit.for_request(rid)
                            self._json(200, res)
                elif route == "/efficiency":
                    # Goodput & hardware-efficiency ledger
                    # (obs/efficiency.py): device-time buckets (sum to the
                    # measured device wall by construction), token goodput
                    # classes, per-tenant attribution, the analytic
                    # FLOPs/HBM roofline (MFU/MBU when device peaks are
                    # known), plus the scheduler decision-audit ring.
                    # `cake-tpu top` polls this next to /stats and /slo.
                    eff = getattr(api.engine, "efficiency", None)
                    if eff is None:
                        self._json(
                            404,
                            {"error": "efficiency ledger needs the batch "
                             "engine (--api-batch > 1)"},
                        )
                    else:
                        body = eff.snapshot()
                        audit = getattr(api.engine, "audit", None)
                        if audit is not None:
                            body["decision_ring"] = audit.snapshot(
                                limit=200
                            )
                        self._json(200, body)
                elif route == "/slo":
                    # Per-tenant SLO view (obs/slo.py): declared objectives,
                    # rolling fast/slow-window SLIs (TTFT p99, deadline hit
                    # rate, error/shed rates, goodput tok/s) and error-
                    # budget burn rates per tenant.
                    slo = getattr(api.engine, "slo", None)
                    if slo is None:
                        self._json(
                            404,
                            {"error": "SLO tracking needs the batch "
                             "engine (--api-batch > 1)"},
                        )
                    else:
                        self._json(200, slo.snapshot())
                elif route == "/requests":
                    # Traffic observatory (obs/requestlog.py): the bounded
                    # ring of per-request completion records — tenant,
                    # token counts, queue/TTFT/TPOT timing ladder, finish
                    # reason, SLO verdict, phase digest, decision causes.
                    # ?tenant= / ?finish= filter, ?since=<seq> is the tail
                    # cursor (`cake-tpu requests --follow` wraps it),
                    # ?limit= keeps the newest N. --request-log streams the
                    # same records to JSONL, the loadgen replay format.
                    reqlog = getattr(api.engine, "requestlog", None)
                    if reqlog is None:
                        self._json(
                            404,
                            {"error": "request log needs the batch "
                             "engine (--api-batch > 1)"},
                        )
                    else:
                        def _int_q(key):
                            raw = query.get(key, [None])[0]
                            if raw is None:
                                return None
                            try:
                                return int(raw)
                            except ValueError:
                                return None
                        recs = reqlog.snapshot(
                            tenant=query.get("tenant", [None])[0],
                            finish=query.get("finish", [None])[0],
                            since=_int_q("since"),
                            limit=_int_q("limit") or 0,
                        )
                        self._json(
                            200,
                            {
                                "requests": recs,
                                "count": len(recs),
                                **reqlog.stats(),
                            },
                        )
                elif route == "/timeseries":
                    # Rolling SLI time-series (obs/timeseries.py): the
                    # sliding window of per-bucket points (p50/p99 TTFT,
                    # tok/s, shed/429 rate) `cake-tpu top` renders as
                    # sparkline columns.
                    ts = getattr(api.engine, "timeseries", None)
                    if ts is None:
                        self._json(
                            404,
                            {"error": "SLI time-series needs the batch "
                             "engine (--api-batch > 1)"},
                        )
                    else:
                        self._json(200, ts.series())
                elif route == "/api/v1/models":
                    # OpenAI SDK model discovery (client.models.list()): the
                    # one loaded model, in the list-envelope shape.
                    self._json(
                        200,
                        {
                            "object": "list",
                            "data": [
                                {
                                    "id": api.model_name,
                                    "object": "model",
                                    "created": api._started,
                                    "owned_by": "cake-tpu",
                                }
                            ],
                        },
                    )
                elif route == "/stats":
                    # Observability: span timers (per-hop TCP latencies, local
                    # stage times) + host/device memory (utils/trace.py) +
                    # the metrics registry snapshot (histogram percentiles,
                    # counters, gauges — what `cake-tpu stats` renders) + the
                    # batch engine's admission counters under --api-batch.
                    from cake_tpu.obs import memwatch
                    from cake_tpu.obs.timeline import timeline
                    from cake_tpu.utils import metrics, trace

                    body = {
                        "model": api.model_name,
                        "uptime_s": round(time.time() - api._started, 3),
                        "spans": trace.spans.snapshot(),
                        # Structured span tree aggregate (total vs SELF time
                        # per span name) over the timeline ring — what
                        # `cake-tpu stats --spans` renders.
                        "timeline": timeline.aggregate(),
                        "memory": trace.memory_report(),
                        # Allocator-truth watermarks (obs/memwatch.py):
                        # host RSS + per-device HBM in-use/peak/limit, so
                        # `cake-tpu top`/`stats` see memory pressure next
                        # to pool occupancy without scraping /metrics.
                        "memwatch": {
                            "host_rss_bytes": memwatch.host_rss_bytes(),
                            "devices": memwatch.device_memory(),
                        },
                        "metrics": metrics.registry.snapshot(),
                    }
                    from cake_tpu.obs.cluster import cluster

                    api._refresh_cluster()
                    if cluster.nodes():
                        # Per-node federation summary (obs/cluster.py):
                        # clock offset + error bound, probe RTT, report
                        # freshness, headline op/byte telemetry — what
                        # `cake-tpu stats` renders as the per-node table.
                        body["cluster"] = cluster.snapshot()
                    if api.engine is not None:
                        body["engine"] = dict(api.engine.stats)
                        # Which scheduler shape is serving (README
                        # "Continuous scheduling") plus the spill table's
                        # current depth — preempted lanes parked host-side
                        # awaiting a restore.
                        body["engine"]["scheduler"] = getattr(
                            api.engine, "scheduler", "epoch"
                        )
                        spilled = getattr(api.engine, "_spilled", None)
                        if spilled is not None:
                            with api.engine._cv:
                                body["engine"]["spilled"] = len(spilled)
                        if hasattr(api.engine, "phase_stats"):
                            # Latency attribution aggregate + per-epoch
                            # convoy meter (the lockstep tax) — rendered
                            # by `cake-tpu stats` next to the tenant
                            # table; per-request detail at GET /explain.
                            body["phases"] = api.engine.phase_stats()
                        if hasattr(api.engine, "blackbox") and (
                            api.engine.blackbox is not None
                        ):
                            body["blackbox"] = api.engine.blackbox.stats()
                        if hasattr(api.engine, "slo"):
                            # Per-tenant SLO burn view (obs/slo.py; the
                            # full window detail lives at GET /slo).
                            body["slo"] = api.engine.slo.snapshot()
                        if hasattr(api.engine, "efficiency"):
                            # Goodput & hardware-efficiency headline
                            # (obs/efficiency.py; full bucket detail and
                            # the decision ring live at GET /efficiency).
                            body["efficiency"] = (
                                api.engine.efficiency.snapshot()
                            )
                        if hasattr(api.engine, "tenant_stats"):
                            # Per-tenant admission view (runtime/
                            # admission.py): queue depth, active streams,
                            # admitted work tokens, quota refusals, and the
                            # current token-bucket level per tenant.
                            body["tenants"] = api.engine.tenant_stats()
                        prefix = getattr(api.engine, "_prefix", None)
                        if prefix is not None:
                            # Persistent prefix cache (--prefix-cache):
                            # footprint, radix shape, hit/miss/eviction
                            # counters, and how many pages eviction could
                            # free right now (runtime/prefix_cache.py).
                            body["prefix"] = prefix.stats()
                    self._json(200, body)
                else:
                    self._json(404, {"error": "not found"})

            def do_POST(self):
                if self.path not in (CHAT_ROUTE, CANCEL_ROUTE):
                    # Reference returns a default 404 for everything else
                    # (api/mod.rs:105-107).
                    self._json(404, {"error": "not found"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(length) or b"{}")
                except (ValueError, json.JSONDecodeError) as e:
                    self._json(400, {"error": f"bad request body: {e}"})
                    return
                if self.path == CANCEL_ROUTE:
                    # Request cancellation: frees the lane's KV pages
                    # mid-epoch and stops its decode steps (runtime/
                    # serving.py cancel). The id is the chat response id.
                    rid = body.get("id") or body.get("request_id")
                    if not isinstance(rid, str) or not rid:
                        self._json(
                            400, {"error": "body needs a request 'id'"}
                        )
                        return
                    if api.engine is None:
                        self._json(
                            400,
                            {"error": "cancellation needs the batch "
                             "engine (--api-batch > 1)"},
                        )
                        return
                    self._json(
                        200, {"id": rid, "cancelled": api.engine.cancel(rid)}
                    )
                    return
                try:
                    response = api.handle_chat(body, self)
                except ApiError as e:
                    self._json(e.code, {"error": str(e)}, headers=e.headers)
                    return
                except Exception as e:  # noqa: BLE001 - surface as 500
                    log.exception("chat handler failed")
                    self._json(500, {"error": str(e)})
                    return
                if response is not None:
                    self._json(200, response)

        server = ThreadingHTTPServer((host, port), Handler)
        server.daemon_threads = True
        return server

    def serve_forever(self, host: str, port: int) -> None:
        server = self.make_server(host, port)
        log.info("API listening on http://%s:%d%s", host, port, CHAT_ROUTE)
        server.serve_forever()


class ApiError(Exception):
    def __init__(self, code: int, message: str,
                 headers: dict[str, str] | None = None):
        super().__init__(message)
        self.code = code
        self.headers = headers or {}


class _SseStream:
    """SSE emitter for chat.completion.chunk events.

    ``produce(on_token) -> finish_reason`` drives generation — a locked
    LlamaGenerator.generate or a BatchEngine stream handle — and the emitter
    owns only the wire format.
    """

    def __init__(self, api: ApiServer, produce, rid: str, created: int,
                 usage_fn=None):
        self.api = api
        self.produce = produce
        self.rid = rid
        self.created = created
        # stream_options {"include_usage": true}: () -> (prompt_tokens,
        # completion_tokens), read AFTER produce() returns so the counts
        # are final.
        self.usage_fn = usage_fn

    def _chunk(self, delta: dict, finish: str | None = None) -> bytes:
        payload = {
            "id": self.rid,
            "object": "chat.completion.chunk",
            "created": self.created,
            "model": self.api.model_name,
            "choices": [
                {"index": 0, "delta": delta, "finish_reason": finish}
            ],
        }
        return f"data: {json.dumps(payload)}\n\n".encode()

    def run(self, handler: BaseHTTPRequestHandler) -> None:
        """Stream the completion. Once headers are sent, errors are reported as
        an SSE error event (never a second HTTP response into the open chunked
        stream) and the stream is terminated cleanly.

        Writes run under a socket timeout: a client that stops reading raises
        socket.timeout once the TCP send buffer fills, aborting the stream
        instead of blocking forever while holding the generator lock. The
        original timeout is restored afterwards so keep-alive reuse of the
        connection is unaffected."""
        prev_timeout = handler.connection.gettimeout()
        handler.connection.settimeout(self.api.stream_write_timeout)
        try:
            self._run_stream(handler)
        finally:
            try:
                handler.connection.settimeout(prev_timeout)
            except OSError:
                pass

    def _run_stream(self, handler: BaseHTTPRequestHandler) -> None:
        handler.send_response(200)
        handler.send_header("Content-Type", "text/event-stream")
        handler.send_header("Cache-Control", "no-cache")
        handler.send_header("Transfer-Encoding", "chunked")
        handler.end_headers()

        def write(data: bytes) -> None:
            spec = faults.check("api.stream")
            if spec is not None and spec.kind == "stall":
                faults.sleep(spec)  # a consumer that stopped reading
            handler.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")

        try:
            write(self._chunk({"role": "assistant", "content": ""}))

            def on_token(tok: Token) -> None:
                if tok.text:
                    write(self._chunk({"content": tok.text}))

            finish = self.produce(on_token)
            write(self._chunk({}, finish=finish))
            if self.usage_fn is not None:
                # OpenAI shape: the usage chunk carries empty choices and
                # sits between the finish chunk and [DONE].
                n_prompt, n_completion = self.usage_fn()
                payload = {
                    "id": self.rid,
                    "object": "chat.completion.chunk",
                    "created": self.created,
                    "model": self.api.model_name,
                    "choices": [],
                    "usage": {
                        "prompt_tokens": n_prompt,
                        "completion_tokens": n_completion,
                        "total_tokens": n_prompt + n_completion,
                    },
                }
                write(f"data: {json.dumps(payload)}\n\n".encode())
        except (BrokenPipeError, ConnectionResetError, TimeoutError):
            # Client went away or stopped reading mid-stream; abandon it. The
            # chunked stream was never terminated, so the connection cannot be
            # reused — without close_connection the keep-alive loop would block
            # in readline() on the dead socket forever. With a batch engine,
            # also CANCEL the request so the abandoned stream stops burning
            # decode steps and returns its KV pages mid-epoch.
            log.warning("client %s stalled or disconnected mid-stream",
                        handler.client_address)
            self.api._client_gone(self.rid)
            handler.close_connection = True
            return
        except Exception as e:  # noqa: BLE001 - surface in-band
            log.exception("generation failed mid-stream")
            try:
                write(f"data: {json.dumps({'error': str(e)})}\n\n".encode())
            except (BrokenPipeError, ConnectionResetError, TimeoutError, OSError):
                # Client is gone too; never let this propagate to do_POST,
                # which would inject a second HTTP response into the open
                # chunked stream.
                handler.close_connection = True
                return
        try:
            write(b"data: [DONE]\n\n")
            handler.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError, TimeoutError):
            # Terminator never reached the client; drop the connection rather
            # than reuse a stream with no final chunk.
            handler.close_connection = True
