"""Concurrent batched serving: a request queue feeding lockstep batch decode.

The reference serializes API requests behind a global write lock (api/mod.rs:76)
— SURVEY.md §2.6 calls that a quirk, not a contract. This module replaces the
lock with a scheduler: HTTP handler threads ``submit()`` requests into a queue;
one engine thread drains it, groups requests whose sampling knobs compile to
the same fused-decode trace, left-pads the group into ONE batch (the
models/llama/batch.py layout), and decodes all rows in lockstep — streaming
each row's tokens to its own consumer as every chunk lands.

Batching is CONTINUOUS: an epoch owns ``max_batch`` fixed lockstep lanes, and
at every decode-chunk boundary finished lanes free up and queued requests with
the same sampling knobs join the RUNNING epoch — a single-row prefill,
left-padded to end at the epoch's shared slot, scattered into the free lane's
KV row. Nobody waits for the batch to drain (vLLM-style admission, minus
paging: lanes are fixed-shape cache rows).

Per-request correctness is exact, not approximate:
  * Every row carries its OWN PRNG key (ops/sampling.sample_per_row), split
    per step exactly like LlamaGenerator's host loop — so row r's token stream
    is bit-identical to a single-request run with row r's seed, regardless of
    what else happens to share the batch. Tests pin this oracle.
  * Per-row repeat-penalty rings, budgets (max_tokens), and EOS: a finished
    row's lockstep lane computes discarded garbage until the batch drains
    (bounded by the chunk size times remaining rows' budgets).
  * Requests whose knobs differ (temperature/top-k/top-p/penalty — compiled
    into the trace) are NOT merged; they run as separate consecutive batches.

Decode FLOPs grow ~linearly with rows while weight HBM traffic stays constant,
so on TPU a batch of B requests streams at nearly the single-request rate for
each of them — aggregate throughput scales until the MXU saturates.

Failure semantics (README "Failure semantics"): finish reasons are
``stop`` / ``length`` / ``error`` / ``cancelled`` / ``deadline``. A worker
failure that exhausts the wire retry/replay budget (BackendWorkerError)
finishes only the epoch's live streams as ``error`` — already-finished
co-batched streams were bit-identical to a fault-free run — and the engine
keeps serving. ``cancel(request_id)`` ends a queued request immediately or
a running one at the next chunk boundary, returning its KV pages mid-epoch.
Admission sheds (``EngineOverloaded`` -> HTTP 503 + Retry-After) at the
configured queue depth / free-page floor. Fault checkpoints
(runtime/faults.py ``backend.*`` sites) make all of it deterministically
testable on any backend.

Admission SLOs (README "Admission control & SLOs", runtime/admission.py):
every request carries a tenant — per-tenant token-bucket quotas and stream
caps refuse with ``QuotaExceeded`` (HTTP **429** + Retry-After, distinct
from the 503 shed), and the queue itself is deficit-weighted round-robin
across tenant subqueues so one tenant's flood cannot starve another's
admissions or joins. ``deadline_s`` is an end-to-end SLO: queued requests
expire BEFORE admission (no lane, no pages), running streams finish
``"deadline"`` at chunk boundaries, and doomed submissions (deadline below
the estimated queue wait) are shed outright. ``epoch_stall_s`` arms the
stuck-epoch watchdog: a backend dispatch that neither returns nor raises
within the bound is abandoned and isolated through the same
BackendWorkerError path a dead worker takes.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from collections import deque
from typing import Iterator

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.batch import prompt_bucket
from cake_tpu.models.llama.chat import Message, encode_dialog
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import SamplingConfig, Token, decode_delta
from cake_tpu.models.llama.tokenizer import Tokenizer
from cake_tpu.obs import memwatch
from cake_tpu.obs.timeline import timeline
from cake_tpu.runtime import faults
from cake_tpu.runtime.admission import (
    DEFAULT_TENANT,
    FairQueue,
    QuotaExceeded,
    StallGuard,
    TenantMeter,
    WaitEstimator,
)
from cake_tpu.utils import metrics

__all__ = [
    "BatchEngine", "EngineOverloaded", "QuotaExceeded", "ServeConfig",
    "StreamHandle",
]

log = logging.getLogger("cake_tpu.serving")

_DONE = "__done__"

# Epoch attention-capacity granularity (slots): the bounded paged capacity
# rounds up to this, so compiled-shape variants stay bounded the way 64-slot
# width bucketing bounds join/suffix windows (coarser here — capacity feeds
# whole kernel grids, not one window operand).
_CAPACITY_BUCKET = 256


class EngineOverloaded(RuntimeError):
    """Admission refused by load shedding (queue depth / pool pressure).

    The API layer maps this to HTTP 503 with a ``Retry-After`` header —
    the SLO-aware refusal the multi-core NPU serving study frames: under
    overload, shedding one request early beats queueing it into a timeout.
    """

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Aggregated serving-engine knobs (one object the CLI/API layers build).

    ``kv_mode="paged"`` swaps the default local backend for the paged KV pool
    (runtime/batch_backend.PagedLocalBackend) and switches admission/join
    accounting from fixed lanes to free pages: a request is admitted iff
    ``ceil(prompt / page_size) + page_reserve`` pages are free, decode
    allocates pages incrementally at page boundaries, and finished streams
    return their pages to the pool. ``max_pages`` sizes the pool — set it
    BELOW ``max_batch * pages_per_seq`` to serve more concurrent short
    requests than the dense footprint admits at the same HBM (the capacity
    win pinned in tests/test_paged_serving.py); None keeps the dense-
    equivalent footprint (pure-parity mode).
    """

    max_batch: int = 8
    decode_chunk_size: int = 8
    admission_window: float = 0.01
    # Scheduler shape (README "Continuous scheduling"):
    #   * "epoch"      — the lockstep epoch: admission groups land together,
    #     joins at chunk boundaries, starved streams force-finish "length".
    #   * "continuous" — the per-step scheduler: no admission-window sleep,
    #     queued requests join the moment lanes/pages free (bounded by the
    #     SLO-aware per-step prefill budget), finished lanes retire
    #     immediately, and page pressure PREEMPTS the lowest-priority lane
    #     (its page chain spills host-side as history + sampling state and
    #     re-attaches later through the suffix-prefill arithmetic,
    #     bit-identical) instead of force-finishing it. Streams are
    #     bit-identical to epoch mode given the same admission order.
    scheduler: str = "epoch"  # "epoch" | "continuous"
    # Continuous mode: prompt tokens of join/restore prefill work one step
    # may dispatch before decode resumes. 0 = auto (runtime/admission.py
    # StepBudget: a base grant scaled UP while TTFT burn says the queue is
    # missing its objective and DOWN while a live stream's deadline slack
    # is inside a few chunks).
    step_prefill_tokens: int = 0
    # Prefer grouping queued requests that extend the SAME cached prefix
    # radix path into one epoch/step (prefix cache only): the shared chain
    # is forked while it is hot instead of being evicted between epochs.
    # Candidates outside the head's radix group stay queued for the next
    # epoch — a bounded deferral inside the DRR walk, never starvation.
    cache_aware_order: bool = True
    kv_mode: str = "dense"  # "dense" | "paged"
    page_size: int = 128
    max_pages: int | None = None
    page_reserve: int = 1
    # Decode hot-path op fusion (ops/fuse.py parse_fusion_spec): "none", or
    # "<set>[@impl]" with set ⊆ {norm, ingest, tail} (or "all") and impl ∈
    # {auto, pallas, xla}. Applied to the engine's model config
    # (LlamaConfig.fusion_impl) when the engine builds its own backend; an
    # explicit backend= keeps whatever its config says. Streams are
    # bit-identical fused or unfused (README "Decode fusion").
    fusion_impl: str = "none"
    # ---- failure semantics (README "Failure semantics") ----
    # Per-op wire deadline + idempotent-resend budget for TCP backends
    # (runtime/client.py), and reconnect attempts/backoff after a dead
    # socket. These thread into StageClient via the CLI / master kwargs.
    op_deadline_s: float = 30.0
    op_retries: int = 2
    reconnect_attempts: int = 3
    reconnect_backoff_s: float = 0.5
    # Heartbeat probing of workers (runtime/client.HeartbeatMonitor);
    # 0 = no probe threads.
    heartbeat_interval_s: float = 0.0
    heartbeat_deadline_s: float = 2.0
    # Admission load shedding: refuse (HTTP 503 + Retry-After) instead of
    # queueing without bound. 0 disables each gate. Gates scale with the
    # request's priority class (0 = low, 1 = normal, 2 = high): low sheds
    # first (at half the depth / twice the page floor) and waits longer
    # (Retry-After doubles); high tolerates twice the depth.
    shed_queue_depth: int = 0       # shed when the queue is this deep
    shed_min_free_pages: int = 0    # paged only: shed when the pool is this dry
    retry_after_s: float = 1.0      # hint returned with a shed
    default_priority: int = 1      # requests without an explicit class
    # ---- replica failover (README "Failover") ----
    # When a worker dies mid-epoch (BackendWorkerError) and a healthy
    # replica exists (runtime/router.py), the engine MIGRATES live streams:
    # re-prefills each stream's accumulated tokens through the new route and
    # resumes decode — greedy streams stay bit-identical to a fault-free
    # run. Bounded: at most ``max_failovers`` migrations per epoch within
    # ``failover_budget_s`` of cumulative migration wall time; past either
    # bound (or with no healthy replica) the epoch falls back to PR 6's
    # ``finish_reason="error"`` isolation. ``failover_local`` opts
    # replica-less (local/tp/mesh) backends into migration-in-place for
    # transient faults; ``failover_cooldown_s`` is the router's standby
    # rejoin probation (0 = none: an ejected member is immediately
    # eligible again, so a permanently dead worker is re-probed — and
    # re-ejected — every epoch; keep a real cooldown in production).
    max_failovers: int = 2
    failover_budget_s: float = 30.0
    failover_local: bool = False
    failover_cooldown_s: float = 5.0
    # SSE streaming backpressure: a consumer that stops reading leaves its
    # tokens queued in the stream handle; past this many buffered tokens the
    # stream is cancelled (the PR 6 cancel path — pages freed, lane
    # recycled) instead of growing memory without bound. 0 = unbounded.
    stream_buffer_tokens: int = 0
    # ---- persistent prefix cache (README "Prefix caching") ----
    # kv_mode="paged" only: finished prompts leave their prefix KV page
    # chains in a radix cache (runtime/prefix_cache.py); a later request
    # sharing the prefix forks the chain into its lane (refcounted CoW) and
    # prefills only the uncached suffix — admission charges only that
    # suffix, and the shed gate counts evictable cache pages as available.
    prefix_cache: bool = False
    # Cache budget in pages; 0 = auto (half the pool). Inserts evict LRU
    # unpinned chains past it; pool pressure evicts on demand.
    prefix_cache_pages: int = 0
    # Don't cache or serve prefixes shorter than this many tokens (churn
    # guard); 0 = any full page's worth qualifies.
    prefix_min_tokens: int = 0
    # ---- per-tenant admission & SLOs (README "Admission control & SLOs",
    # runtime/admission.py) ----
    # Token-bucket rate limit per tenant, in work tokens (prompt +
    # max_tokens) per second; refusal = HTTP 429 + Retry-After (distinct
    # from the 503 shed). 0 = unlimited.
    tenant_rate: float = 0.0
    # Bucket capacity in work tokens; 0 = auto (2x tenant_rate).
    tenant_burst: float = 0.0
    # Concurrent (queued + live) streams per tenant; 0 = uncapped.
    tenant_streams: int = 0
    # Deficit-weighted round-robin across tenant subqueues — a burst from
    # one tenant cannot starve another's admissions/joins. False = the old
    # global FIFO (the A/B the overload-storm chaos gate measures). With a
    # single tenant both schedules are identical.
    fair_queue: bool = True
    # DRR quantum in cost tokens per scheduling visit (cost = (prompt +
    # max_tokens) scaled down by the priority factor).
    fair_quantum: int = 256
    # End-to-end deadline applied to requests that carry none; 0 = none.
    # Queued requests expire BEFORE admission (no lane, no pages), running
    # streams expire at chunk boundaries (finish_reason="deadline", pages
    # freed); submissions whose deadline is already smaller than the
    # estimated queue wait are shed immediately (503).
    default_deadline_s: float = 0.0
    # Stuck-epoch watchdog: a backend dispatch making no progress within
    # this bound is abandoned and isolated through the failover/"error"
    # path (runtime/admission.StallGuard). 0 = off.
    epoch_stall_s: float = 0.0
    # ---- declared SLOs + burn tracking (README "Cluster observability &
    # SLOs", obs/slo.py) ----
    # TTFT objective in milliseconds: slo_ttft_target of accepted requests
    # must see their first token within it. 0 = no TTFT objective (the
    # tracker still records per-tenant SLIs; burn rates need an objective).
    slo_ttft_ms: float = 0.0
    slo_ttft_target: float = 0.99
    # Deadline objective: required hit rate over deadline-carrying
    # requests. 0 = off.
    slo_deadline_rate: float = 0.0
    # Burn-rate windows (fast must not exceed slow): the multiwindow rule —
    # feedback fires only while BOTH windows burn.
    slo_fast_window_s: float = 60.0
    slo_slow_window_s: float = 600.0
    # Feed SLO burn back into admission (FairQueue quantum weights +
    # WaitEstimator shed scaling); False = observe/graph only.
    slo_feedback: bool = True
    # ---- black-box anomaly capture (README "Latency attribution &
    # black-box diagnostics", obs/blackbox.py) ----
    # Directory for diagnostic bundles; None/"" = capture off. A bundle is
    # written when a request breaches a declared SLO objective, lands past
    # blackbox_p99_mult x the rolling e2e p99, or dies to a watchdog stall
    # / failover / whole-epoch error — `cake-tpu doctor` renders it.
    blackbox_dir: str | None = None
    # On-disk ring bound: keep only the newest N bundles.
    blackbox_keep: int = 16
    # Global min seconds between captures (an incident storm writes one
    # bundle, not a disk full); 0 = no rate limit.
    blackbox_min_interval_s: float = 5.0
    # Rolling-p99 outlier multiplier (0 = trigger off): a finished request
    # slower than K x the rolling end-to-end p99 captures a bundle.
    blackbox_p99_mult: float = 0.0
    # ---- goodput & hardware efficiency (README "Goodput & hardware
    # efficiency", obs/efficiency.py) ----
    # Device peaks the MFU/bandwidth-utilization roofline divides by.
    # 0 = auto: the built-in table keyed by the visible device kind; on
    # CPU (no table entry) the /efficiency snapshot reports absolute
    # achieved numbers only.
    peak_tflops: float = 0.0
    peak_hbm_gbps: float = 0.0

    def __post_init__(self):
        if self.kv_mode not in ("dense", "paged"):
            raise ValueError(f"kv_mode must be dense|paged, got {self.kv_mode}")
        if self.scheduler not in ("epoch", "continuous"):
            raise ValueError(
                f"scheduler must be epoch|continuous, got {self.scheduler}"
            )
        if self.step_prefill_tokens < 0:
            raise ValueError(
                f"step_prefill_tokens must be >= 0 (0 = auto), got "
                f"{self.step_prefill_tokens}"
            )
        from cake_tpu.ops.fuse import parse_fusion_spec

        parse_fusion_spec(self.fusion_impl)  # raises on a malformed spec
        if self.page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {self.page_size}")
        if self.op_deadline_s <= 0:
            raise ValueError(
                f"op_deadline_s must be positive, got {self.op_deadline_s}"
            )
        if self.op_retries < 0 or self.reconnect_attempts < 1:
            raise ValueError(
                "op_retries must be >= 0 and reconnect_attempts >= 1, got "
                f"{self.op_retries}/{self.reconnect_attempts}"
            )
        if self.shed_queue_depth < 0 or self.shed_min_free_pages < 0:
            raise ValueError("shed thresholds must be >= 0 (0 = off)")
        if self.default_priority not in (0, 1, 2):
            raise ValueError(
                f"default_priority must be 0|1|2, got {self.default_priority}"
            )
        if self.max_failovers < 0 or self.failover_budget_s <= 0:
            raise ValueError(
                "max_failovers must be >= 0 and failover_budget_s positive, "
                f"got {self.max_failovers}/{self.failover_budget_s}"
            )
        if self.failover_cooldown_s < 0 or self.stream_buffer_tokens < 0:
            raise ValueError(
                "failover_cooldown_s and stream_buffer_tokens must be >= 0"
            )
        if self.prefix_cache and self.kv_mode != "paged":
            raise ValueError(
                "prefix_cache shares physical KV pages across requests and "
                "therefore needs kv_mode='paged'"
            )
        if self.prefix_cache_pages < 0 or self.prefix_min_tokens < 0:
            raise ValueError(
                "prefix_cache_pages and prefix_min_tokens must be >= 0"
            )
        if (
            self.tenant_rate < 0
            or self.tenant_burst < 0
            or self.tenant_streams < 0
        ):
            raise ValueError(
                "tenant_rate, tenant_burst and tenant_streams must be >= 0 "
                "(0 = gate off)"
            )
        if self.fair_quantum < 1:
            raise ValueError(
                f"fair_quantum must be >= 1, got {self.fair_quantum}"
            )
        if self.default_deadline_s < 0 or self.epoch_stall_s < 0:
            raise ValueError(
                "default_deadline_s and epoch_stall_s must be >= 0 (0 = off)"
            )
        if (
            self.slo_fast_window_s <= 0
            or self.slo_slow_window_s < self.slo_fast_window_s
        ):
            raise ValueError(
                "slo windows need 0 < fast <= slow, got "
                f"{self.slo_fast_window_s}/{self.slo_slow_window_s}"
            )
        # slo_ttft_ms / targets validate in SloObjectives (obs/slo.py) —
        # constructed eagerly here so a bad flag fails at config time.
        from cake_tpu.obs.slo import SloObjectives

        SloObjectives(
            ttft_ms=self.slo_ttft_ms,
            ttft_target=self.slo_ttft_target,
            deadline_rate=self.slo_deadline_rate,
        )
        if self.blackbox_keep < 1:
            raise ValueError(
                f"blackbox_keep must be >= 1, got {self.blackbox_keep}"
            )
        if self.blackbox_min_interval_s < 0 or self.blackbox_p99_mult < 0:
            raise ValueError(
                "blackbox_min_interval_s and blackbox_p99_mult must be >= 0"
            )
        if self.peak_tflops < 0 or self.peak_hbm_gbps < 0:
            raise ValueError(
                "peak_tflops and peak_hbm_gbps must be >= 0 (0 = auto)"
            )
        if self.page_reserve < 1:
            # The admission charge is ceil(prompt/page_size) + reserve, but a
            # left-padded window straddling a page boundary can MAP one page
            # more than ceil(prompt/page_size); reserve >= 1 is what makes
            # the charge an upper bound, so epoch-start allocation can never
            # outrun what admission accounted for.
            raise ValueError(
                f"page_reserve must be >= 1, got {self.page_reserve}"
            )


@dataclasses.dataclass
class _Request:
    prompt_ids: list[int]
    max_tokens: int
    sampling: SamplingConfig
    handle: "StreamHandle"
    # Request-scoped telemetry: the trace id rides the wire frames
    # (runtime/proto.py) and keys the flight-recorder lifecycle; the
    # timestamps feed the queue-wait / TTFT / inter-token histograms.
    rid: str = ""
    t_submit: float = 0.0
    t_last_token: float = 0.0
    # Latency attribution stamps (obs/critpath.py): when the request left
    # the queue (perf_counter; 0 = not yet) and how long submit()'s
    # admission gates (quota + shed) took — both ride the request span's
    # args so GET /explain can decompose queue vs admission time.
    t_admit: float = 0.0
    admit_s: float = 0.0
    # Priority class (0 low / 1 normal / 2 high): scales the shedding
    # gates and the Retry-After hint — low sheds first under overload.
    priority: int = 1
    # Per-tenant admission (runtime/admission.py): the fair queue's
    # subqueue key and the quota-accounting label.
    tenant: str = DEFAULT_TENANT
    # Absolute end-to-end deadline (time.monotonic clock); 0.0 = none.
    # Queued past it -> expired before admission; running past it ->
    # finish_reason="deadline" at the next chunk boundary.
    deadline: float = 0.0

    def knobs(self) -> tuple:
        # Trace compatibility = batch compatibility (SamplingConfig.trace_knobs).
        return self.sampling.trace_knobs()


class StreamHandle:
    """Consumer side of one submitted request.

    ``tokens()`` yields Token objects as the engine produces them and returns
    once the stream finishes; ``text()`` blocks to completion. An engine-side
    failure re-raises here.
    """

    def __init__(self, n_prompt: int, request_id: str = ""):
        self.prompt_tokens = n_prompt
        self.completion_tokens = 0
        self.finish_reason: str = "length"
        self.request_id = request_id
        self._events: deque = deque()
        self._cv = threading.Condition()
        # Fired exactly once when the stream terminates (a _DONE or an
        # exception lands) — the ONE choke point every finish path funnels
        # through, which is what lets the tenant meter release the stream's
        # quota slot without every caller remembering to.
        self._on_close = None

    def buffered(self) -> int:
        """Events produced but not yet consumed — the per-client output
        buffer the streaming backpressure watermark bounds."""
        with self._cv:
            return len(self._events)

    # -- engine side -------------------------------------------------------
    def _emit(self, item) -> None:
        cb = None
        with self._cv:
            self._events.append(item)
            self._cv.notify()
            if item is _DONE or isinstance(item, Exception):
                cb, self._on_close = self._on_close, None
        if cb is not None:
            cb()

    # -- consumer side -----------------------------------------------------
    def tokens(self) -> Iterator[Token]:
        while True:
            with self._cv:
                while not self._events:
                    # Deliberately unbounded: the CONSUMER blocks on the
                    # engine, whose own liveness is what the stall watchdog
                    # and deadline machinery bound — a timeout here would
                    # turn backpressure into spurious stream errors.
                    self._cv.wait()  # cake-lint: disable=unbounded-wait
                item = self._events.popleft()
            if item is _DONE:
                return
            if isinstance(item, Exception):
                raise item
            yield item

    def text(self) -> str:
        return "".join(t.text for t in self.tokens())


class BatchEngine:
    """One device-owning thread serving many concurrent requests.

    Device execution goes through a batch backend (runtime/batch_backend.py):
    local single-device by default, or tensor-parallel / in-mesh pipelined —
    continuous batching composes with the model-parallel deployment modes
    instead of falling back to the serialized generator path.
    """

    def __init__(
        self,
        config: LlamaConfig,
        params: M.Params | None,
        tokenizer: Tokenizer,
        *,
        max_seq_len: int | None = None,
        cache_dtype: jnp.dtype = jnp.bfloat16,
        decode_chunk_size: int = 8,
        max_batch: int = 8,
        admission_window: float = 0.01,
        backend=None,
        speculative_k: int = 0,
        proposer_factory=None,
        serve: "ServeConfig | None" = None,
    ):
        if (
            serve is not None
            and serve.fusion_impl != getattr(config, "fusion_impl", "none")
            and serve.fusion_impl != "none"
        ):
            # The aggregate knob surface wins (as for the other ServeConfig
            # fields): thread the fusion spec onto the model config BEFORE
            # any backend closes over it. Only effective when the engine
            # builds its own (local/paged) backend below — an explicit
            # backend= already baked its config at construction.
            config = dataclasses.replace(config, fusion_impl=serve.fusion_impl)
        self.config = config
        self.tokenizer = tokenizer
        self.max_seq_len = int(max_seq_len or config.max_position_embeddings)
        self.cache_dtype = cache_dtype
        if serve is not None:
            # The aggregate knob object wins over the individual kwargs it
            # covers (callers pass one or the other, not both).
            decode_chunk_size = serve.decode_chunk_size
            max_batch = serve.max_batch
            admission_window = serve.admission_window
        kv_mode = serve.kv_mode if serve is not None else "dense"
        # Scheduler shape (README "Continuous scheduling"): "epoch" keeps
        # the lockstep epoch; "continuous" admits per step, retires lanes
        # immediately, and preempts (spills) instead of force-finishing.
        self.scheduler = serve.scheduler if serve is not None else "epoch"
        self.cache_aware_order = (
            serve.cache_aware_order if serve is not None else True
        )
        from cake_tpu.runtime.admission import StepBudget

        self._step_budget = StepBudget(
            serve.step_prefill_tokens if serve is not None else 0
        )
        # Host-side spill table (continuous mode): rid -> _SpilledLane. A
        # preempted lane's pages are gone; its history + sampling state
        # wait here until pages free and a restore re-attaches them. Listed
        # in _STEP_STATE: every mutation holds the engine cv (the
        # step-state-unlocked lint rule) — submit/cancel/deadline threads
        # and the engine thread all reach it.
        self._spilled: dict[str, "_SpilledLane"] = {}
        # Admission load shedding (ServeConfig): 0 = each gate off.
        self.shed_queue_depth = serve.shed_queue_depth if serve else 0
        self.shed_min_free_pages = serve.shed_min_free_pages if serve else 0
        self.retry_after_s = serve.retry_after_s if serve else 1.0
        self.default_priority = serve.default_priority if serve else 1
        # Replica failover bounds + streaming backpressure (ServeConfig).
        self.max_failovers = serve.max_failovers if serve else 2
        self.failover_budget_s = serve.failover_budget_s if serve else 30.0
        self.failover_local = serve.failover_local if serve else False
        self.stream_buffer_tokens = serve.stream_buffer_tokens if serve else 0
        # Per-epoch failover accounting (engine thread only; reset per epoch).
        self._fo_count = 0
        self._fo_spent_s = 0.0
        if backend is None:
            if params is None:
                # Fail here, not later inside a jitted prefill with an opaque
                # tracer error: params may be None only when an explicit
                # backend already owns the placed weights.
                raise ValueError(
                    "BatchEngine needs either params (for the default local "
                    "backend) or an explicit backend="
                )
            if kv_mode == "paged":
                from cake_tpu.runtime.batch_backend import PagedLocalBackend

                pages_per_seq = -(-self.max_seq_len // serve.page_size)
                backend = PagedLocalBackend(
                    config, params,
                    max_seq_len=self.max_seq_len, cache_dtype=cache_dtype,
                    page_size=serve.page_size,
                    max_pages=serve.max_pages
                    or max(1, max_batch) * pages_per_seq,
                    page_reserve=serve.page_reserve,
                )
            else:
                from cake_tpu.runtime.batch_backend import LocalBatchBackend

                backend = LocalBatchBackend(
                    config, params,
                    max_seq_len=self.max_seq_len, cache_dtype=cache_dtype,
                )
        elif kv_mode == "paged" and getattr(backend, "kv_mode", "dense") != "paged":
            raise ValueError(
                "kv_mode='paged' needs a paged backend "
                "(runtime/batch_backend.PagedLocalBackend); the "
                f"provided {type(backend).__name__} is dense"
            )
        self.backend = backend
        # Thread the wire-resilience knobs into a TCP backend's live
        # clients (ServeConfig is the ONE config surface; without this the
        # fields would validate and then silently do nothing for
        # programmatic engines — the CLI threads the same values into
        # DistributedForwardStep at construction, so this is idempotent).
        self._hb_clients = getattr(
            getattr(backend, "step", None), "clients", {}
        )
        if serve is not None:
            for c in self._hb_clients.values():
                if hasattr(c, "configure"):
                    c.configure(
                        op_deadline_s=serve.op_deadline_s,
                        op_retries=serve.op_retries,
                        reconnect_attempts=serve.reconnect_attempts,
                        reconnect_backoff_s=serve.reconnect_backoff_s,
                    )
        self.heartbeat_interval_s = serve.heartbeat_interval_s if serve else 0.0
        self.heartbeat_deadline_s = serve.heartbeat_deadline_s if serve else 2.0
        self.monitor = None  # HeartbeatMonitor, started with the engine
        # Replica router (TCP backends only): owns per-epoch route choice,
        # ejection, and standby rejoin (runtime/router.py); the engine
        # threads its cooldown knob and heartbeat monitor into it.
        self._router = getattr(
            getattr(backend, "step", None), "router", None
        )
        if self._router is not None and serve is not None:
            self._router.cooldown_s = serve.failover_cooldown_s
        # Paged accounting seam: the allocator (when the backend has one)
        # drives admission, page growth, and release; None = dense lanes.
        self._alloc = getattr(backend, "allocator", None)
        self.kv_mode = getattr(backend, "kv_mode", "dense")
        # Persistent prefix cache (runtime/prefix_cache.py): fork shared
        # prompt-prefix page chains at admission, prefill only the uncached
        # suffix, insert/refresh chains on finish. Paged local backend only
        # — the cache IS pool pages, and the suffix path needs the paged
        # cached-chunk prefill.
        self._prefix = None
        if serve is not None and serve.prefix_cache:
            if self._alloc is None or not hasattr(backend, "suffix_prefill"):
                raise ValueError(
                    "prefix_cache needs a paged backend with suffix-prefill "
                    "support (runtime/batch_backend.PagedLocalBackend); "
                    f"{type(backend).__name__} has neither"
                )
            from cake_tpu.runtime.prefix_cache import PrefixCache

            self._prefix = PrefixCache(
                self._alloc,
                max_pages=serve.prefix_cache_pages
                or max(1, self._alloc.pages_total // 2),
                min_tokens=serve.prefix_min_tokens,
            )
            backend.attach_prefix_cache(self._prefix)
        # Per-lane chain pins for the CURRENT epoch (engine thread only):
        # released when the lane's pages return to the pool. ``_lane_info``
        # remembers each real lane's (request, pad) so insert-on-release can
        # adopt the prompt-prefix chain without the _RowState (which is gone
        # by the time the pages actually free).
        self._lane_leases: dict[int, object] = {}
        self._lane_info: dict[int, tuple[_Request, int]] = {}
        # True once the current epoch reached its clean end and retained the
        # pool buffer; a failed epoch leaves it False and the finally path
        # clears the cache (chains must never outlive their bytes).
        self._epoch_kv_retained = False
        self.decode_chunk_size = max(1, decode_chunk_size)
        self.max_batch = max(1, max_batch)
        self.admission_window = admission_window
        # > 0 enables batched prompt-lookup speculative decoding: every row
        # drafts K tokens from ITS OWN history, one shared cached-chunk
        # forward verifies all rows, and the epoch advances by the MINIMUM
        # accepted length across live rows (models/llama/batch.py speculative
        # section). Greedy rows stay byte-identical; sampled rows keep the
        # exact plain-decode distribution. Requires repeat_penalty == 1.0 and
        # a backend exposing verify_greedy/verify_sampled.
        self.speculative_k = max(0, speculative_k)
        # Optional drafting seam: a zero-arg callable building one proposer
        # PER LANE (models/llama/speculative.py — LookupProposer,
        # DraftModelProposer). Lane proposers persist across row joins: a
        # DraftModelProposer resyncs to the new row's history by common
        # prefix, so no invalidation protocol is needed. None = prompt
        # lookup, the stateless default.
        self.proposer_factory = proposer_factory
        self._lane_proposers: dict[int, object] = {}
        # Resolved lazily from the first factory product: an object with
        # ``propose_batch`` drafts EVERY lane in one pair of batched
        # dispatches (BatchedDraftModelProposer); otherwise one per-lane
        # proposer per lane (2 dispatches per lane per round).
        self._batched_proposer = None
        self._proposer_mode: str | None = None
        self._spare_proposer = None
        # Per-tenant admission (runtime/admission.py): quota meter (429s),
        # fair queue (DRR across tenant subqueues — the old global FIFO
        # when fair_queue=False or a single tenant), queue-wait estimator
        # (deadline-aware shedding), stuck-epoch watchdog.
        self.tenant_meter = TenantMeter(
            rate=serve.tenant_rate if serve else 0.0,
            burst=serve.tenant_burst if serve else 0.0,
            max_streams=serve.tenant_streams if serve else 0,
        )
        self._queue: FairQueue = FairQueue(
            fair=serve.fair_queue if serve else True,
            quantum=serve.fair_quantum if serve else 256,
            cost=self._req_cost,
        )
        self._wait_est = WaitEstimator()
        # Per-tenant SLO tracking + burn-rate feedback (obs/slo.py, README
        # "Cluster observability & SLOs"): SLIs record unconditionally;
        # burn rates need declared objectives (slo_ttft_ms /
        # slo_deadline_rate), and feedback (fair-queue quantum weights +
        # shed-estimate scaling) applies about once a second from the
        # scheduler loop.
        from cake_tpu.obs.slo import SloObjectives, SloTracker

        self.slo = SloTracker(
            SloObjectives(
                ttft_ms=serve.slo_ttft_ms if serve else 0.0,
                ttft_target=serve.slo_ttft_target if serve else 0.99,
                deadline_rate=serve.slo_deadline_rate if serve else 0.0,
            ),
            fast_window_s=serve.slo_fast_window_s if serve else 60.0,
            slow_window_s=serve.slo_slow_window_s if serve else 600.0,
        )
        self.slo_feedback = serve.slo_feedback if serve else True
        # tenant -> shed-estimate scale (>= 1). Replaced wholesale by
        # _apply_slo_feedback (atomic rebind; read lock-free in submit).
        self._slo_shed_scale: dict[str, float] = {}
        # Tenants currently holding a fair-queue quantum weight > 1: a
        # tenant the tracker LRU-evicts while weighted must still be
        # reset, or it would keep its boosted share forever.
        self._slo_weighted: set[str] = set()
        self._slo_next_feedback = 0.0
        self.default_deadline_s = serve.default_deadline_s if serve else 0.0
        self.epoch_stall_s = serve.epoch_stall_s if serve else 0.0
        self._guard = (
            StallGuard(self.epoch_stall_s, on_stall=self._on_epoch_stall)
            if self.epoch_stall_s > 0
            else None
        )
        self._cv = threading.Condition()
        self._stop = False
        self._thread: threading.Thread | None = None
        # Cancellation bookkeeping (all under _cv): rids of requests live in
        # the CURRENT epoch, and rids whose cancel is pending a chunk
        # boundary. Queued requests cancel immediately in cancel().
        self._live_rids: set[str] = set()
        self._cancel_ids: set[str] = set()
        # Observability (also lets tests assert real batching happened).
        self.stats = {
            "batches": 0, "rows": 0, "max_rows": 0, "joins": 0,
            "spec_rounds": 0, "spec_tokens": 0,
            # Paged mode only: streams force-finished ("length") because the
            # page pool had no free page at a decode page boundary.
            "page_truncations": 0,
            # Failure-semantics taxonomy (README): streams finished "error"
            # after a worker failure, streams cancelled, submissions shed;
            # failovers = migrations performed, recovered = live streams
            # carried through one, backpressured = streams cancelled at the
            # output-buffer watermark.
            "stream_errors": 0, "cancelled": 0, "shed": 0,
            "failovers": 0, "recovered": 0, "backpressured": 0,
            # Prefix cache: admissions/joins served a cached chain vs not
            # (cache disabled counts nothing).
            "prefix_hits": 0, "prefix_misses": 0,
            # Admission SLOs (runtime/admission.py): quota 429s, requests
            # expired past their deadline (queued or running), and backend
            # dispatches abandoned by the stuck-epoch watchdog.
            "quota_refusals": 0, "deadline_expired": 0, "epoch_stalls": 0,
            # Continuous scheduler (README "Continuous scheduling"): lanes
            # preempted under page pressure (spilled host-side) and spilled
            # lanes re-attached (bit-identical resume).
            "preemptions": 0, "restores": 0,
        }
        # Latency attribution (README "Latency attribution & black-box
        # diagnostics"): live per-phase accounting — the engine knows each
        # dispatch's wall time and how many of its tokens every row
        # consumed, so the aggregate cake_phase_seconds{phase} histograms
        # and the per-epoch convoy meter cost a few float adds per chunk.
        # Engine-thread writes; /stats reads a copy (same discipline as
        # ``stats`` above).
        # One small lock: the engine thread inserts phase keys while the
        # /stats HTTP thread snapshots them (a lock-free sorted() over a
        # growing dict can raise mid-iteration).
        self._phase_lock = threading.Lock()
        self.phase_totals: dict[str, dict] = {}
        self.convoy_stats = {
            "epochs": 0, "seconds_total": 0.0, "frac_last": 0.0,
            "frac_sum": 0.0,
        }
        # Per-epoch scratch (engine thread only; reset in _run_batch).
        self._epoch_rows: list[_RowState] = []
        self._epoch_t0 = 0.0
        self._epoch_head_rid = ""
        self._epoch_stalled = False
        # Black-box anomaly capture (obs/blackbox.py): None = off.
        self.blackbox = None
        if serve is not None and serve.blackbox_dir:
            from cake_tpu.obs.blackbox import BlackBox

            self.blackbox = BlackBox(
                serve.blackbox_dir,
                keep=serve.blackbox_keep,
                min_interval_s=serve.blackbox_min_interval_s,
                p99_mult=serve.blackbox_p99_mult,
            )
        # Goodput & hardware-efficiency ledger + scheduler decision audit
        # (README "Goodput & hardware efficiency", obs/efficiency.py):
        # every dispatch's wall lands in one taxonomy bucket, every
        # emitted token in a goodput class, and every admission verdict
        # records a structured cause /explain can retrieve.
        from cake_tpu.obs.efficiency import DecisionAudit, EfficiencyLedger

        self.audit = DecisionAudit()
        self.efficiency = EfficiencyLedger(
            config=self.config,
            peak_tflops=serve.peak_tflops if serve else 0.0,
            peak_hbm_gbps=serve.peak_hbm_gbps if serve else 0.0,
            audit=self.audit,
        )
        # Traffic observatory (README "Traffic observatory"): the canonical
        # per-request completion record — every terminal outcome, refusals
        # included, lands in the bounded ring behind GET /requests and the
        # optional --request-log JSONL sink (obs/requestlog.py; the loadgen
        # replay trace format) — and the rolling SLI time-series behind
        # GET /timeseries (obs/timeseries.py; `cake-tpu top` sparklines).
        from cake_tpu.obs.requestlog import RequestLog
        from cake_tpu.obs.timeseries import SliTimeseries

        self.requestlog = RequestLog()
        self.timeseries = SliTimeseries()

    def _req_cost(self, req: "_Request") -> float:
        """DRR cost of one request: its requested work (prompt + budget),
        scaled DOWN by the priority factor so a high-priority request
        consumes half the fair-share budget and low twice — priorities bias
        service inside a tenant's share without breaking cross-tenant
        isolation."""
        return (
            len(req.prompt_ids) + req.max_tokens
        ) / self._PRIORITY_FACTOR[req.priority]

    def _on_epoch_stall(self, op: str) -> None:
        self.stats["epoch_stalls"] += 1
        # The abandoned dispatch's wall is the watchdog bound — the
        # device (or its wire path) produced nothing for it.
        self.efficiency.note_stall(self.epoch_stall_s)
        # Capture the moment, not the aftermath: the abandoned dispatch is
        # about to unwind the epoch through the error path, and the
        # timeline slice still holds the stalled chunk (StallGuard already
        # recorded the epoch-stall instant this bundle's attribution
        # subtracts from the dispatch span).
        self._epoch_stalled = True
        self._capture("stall", self._epoch_head_rid or None)

    # ------------------------------------------- latency attribution plane

    def phase_stats(self) -> dict:
        """The ``/stats`` phases block (rendered by ``cake-tpu stats``):
        aggregate per-phase seconds over finished requests plus the
        per-epoch convoy meter — the lockstep tax, visible without pulling
        a trace."""
        with self._phase_lock:
            totals = {
                p: dict(d) for p, d in self.phase_totals.items()
            }
            cv = dict(self.convoy_stats)
        return {
            "phases": {
                p: {
                    "seconds": round(d["seconds"], 6),
                    "requests": d["requests"],
                }
                for p, d in sorted(totals.items())
            },
            "convoy": {
                "epochs": cv["epochs"],
                "seconds_total": round(cv["seconds_total"], 6),
                "frac_last": round(cv["frac_last"], 4),
                "frac_mean": round(
                    cv["frac_sum"] / cv["epochs"], 4
                ) if cv["epochs"] else 0.0,
            },
        }

    def _phase_observe(self, phase: str, seconds: float) -> None:
        if seconds <= 1e-9:
            return
        metrics.registry.histogram(
            "cake_phase_seconds",
            "Per-request latency attribution by canonical phase "
            "(obs/critpath.py taxonomy; convoy = lockstep epoch tax).",
        ).observe(seconds, phase=phase)
        with self._phase_lock:
            agg = self.phase_totals.setdefault(
                phase, {"seconds": 0.0, "requests": 0}
            )
            agg["seconds"] += seconds
            agg["requests"] += 1

    def _observe_request(self, row: "_RowState") -> None:
        """Finish-time attribution for one stream: fold its measured
        phases into the aggregate histograms, then run the black-box
        triggers (SLO breach / p99 outlier)."""
        req = row.req
        # t_submit is stamped AFTER submit()'s tokenize/gate work, so the
        # queue wait already excludes it — admission is its OWN additive
        # slice, never subtracted from queue.
        queue_s = max(
            0.0, (req.t_admit or row.t_open or req.t_submit) - req.t_submit
        )
        self._phase_observe("queue", queue_s)
        self._phase_observe("admission", req.admit_s)
        for phase, v in row.phase.items():
            self._phase_observe(phase, v)
        bb = self.blackbox
        if bb is None:
            return
        e2e = req.admit_s + max(
            0.0, (row.t_close or time.perf_counter()) - req.t_submit
        )
        outlier = bb.observe_latency(e2e)
        obj = self.slo.objectives
        reason = None
        if (
            req.handle.finish_reason == "deadline"
            and obj.deadline_rate > 0
        ):
            reason = "slo-deadline"
        elif (
            obj.ttft_ms > 0
            and row.ttft_s is not None
            and row.ttft_s * 1e3 > obj.ttft_ms
        ):
            reason = "slo-ttft"
        elif outlier:
            reason = "latency-outlier"
        if reason is not None:
            self._capture(reason, req.rid)

    # Backend execution shape -> the request record's ``node`` field; TCP
    # backends report the replica router's live routes instead.
    _NODE_LABELS = {
        "LocalBatchBackend": "local",
        "PagedLocalBackend": "local",
        "TPBatchBackend": "tp",
        "PipelineBatchBackend": "pipeline",
        "DistributedBatchBackend": "tcp",
    }

    def _node_label(self) -> str:
        """Routed node(s) for the request record: a TCP backend answers
        with the replica router's CURRENT routes (so a mid-run failover is
        visible in the log), in-process backends with their shape."""
        step = getattr(self.backend, "step", None)
        router = getattr(step, "router", None)
        if router is not None:
            try:
                routes = sorted(
                    set(router.snapshot().get("routes", {}).values())
                )
            except Exception:  # noqa: BLE001 — telemetry must not raise
                routes = []
            if routes:
                return "+".join(routes)
        return self._NODE_LABELS.get(
            type(self.backend).__name__, "local"
        )

    def _record_request(
        self, req: "_Request", row: "_RowState | None" = None,
        finish: str | None = None,
    ) -> None:
        """One canonical completion record per terminated request
        (obs/requestlog.py): every finish funnel — _RowState.finish for
        admitted rows, the queued cancel/expire paths, stranded joiners,
        whole-batch errors — calls through here, so the /requests ring,
        the --request-log JSONL sink, and the /timeseries outcome tallies
        always agree with the SLO tracker on what terminated how."""
        handle = req.handle
        finish = finish or handle.finish_reason
        now = time.perf_counter()
        n = handle.completion_tokens
        t_open = row.t_open if row is not None else None
        admitted = req.t_admit or t_open
        queue_s = max(0.0, (admitted or now) - req.t_submit)
        phases = {"queue": queue_s, "admission": req.admit_s}
        if row is not None:
            phases.update(row.phase)
        phases = {
            p: round(v, 6) for p, v in phases.items() if v > 1e-9
        }
        ttft = row.ttft_s if row is not None else None
        tpot = None
        if ttft is not None and n >= 2 and req.t_last_token:
            tpot = max(
                0.0, req.t_last_token - (req.t_submit + ttft)
            ) / (n - 1)
        t_close = (row.t_close if row is not None else None) or now
        wall = req.admit_s + max(0.0, t_close - req.t_submit)
        deadline_s = None
        if req.deadline:
            # Recover the request's ORIGINAL relative deadline (replay
            # re-issues it): absolute monotonic deadline minus the submit
            # instant, reconstructed from elapsed perf_counter time —
            # both clocks tick at wall rate, so the skew is negligible.
            deadline_s = round(
                req.deadline
                - (time.monotonic() - (now - req.t_submit)), 3
            )
        obj = self.slo.objectives
        if finish == "deadline":
            verdict = "deadline_miss"
        elif obj.ttft_ms > 0 and (
            ttft is None or ttft * 1e3 > obj.ttft_ms
        ):
            verdict = "ttft_miss"
        elif obj.ttft_ms > 0 or req.deadline:
            verdict = "ok"
        else:
            verdict = "none"
        decisions = [
            f"{d['action']}:{d['cause']}"
            for d in self.audit.for_request(req.rid)
        ][:16]
        try:
            self.requestlog.record(
                request_id=req.rid,
                tenant=req.tenant,
                priority=req.priority,
                prompt_tokens=handle.prompt_tokens,
                max_tokens=int(req.max_tokens),
                completion_tokens=n,
                queue_s=round(queue_s, 6),
                admit_s=round(req.admit_s, 6),
                ttft_s=None if ttft is None else round(ttft, 6),
                tpot_s=None if tpot is None else round(tpot, 6),
                wall_s=round(wall, 6),
                finish_reason=finish,
                slo=verdict,
                phases=phases,
                decisions=decisions,
                node=self._node_label(),
                deadline_s=deadline_s,
                # Arrival wall time (replay preserves the gaps): now minus
                # the elapsed stream wall minus the admission slice that
                # ran before t_submit was stamped.
                t_wall=round(
                    time.time() - (now - req.t_submit) - req.admit_s, 3
                ),
            )
        except ValueError:
            # Schema drift is a bug the tests/lint catch; a finishing
            # stream must never die to its own telemetry.
            log.exception("request-log record failed for %s", req.rid)
        self.timeseries.observe_finish(finish)

    def _record_refusal(
        self, rid: str, tenant: str, priority: int, kind: str,
        prompt_tokens: int, max_tokens: int, deadline_s: "float | None",
        admit_s: float,
    ) -> None:
        """Refusal record (quota 429 / shed 503): never admitted, but part
        of the replayable trace — offered traffic is not a hole in the
        capture just because the server turned it away."""
        try:
            self.requestlog.record(
                request_id=rid,
                tenant=tenant,
                priority=priority,
                prompt_tokens=prompt_tokens,
                max_tokens=int(max_tokens),
                completion_tokens=0,
                queue_s=0.0,
                admit_s=round(admit_s, 6),
                ttft_s=None,
                tpot_s=None,
                wall_s=round(admit_s, 6),
                finish_reason=kind,
                slo="refused",
                phases=(
                    {"admission": round(admit_s, 6)}
                    if admit_s > 1e-9 else {}
                ),
                decisions=[],
                node=self._node_label(),
                deadline_s=deadline_s,
            )
        except ValueError:
            log.exception("request-log refusal record failed for %s", rid)
        self.timeseries.observe_finish(kind)

    def _capture(self, reason: str, rid: str | None) -> None:
        """Snapshot one diagnostic bundle (rate-limited inside BlackBox).
        Never raises: diagnostics must not take the engine down."""
        bb = self.blackbox
        if bb is None:
            return
        try:
            from cake_tpu.obs import critpath

            events = timeline.snapshot()
            exp = critpath.explain(events, rid) if rid else None
            tl_slice = (
                timeline.snapshot(rid) if rid else events[-200:]
            )
            extra: dict = {
                "engine": dict(self.stats),
                "phase_stats": self.phase_stats(),
                "slo": self.slo.snapshot(),
                "metrics": metrics.registry.snapshot(),
                "efficiency": self.efficiency.snapshot(),
                "decisions": self.audit.snapshot(limit=50),
            }
            if self._alloc is not None:
                extra["pool"] = {
                    "pages_total": self._alloc.pages_total,
                    "pages_free": self._alloc.pages_free,
                }
            if self._prefix is not None:
                extra["prefix"] = self._prefix.stats()
            bb.capture(
                reason, rid, explain=exp, timeline=tl_slice,
                events=metrics.flight.snapshot()[-200:], extra=extra,
            )
        except Exception:  # noqa: BLE001 — diagnostics never hurt serving
            log.exception("blackbox capture failed")

    def _finish_epoch_convoy(self) -> None:
        """Per-epoch convoy meter, finalized in _run_batch's finally: the
        rows' accumulated convoy shares (padding + unconsumed chunk
        fractions) plus lane idle time (a lane that sat finished or empty
        while the epoch kept serving co-batched streams).
        ``convoy_frac`` normalizes by served-lane-seconds, so 0 = no tax
        and 1 = the epoch spent ALL its lane time on convoy."""
        rows = self._epoch_rows
        if not rows or self._epoch_t0 <= 0.0:
            return
        now = time.perf_counter()
        dur = max(1e-9, now - self._epoch_t0)
        lane_occ: dict[int, float] = {}
        convoy = 0.0
        for row in rows:
            if row.t_open:
                occ = max(0.0, (row.t_close or now) - row.t_open)
                lane_occ[row.lane] = lane_occ.get(row.lane, 0.0) + occ
            convoy += row.phase.get("convoy", 0.0)
        idle = 0.0
        if self.scheduler != "continuous":
            # Epoch-mode tax only: a lockstep epoch keeps a lane
            # occupied-shaped while unable to serve the queue. Under the
            # continuous scheduler an empty lane is admission HEADROOM —
            # anything admissible would have joined this very step, so the
            # meter bills only the real per-row convoy shares (padding +
            # unconsumed chunk fractions), which go to ~0 by construction.
            idle = sum(
                max(0.0, dur - min(occ, dur)) for occ in lane_occ.values()
            )
        total = convoy + idle
        frac = min(1.0, total / (dur * max(1, len(lane_occ))))
        metrics.registry.histogram(
            "cake_convoy_seconds",
            "Per-epoch lockstep convoy tax: lane-seconds spent on "
            "co-batched streams' work + finished/idle lane time.",
        ).observe(total)
        metrics.registry.gauge(
            "cake_convoy_frac",
            "Last epoch's convoy fraction of served-lane-seconds "
            "(0 = no lockstep tax).",
        ).set(frac)
        with self._phase_lock:
            cv = self.convoy_stats
            cv["epochs"] += 1
            cv["seconds_total"] += total
            cv["frac_last"] = frac
            cv["frac_sum"] += frac

    def tenant_stats(self) -> dict:
        """Per-tenant view for ``/stats``: quota accounting (meter) plus
        the fair queue's current depths."""
        out = self.tenant_meter.snapshot()
        with self._cv:
            queued = self._queue.queued_by_tenant()
        for tenant, n in queued.items():
            out.setdefault(tenant, {})["queued"] = n
        return out

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        if self._thread is not None:
            return
        if self.heartbeat_interval_s > 0 and self._hb_clients:
            # Engine-owned worker liveness (ServeConfig heartbeat knobs):
            # one PING prober per worker of the TCP backend's step.
            from cake_tpu.runtime.client import HeartbeatMonitor

            self.monitor = HeartbeatMonitor(
                {n: c.host for n, c in self._hb_clients.items()},
                interval_s=self.heartbeat_interval_s,
                deadline_s=self.heartbeat_deadline_s,
            ).start()
            if self._router is not None:
                # Routing consumes the liveness view: an unhealthy member
                # leaves rotation at the next refresh, and its recovery
                # (plus cooldown) readmits it — standby rejoin.
                self._router.attach_monitor(self.monitor)
        self._thread = threading.Thread(
            target=self._loop, name="batch-engine", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._guard is not None:
            # BEFORE joining the engine thread: it may be parked inside the
            # guard's bounded wait on a genuinely stalled dispatch — the
            # guard's stop wakes it immediately (as a worker-error, not a
            # counted stall) instead of stop() riding out the full bound.
            self._guard.stop()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        if self.monitor is not None:
            self.monitor.stop()
            self.monitor = None

    # ------------------------------------------------------------ submission

    def submit(
        self,
        messages: list[Message],
        max_tokens: int,
        sampling: SamplingConfig,
        request_id: str | None = None,
        priority: int | None = None,
        tenant: str | None = None,
        deadline_s: float | None = None,
    ) -> StreamHandle:
        """Queue one chat completion; returns immediately with its stream.

        ``request_id`` (the API's chatcmpl id, or a fresh one) keys this
        request's flight-recorder lifecycle and wire-frame trace attribution.
        ``priority`` (0 low / 1 normal / 2 high; ServeConfig
        ``default_priority`` otherwise) scales the load-shedding gates — low
        priority sheds first and is told to retry later. ``tenant`` keys the
        per-tenant quota gates and the fair queue (runtime/admission.py;
        ``QuotaExceeded`` -> HTTP 429 + Retry-After); ``deadline_s``
        (ServeConfig ``default_deadline_s`` otherwise; 0/None = none) is the
        end-to-end SLO — queued past it the request expires unadmitted,
        running past it the stream finishes ``"deadline"`` at the next chunk
        boundary, and a deadline the estimated queue wait already exceeds is
        shed immediately. Raises ValueError for over-length prompts and bad
        deadlines (the server maps both to 400 BEFORE any streaming headers
        go out).
        """
        t_enter = time.perf_counter()
        ids = self.tokenizer.encode(
            encode_dialog(messages, self.config.dialog_template)
        )
        # Left-pad bucket rounding can add slots ahead of the prompt; require
        # room for the bucket plus at least one generated token. Same helper
        # as the actual layout (models/llama/batch.py) so they cannot drift.
        bucket_ceiling = prompt_bucket(len(ids), self.max_seq_len)
        if bucket_ceiling >= self.max_seq_len:
            raise ValueError(
                f"prompt is {len(ids)} tokens but the context window "
                f"is {self.max_seq_len}"
            )
        if self._alloc is not None:
            # A prompt needing more pages than the whole pool can NEVER be
            # admitted — refuse here (maps to 400) rather than queueing it
            # forever behind the free-page admission gate.
            need = self._alloc.pages_needed(len(ids)) + self._alloc.reserve_pages
            if need > self._alloc.pages_total:
                raise ValueError(
                    f"prompt needs {need} KV pages (page_size "
                    f"{self._alloc.page_size}) but the pool holds "
                    f"{self._alloc.pages_total}"
                )
        if priority is None:
            priority = self.default_priority
        priority = max(0, min(2, int(priority)))
        tenant = (str(tenant).strip() if tenant else "") or DEFAULT_TENANT
        if deadline_s is None and self.default_deadline_s > 0:
            deadline_s = self.default_deadline_s
        if deadline_s is not None and (
            not isinstance(deadline_s, (int, float)) or deadline_s <= 0
        ):
            raise ValueError(
                f"deadline_s must be a positive number, got {deadline_s!r}"
            )
        rid = request_id or metrics.new_request_id()
        # Quota gates first (429 beats 503: a refusal the caller can fix by
        # backing off is more actionable than "server busy"); admit()
        # registers the stream atomically, so any later refusal must
        # close() it again.
        try:
            self.tenant_meter.admit(
                tenant, rid, len(ids) + int(max_tokens)
            )
        except QuotaExceeded:
            self.stats["quota_refusals"] += 1
            self.slo.observe_refusal(tenant, "quota")
            self._record_refusal(
                rid, tenant, priority, "quota", len(ids), max_tokens,
                deadline_s, time.perf_counter() - t_enter,
            )
            raise
        try:
            self._maybe_shed(
                len(ids), priority, deadline_s=deadline_s, tenant=tenant
            )
        except EngineOverloaded:
            # Refund: the quota grant above charged the caller's bucket,
            # but a shed is SERVER saturation — without the credit back,
            # 503-hinted retries would drain the tenant's own budget on
            # zero-work submissions and surface as spurious 429s.
            self.tenant_meter.close(rid, refund=True)
            self._record_refusal(
                rid, tenant, priority, "shed", len(ids), max_tokens,
                deadline_s, time.perf_counter() - t_enter,
            )
            raise
        handle = StreamHandle(n_prompt=len(ids), request_id=rid)
        handle._on_close = lambda: self.tenant_meter.close(rid)
        req = _Request(
            ids, max_tokens, sampling, handle,
            rid=rid, t_submit=time.perf_counter(), priority=priority,
            tenant=tenant,
            deadline=(
                time.monotonic() + deadline_s if deadline_s else 0.0
            ),
            # Tokenize + quota + shed wall time: the "admission" slice of
            # the queue phase in the /explain decomposition.
            admit_s=time.perf_counter() - t_enter,
        )
        # Record BEFORE enqueueing: once the queue holds the request the
        # scheduler may admit it immediately, and an 'admitted' flight event
        # must never precede its 'submitted'. (A stopped-engine raise below
        # leaves a lone 'submitted' event — an honest timeline for a refusal.)
        metrics.registry.counter(
            "cake_engine_submitted_total", "Requests accepted into the queue."
        ).inc()
        metrics.flight.record(
            "submitted", rid,
            prompt_tokens=len(ids), max_tokens=int(max_tokens),
        )
        with self._cv:
            if self._stop:
                self.tenant_meter.close(rid, refund=True)
                raise RuntimeError("engine is stopped")
            self._queue.append(req)
            self._cv.notify_all()
        return handle

    # Priority classes scale the shedding gates: low (0) sheds at half the
    # depth / double the page floor and is told to retry twice as late;
    # high (2) tolerates double the depth — so under overload low-priority
    # traffic degrades first (the first slice of per-tenant fairness).
    _PRIORITY_FACTOR = {0: 0.5, 1: 1.0, 2: 2.0}

    # Per-step scheduler state shared between the engine thread and the
    # submit/cancel/API threads under the continuous scheduler's
    # admit-anytime model. Declaring it here is the step-state-unlocked
    # lint contract (cake_tpu/analysis/rules/scheduler.py): every mutation
    # of these attributes must hold the engine cv — unlike
    # unlocked-shared-mutation, which only fires once SOME site is
    # guarded, the declaration enforces the invariant even before the
    # first correct site exists.
    _STEP_STATE = ("_spilled",)

    def _maybe_shed(
        self, n_prompt: int, priority: int = 1,
        deadline_s: float | None = None, tenant: str = DEFAULT_TENANT,
    ) -> None:
        """Admission load shedding: refuse NOW (503 + Retry-After at the API)
        rather than queueing into a timeout. Three gates: queue depth and
        paged-pool pressure (each off at 0, both scaled by the request's
        priority class), plus the deadline-aware gate — when the request
        carries a deadline the ESTIMATED queue wait (EWMA of observed
        waits, scaled by depth) already exceeds, queueing it is a
        guaranteed timeout that would still pin pages when it finally ran;
        refusing is strictly kinder."""
        factor = self._PRIORITY_FACTOR[priority]
        reason = None
        with self._cv:
            depth = len(self._queue)
        est = (
            self._wait_est.estimate(
                depth, self.max_batch,
                # SLO burn feedback: a tenant already missing objectives
                # gets an inflated estimate — its doomed-deadline
                # submissions shed earlier instead of queueing work that
                # would miss anyway (obs/slo.py adjustments).
                scale=self._slo_shed_scale.get(tenant, 1.0),
            )
            if deadline_s
            else 0.0
        )
        cause = ""
        if deadline_s and est > deadline_s:
            cause = "deadline_doomed"
            reason = (
                f"estimated queue wait {est:.2f}s already exceeds the "
                f"request deadline {deadline_s:.2f}s"
            )
        elif self.shed_queue_depth and depth >= self.shed_queue_depth * factor:
            cause = "queue_depth"
            reason = (
                f"queue depth {depth} >= {self.shed_queue_depth * factor:g} "
                f"(priority {priority})"
            )
        elif self.shed_min_free_pages and self._alloc is not None:
            # Pages reclaimable by prefix-cache eviction count as available:
            # admission evicts before mapping, so a full-but-COLD cache is
            # capacity, not pressure — without this a cache that grew to the
            # pool floor would shed forever (shed-after-evict ordering is
            # pinned in tests/test_prefix_serving.py).
            free_eff = self._alloc.pages_free + (
                self._prefix.reclaimable() if self._prefix is not None else 0
            )
            if free_eff < self.shed_min_free_pages / factor:
                cause = "page_pressure"
                reason = (
                    f"{free_eff} free+reclaimable KV pages < floor "
                    f"{self.shed_min_free_pages / factor:g} "
                    f"(priority {priority})"
                )
        if reason is None:
            return
        self.audit.record("shed", cause, tenant=tenant, detail=reason[:120])
        self.stats["shed"] += 1
        self.slo.observe_refusal(tenant, "shed")
        metrics.registry.counter(
            "cake_shed_total",
            "Submissions refused by admission load shedding "
            "(queue-depth / free-page gates; HTTP 503 + Retry-After).",
        ).inc()
        metrics.flight.record(
            "shed", prompt_tokens=n_prompt, reason=reason,
            priority=priority,
        )
        raise EngineOverloaded(
            f"engine overloaded: {reason}",
            retry_after_s=self.retry_after_s / factor,
        )

    # ---------------------------------------------------------- cancellation

    def cancel(self, request_id: str) -> bool:
        """Cancel one request by id (the chat response id).

        Queued: removed and finished immediately with
        ``finish_reason="cancelled"``. Running: finished at the next chunk
        boundary — its lane's pages return to the pool mid-epoch and the
        lane frees up for joins, so an abandoned stream stops burning decode
        steps. Returns False for ids that are not queued or live (already
        finished, or never existed) — cancel is idempotent.
        """
        sp = None
        with self._cv:
            for r in self._queue:
                if r.rid == request_id:
                    self._queue.remove(r)
                    self._finish_cancelled_locked(r)
                    return True
            sp = self._spilled.pop(request_id, None)
            if sp is None and request_id in self._live_rids:
                self._cancel_ids.add(request_id)
                return True
        if sp is not None:
            # A spilled lane holds no pages and no device state: cancel is
            # immediate — finish the stream here, off the engine thread
            # (same taxonomy as a mid-epoch cancel, zero pages to free).
            self._note_cancelled(sp.row, "spilled")
            sp.row.cancel()
            return True
        return False

    def quiesce(self, timeout: float = 30.0) -> bool:
        """Block until the page pool is idle: every lane's pages returned,
        only the prefix cache (if any) still holding pages.

        A stream CLOSES (its last token and end-of-stream are emitted) at
        the chunk boundary, BEFORE the epoch's insert-on-finish/release
        bookkeeping runs on the engine thread — so a caller that read
        end-of-stream and immediately inspects pool state or clears the
        cache races live allocator mutation (and a ``clear()`` that loses
        the race leaves the just-finished prompts' chains behind). Polling
        here is the one supported way to wait that race out; the bench and
        the chaos/prefix tests all come through this method. Returns False
        on timeout; dense engines are always idle."""
        if self._alloc is None:
            return True
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            held = (
                self._prefix.stats()["pages"]
                if self._prefix is not None
                else 0
            )
            if self._alloc.pages_free == self._alloc.pages_total - held:
                return True
            time.sleep(0.01)
        return False

    def _finish_cancelled_locked(self, req: _Request) -> None:
        """Close a never-admitted request as cancelled (queue removal)."""
        req.handle.finish_reason = "cancelled"
        self.stats["cancelled"] += 1
        metrics.registry.counter(
            "cake_cancelled_total", "Requests cancelled (queued or live)."
        ).inc()
        metrics.flight.record("cancelled", req.rid, where="queued")
        metrics.flight.record(
            "finished", req.rid, finish_reason="cancelled",
            completion_tokens=0,
        )
        self._record_request(req)
        req.handle._emit(_DONE)

    def _fail_spilled_locked(self, error: str) -> None:
        """Close every spilled stream with a raised error (caller holds the
        cv — the stop path): a parked lane must never outlive the engine."""
        for sp in self._spilled.values():
            sp.row.req.handle._emit(RuntimeError(error))
            sp.row.req.handle._emit(_DONE)
            sp.row.close_span(error=error)
        # The _locked suffix is the contract: every caller already holds
        # the engine cv around this call (the stop and epoch-error paths).
        # cake-lint: disable-next-line=step-state-unlocked, unlocked-shared-mutation
        self._spilled.clear()

    def _expire_queued(self, req: _Request) -> None:
        """Close a queued request whose end-to-end deadline passed before
        admission: it never occupies a lane or maps a page — the whole
        point of expiring BEFORE admission instead of discovering the
        deadline mid-decode (caller removes it from the queue)."""
        req.handle.finish_reason = "deadline"
        self.stats["deadline_expired"] += 1
        self.audit.record(
            "expire", "deadline_expired", rid=req.rid, tenant=req.tenant,
            detail="queued",
        )
        metrics.registry.counter(
            "cake_deadline_expired_total",
            "Requests past their end-to-end deadline (where=queued expired "
            "before admission; where=running at a chunk boundary).",
        ).inc(where="queued")
        metrics.flight.record("deadline-expired", req.rid, where="queued")
        metrics.flight.record(
            "finished", req.rid, finish_reason="deadline",
            completion_tokens=0,
        )
        timeline.instant(
            "deadline-expired", rid=req.rid, track="engine",
            args={"where": "queued"},
        )
        # SLO view: a deadline miss AND (by definition — no first token
        # within any bound) a TTFT miss for this tenant (obs/slo.py).
        self.slo.observe_finish(
            req.tenant, "deadline",
            had_deadline=True, got_first_token=False,
        )
        self._record_request(req)
        req.handle._emit(_DONE)

    def _apply_deadlines(self, rows: list) -> None:
        """Chunk-boundary deadline sweep: running streams past their
        deadline finish ``"deadline"`` NOW (their lanes free this very
        round, pages release in the caller's _release_finished pass), and
        queued requests past theirs expire without ever admitting."""
        now = time.monotonic()
        for lane, row in enumerate(rows):
            if (
                row is not None
                and row.req.deadline
                and now > row.req.deadline
            ):
                self.stats["deadline_expired"] += 1
                self.audit.record(
                    "expire", "deadline_expired", rid=row.req.rid,
                    tenant=row.req.tenant, detail="running",
                )
                row.expire()
                rows[lane] = None
        expired_spills = []
        with self._cv:
            for rid, sp in list(self._spilled.items()):
                if sp.row.req.deadline and now > sp.row.req.deadline:
                    del self._spilled[rid]
                    expired_spills.append(sp)
        for sp in expired_spills:
            # A spilled lane past its deadline never restores: no pages to
            # free, the stream's delivered tokens stand (row.expire counts
            # the where=running metric — the stream WAS running when
            # preempted, the spill just parked it).
            self.stats["deadline_expired"] += 1
            sp.row.expire()
        if self._queue.deadline_count:
            expired = []
            with self._cv:
                for r in self._queue:
                    if r.deadline and now > r.deadline:
                        self._queue.remove(r)
                        expired.append(r)
            for r in expired:
                self._expire_queued(r)

    def _shed_backpressure(self, row: "_RowState") -> None:
        """Streaming backpressure: a consumer that stopped draining its
        stream handle has ``stream_buffer_tokens`` tokens parked in the
        per-client output buffer — treat it like a gone client
        (runtime/api.py ``_client_gone``) and route the stream into the
        cancel path: it finishes ``"cancelled"`` at this chunk boundary,
        returning its pages and lane, instead of growing memory without
        bound."""
        self.stats["backpressured"] += 1
        metrics.registry.counter(
            "cake_stream_backpressure_total",
            "Streams cancelled at the output-buffer high watermark "
            "(consumer stopped reading).",
        ).inc()
        metrics.flight.record(
            "stream-backpressure", row.req.rid,
            buffered=row.req.handle.buffered(),
            watermark=self.stream_buffer_tokens,
        )
        log.warning(
            "stream %s backpressured (%d tokens buffered >= %d); cancelling",
            row.req.rid, row.req.handle.buffered(), self.stream_buffer_tokens,
        )
        with self._cv:
            if row.req.rid in self._live_rids:
                self._cancel_ids.add(row.req.rid)

    def _row_finished(self, rid: str) -> None:
        """Row lifecycle hook (called by _RowState.finish): drop the rid
        from the live/cancel sets so cancel() answers honestly."""
        with self._cv:
            self._live_rids.discard(rid)
            self._cancel_ids.discard(rid)

    def _apply_cancels(self, rows: list) -> None:
        """Chunk-boundary cancellation sweep: finish flagged rows as
        "cancelled" and free their lanes (pages release in the caller's
        _release_finished pass)."""
        with self._cv:
            if not self._cancel_ids:
                return
            pending = set(self._cancel_ids)
        for lane, row in enumerate(rows):
            if row is not None and row.req.rid in pending:
                self.stats["cancelled"] += 1
                metrics.registry.counter(
                    "cake_cancelled_total",
                    "Requests cancelled (queued or live).",
                ).inc()
                metrics.flight.record(
                    "cancelled", row.req.rid, where="epoch",
                    completion_tokens=row.n,
                )
                row.cancel()
                rows[lane] = None

    # ------------------------------------------------------------ scheduler

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._spilled and not self._stop:
                    # Deliberately unbounded: the idle scheduler park;
                    # submit(), cancel-of-spilled, and stop() all notify
                    # under this cv (spills themselves are created by this
                    # thread, never while it parks here).
                    self._cv.wait()  # cake-lint: disable=unbounded-wait
                if self._stop:
                    for r in self._queue:
                        r.handle._emit(RuntimeError("engine stopped"))
                    self._queue.clear()
                    self._fail_spilled_locked("engine stopped")
                    return
            # Admission window: let a burst of concurrent submissions land so
            # they batch together instead of trickling into 1-row batches.
            # The continuous scheduler skips it — requests admit the moment
            # the step loop sees them; batching happens per step, not per
            # admission decision.
            if self.admission_window > 0 and self.scheduler != "continuous":
                time.sleep(self.admission_window)
            self._apply_slo_feedback()
            # Pending spills run FIRST: they are previously admitted work —
            # a spill-seeded segment restores them as its seed rows, and
            # queued requests with the same knobs join it per step.
            with self._cv:
                spill_seed = self.scheduler == "continuous" and bool(
                    self._spilled
                )
            batch = [] if spill_seed else self._admit()
            if not batch and not spill_seed:
                continue
            if batch:
                # Spill-seeded segments account themselves in _run_epoch
                # once the seed size is known (and a seed that dissolves —
                # every spill cancelled/doomed first — counts nothing).
                self._note_batch_started(len(batch))
            try:
                self._run_batch(batch)
            except Exception as e:  # noqa: BLE001 — surface to every consumer
                log.exception("batch failed")
                for r in batch:
                    if r.handle._on_close is not None:
                        # Stream not yet terminated (a closed handle's
                        # _on_close has fired and cleared): this consumer
                        # is about to see the raised error — count it in
                        # the tenant's error SLI, once (already-finished
                        # co-batched rows were observed at their finish).
                        self.slo.observe_finish(
                            r.tenant, "error",
                            had_deadline=bool(r.deadline),
                            got_first_token=r.handle.completion_tokens > 0,
                        )
                        self._record_request(r, finish="error")
                    r.handle._emit(e)
                    r.handle._emit(_DONE)

    def _note_batch_started(self, n_rows: int) -> None:
        """Epoch/segment-start accounting, shared by queue admissions
        (_loop) and spill-seeded segments (_run_epoch, once the seed size
        is known)."""
        self.stats["batches"] += 1
        self.stats["rows"] += n_rows
        self.stats["max_rows"] = max(self.stats["max_rows"], n_rows)
        metrics.registry.counter(
            "cake_engine_batches_total", "Decode epochs started."
        ).inc()
        metrics.registry.histogram(
            "cake_batch_rows",
            "Requests admitted per epoch at epoch start.",
            buckets=(1, 2, 4, 8, 16, 32, 64),
        ).observe(n_rows)

    def _apply_slo_feedback(self, force: bool = False) -> None:
        """Feed per-tenant burn rates back into admission (obs/slo.py):
        burning tenants get a FairQueue quantum weight > 1 (their queue
        drains ahead) and an inflated WaitEstimator shed scale (their
        doomed-deadline submissions refuse earlier). Rate-limited to about
        once a second — the windows move on second granularity, and the
        scheduler loop calls this every iteration."""
        if not self.slo_feedback:
            return
        now = time.monotonic()
        if not force and now < self._slo_next_feedback:
            return
        self._slo_next_feedback = now + 1.0
        adj = self.slo.adjustments()
        if not adj and not self._slo_shed_scale and not self._slo_weighted:
            return
        self._slo_shed_scale = {
            t: a["shed_scale"]
            for t, a in adj.items()
            if a["shed_scale"] > 1.0
        }
        with self._cv:
            for t, a in adj.items():
                self._queue.set_weight(t, a["quantum_weight"])
            # A weighted tenant the tracker evicted (LRU past its tenant
            # cap) no longer appears in adjustments — reset it here, or
            # its boosted share would outlive the burn that earned it.
            for t in self._slo_weighted - set(adj):
                self._queue.set_weight(t, 1.0)
        self._slo_weighted = {
            t for t, a in adj.items() if a["quantum_weight"] > 1.0
        }

    def _backend_guard(self, op: str) -> None:
        """Fault checkpoint in front of a backend dispatch (runtime/faults.py
        ``backend.*`` sites): ``stall`` sleeps, ``kill``/``crash`` raise the
        same typed failure a dead worker produces — so the engine's isolation
        path is testable on ANY backend, not just live TCP clusters."""
        spec = faults.check(f"backend.{op}")
        if spec is None:
            return
        if spec.kind == "stall":
            faults.sleep(spec)
        elif spec.kind in ("kill", "crash"):
            from cake_tpu.runtime.batch_backend import BackendWorkerError

            raise BackendWorkerError("<fault-plan>", op)

    def _dispatch(self, op: str, fn):
        """Run one backend dispatch (fault checkpoint included) under the
        stuck-epoch watchdog. With ``epoch_stall_s`` off this is exactly
        the old inline guard+call; with it on, the dispatch runs on the
        guard's watchdog thread — MATERIALIZED (block_until_ready) so a
        device that accepts the async dispatch but hangs at readback is
        caught too — and a stall (a backend that neither returns nor
        raises — the PR 6 ``stall`` fault kind, a wedged device) is
        abandoned within the bound and surfaced as the same
        ``BackendWorkerError`` a dead worker produces, so it flows through
        failover/error isolation instead of parking the engine forever.

        Abandonment contract: the stalled dispatch keeps running on its
        (disposable, daemon) thread. That is SAFE on the in-process
        backends — jax arrays are immutable, the late result is discarded,
        and the failed epoch's pool buffer is replaced wholesale by the
        next epoch's ``init_kv`` (a failed prefix-cache epoch also clears
        its chains) — so the stale computation can only ever read dead
        bytes, never write live ones. On the TCP backends the wire layer's
        own per-op deadlines/retries (``op_deadline_s``) already convert a
        hung worker into BackendWorkerError without the watchdog, so the
        guard is the local/device half of the same bound, not a substitute
        for wire deadlines."""
        if self._guard is None:
            self._backend_guard(op)
            return fn()

        def job():
            self._backend_guard(op)
            # Block on EVERY output leaf while still on the watchdog
            # thread: dispatch-accepted-but-readback-hung is the wedged-
            # device shape the watchdog exists for.
            return jax.block_until_ready(fn())

        return self._guard.call(job, op=op)

    # ------------------------------------------------- replica failover
    # Transparent recovery (README "Failover"): when a worker dies after
    # the wire retry budget (BackendWorkerError) and a healthy replica
    # exists, the epoch's live streams MIGRATE instead of finishing
    # "error" — each stream's accumulated tokens (prompt + generated so
    # far) re-prefill through the new route as one batched windowed
    # prefill, and decode resumes at the same slot with the same sampling
    # state. Greedy streams are bit-identical to a fault-free run.

    def _failover_or_raise(self, e) -> None:
        """Gate one failover attempt; re-raises ``e`` when migration is not
        possible (no healthy replica, budget burned, or too many attempts
        this epoch) so the caller degrades to PR 6's error isolation."""
        if self._fo_count >= self.max_failovers:
            log.warning("failover limit reached (%d); degrading", self._fo_count)
            raise e
        if self._fo_spent_s >= self.failover_budget_s:
            log.warning(
                "failover budget burned (%.2fs >= %.2fs); degrading",
                self._fo_spent_s, self.failover_budget_s,
            )
            raise e
        failover = getattr(self.backend, "failover", None)
        if failover is not None:
            # TCP: eject the dead member and re-route its replica group
            # (runtime/router.py records cake_failover_total + the event).
            if not failover(e.node):
                raise e  # no healthy replica left for that span
        elif not self.failover_local:
            raise e  # replica-less backend without the in-place opt-in
        else:
            # In-place retry on a local/tp/mesh backend (transient fault):
            # same observability the router gives the TCP path.
            metrics.registry.counter(
                "cake_failover_total",
                "Failovers away from a worker (labelled by the FAILED "
                "node).",
            ).inc(node=e.node)
            metrics.flight.record("failover", node=e.node, to=e.node)
        self._fo_count += 1
        self.stats["failovers"] += 1
        # Post-mortem bundle at the migration decision (rate-limited): the
        # flight tail still holds the worker-death breadcrumbs.
        self._capture("failover", self._epoch_head_rid or None)

    def _migrate_kv(self, rows: list, B: int, slot: int):
        """Rebuild every live stream's KV on the (re-routed) backend.

        At a chunk boundary the invariant is: ``row.history`` holds prompt +
        all emitted tokens, KV covers slots ``[pad, slot)`` =
        ``history[:-1]``, and ``history[-1]`` is the pending token at
        ``slot``. So migration is ONE batched prefill of each live row's
        ``history[:-1]`` into a window ending at the shared slot — the same
        per-row ``ends`` arithmetic as a continuous-batching join — after a
        fresh ``init_kv`` (new replay session on the new route; paged: pool
        reset + per-lane remap). Sampling state (keys/rings) is host/master
        state and rides through untouched.
        """
        t0 = time.perf_counter()
        live = [(lane, row) for lane, row in enumerate(rows) if row is not None]
        with timeline.span(
            "failover-migrate", track="router",
            args={"slot": int(slot), "live": len(live)},
        ):
            # The re-prefill window rides the SAME epoch capacity as every
            # other dispatch (one-capacity rule): W >= slot still holds
            # because the capacity always covers the epoch's slot ceiling.
            capw = self.max_seq_len
            if hasattr(self.backend, "capacity_slots"):
                capw = min(capw, self.backend.capacity_slots())
            W = min(-(-slot // 64) * 64, capw)
            tokens = np.zeros((B, W), np.int32)
            pads = np.full((B,), slot - 1, np.int32)
            # Dummy/finished lanes carry a 1-token bos window: garbage
            # nobody reads, exactly like epoch-start dummy lanes.
            tokens[:, slot - 1] = self.config.bos_token_id
            ends = np.full((B,), slot, np.int32)
            for lane, row in live:
                hist = row.history[:-1]  # KV prefix; history[-1] is pending
                tokens[lane, slot - len(hist): slot] = hist
                pads[lane] = slot - len(hist)
            if self._prefix is not None:
                # Migration rebuilds the pool from ZERO on the new route:
                # every cached chain's bytes die with the old pool, so the
                # chains, their pins, and the stale retained buffer go too.
                # Live lanes' prefixes re-prefill below and re-insert on
                # finish (their _lane_info pads are the original pads —
                # history only ever grows to the right of the prompt).
                self._prefix.clear(reason="failover-migrate")
                self.backend.drop_retained_kv()
                self._lane_leases.clear()
            kv = self.backend.init_kv(B)
            if self._alloc is not None:
                for lane, _ in live:
                    self._alloc.map_range(lane, int(pads[lane]), slot)
                self._pool_counter()
            if self._prefix is not None:
                # Cache-enabled epochs were prefilled through the cached-
                # chunk arithmetic; the rebuilt KV must be too, or the
                # resumed decode reads ulp-different bytes and greedy
                # streams stop being bit-identical to the fault-free run.
                # Thresholds at the pads = all-fresh; the dead tail past
                # ``slot`` writes nothing (those slots are unmapped).
                _, kv = self._dispatch(
                    "prefill",
                    lambda: self.backend.suffix_prefill(
                        tokens, kv, jnp.asarray(pads),
                        np.asarray(pads, np.int32), 0,
                    ),
                )
            else:
                _, kv = self._dispatch(
                    "prefill",
                    lambda: self.backend.prefill(
                        tokens, kv, jnp.asarray(pads), ends=jnp.asarray(ends)
                    ),
                )
        dt = time.perf_counter() - t0
        self._fo_spent_s += dt
        # Hardware ledger: the migration's re-prefill is redone work a
        # worker death cost the device.
        self.efficiency.note_failover(dt)
        self.stats["recovered"] += len(live)
        metrics.registry.histogram(
            "cake_failover_seconds",
            "Wall seconds per live-stream migration (re-prefill through "
            "the failed-over route).",
        ).observe(dt)
        metrics.registry.counter(
            "cake_streams_recovered_total",
            "Live streams carried through a failover migration (vs "
            "cake_stream_errors_total when no replica could take over).",
        ).inc(len(live))
        metrics.flight.record(
            "failover-migrated", live=len(live), slot=int(slot),
            seconds=round(dt, 6),
        )
        log.warning(
            "failover migration: %d live stream(s) re-prefilled at slot %d "
            "in %.3fs", len(live), slot, dt,
        )
        return kv

    def _pages_for(self, req: _Request, end_slot: int | None = None) -> int:
        """Admission price of one request: prompt pages + the reserve, LESS
        the cached-prefix discount — a warm request pays pages only for its
        uncached suffix (forked chain pages are already allocated and merely
        gain a reference).

        The discount depends on the lane's pad alignment. A JOIN knows it
        exactly (``end_slot`` = the epoch's shared slot); epoch-start
        admission estimates it from the request's solo bucket — exact for
        the homogeneous traffic that hits most (a shared system prompt with
        same-shape suffixes), conservative-or-optimistic otherwise, which is
        safe: the epoch-start mapping degrades a mispriced row to a cold
        prefill (or a page-truncated finish) instead of failing the epoch.
        """
        n = len(req.prompt_ids)
        served = 0
        if self._prefix is not None:
            end = (
                end_slot
                if end_slot is not None
                else prompt_bucket(n, self.max_seq_len)
            )
            served = self._prefix.match_tokens(
                req.prompt_ids, (end - n) % self._alloc.page_size
            )
        return self._alloc.pages_needed(n - served) + self._alloc.reserve_pages

    # ------------------------------------------------- prefix-cache wiring
    # Fork-at-admission / insert-on-release (runtime/prefix_cache.py): a
    # lane whose prompt extends a cached chain splices the chain's pages
    # into its block table (+1 ref each, pinned by a lease) and computes
    # only the uncached tail; when its pages return to the pool the prompt-
    # prefix chain is adopted back into the cache instead of freed.

    def _fork_lane(
        self, lane: int, req: _Request, pad: int, end: int,
        ids: list[int] | None = None,
    ):
        """Fork the longest cached chain under one lane, split the boundary
        page when the fresh region starts mid-page (make_private — the
        first divergent write must never scribble a shared page), and map
        the uncached tail [fresh, end). ``ids`` overrides the matched token
        sequence (a spilled lane's restore matches its HISTORY — which
        starts with the prompt, so the cached prompt chain still serves its
        head); default is the request's prompt.

        Returns (fresh, cow_pair): the first slot the lane must compute AND
        the first it may write (the write_starts threshold), plus the
        (src, dst) physical pages of a boundary split the CALLER must
        copy_pages before any write lands (None when the chain ends on a
        page boundary) — returned, not applied, so an epoch's splits batch
        into ONE device copy. Raises PageExhausted only when even on-demand
        cache eviction cannot supply the tail's pages (the admission
        estimate priced a different alignment class)."""
        from cake_tpu.models.llama.paged_cache import PageExhausted

        fresh = pad
        pair = None
        plan = self._prefix.fork(
            lane, ids if ids is not None else req.prompt_ids, pad,
            rid=req.rid,
        )
        if plan is None:
            self.stats["prefix_misses"] += 1
        else:
            self.stats["prefix_hits"] += 1
            self._lane_leases[lane] = plan.lease
            fresh = pad + plan.served
            if plan.cow_logical is not None:
                try:
                    pair = self._alloc.make_private(lane, plan.cow_logical)
                except PageExhausted:
                    if self._prefix.reclaim(1, rid=req.rid):
                        pair = self._alloc.make_private(
                            lane, plan.cow_logical
                        )
                    else:
                        # Degraded split: give the shared page back and
                        # recompute its tokens into a fresh page map_range
                        # allocates below — never write a shared page.
                        self._alloc.unmap_page(lane, plan.cow_logical)
                        fresh = max(
                            pad, plan.cow_logical * self._alloc.page_size
                        )
                        pair = None
        try:
            self._alloc.map_range(lane, fresh, end)
        except PageExhausted:
            # Cold cache pages are reclaimable capacity, not pressure:
            # evict enough for the tail and retry once.
            self._prefix.reclaim(
                self._alloc.pages_needed(end - fresh) + 1, rid=req.rid
            )
            self._alloc.map_range(lane, fresh, end)
        self._lane_info[lane] = (req, pad)
        return fresh, pair

    def _prefix_layout(
        self, reqs: list, rows: list, pads, bucket: int, kv,
        ids_list: list | None = None,
    ):
        """Epoch-start lane layout under the prefix cache: fork every real
        lane's longest cached chain and map only its uncached tail.
        ``ids_list`` overrides the per-lane matched tokens (spill-seeded
        segments lay out histories, not prompts).

        Returns (kv, write_starts [B] int32) — the caller dispatches the
        windowed suffix prefill with these per-lane fresh thresholds (cold
        lanes' thresholds sit at their pads: full compute, every write
        lands). A lane that cannot get its pages even after on-demand
        eviction force-finishes as "length": pool pressure degrades one
        stream, never the epoch."""
        ws = np.asarray(pads, np.int32).copy()
        cow_src: list[int] = []
        cow_dst: list[int] = []
        # The fork pass is its own (nested) span so /explain can report
        # prefix-cache fork time apart from the prefill compute around it;
        # the finally below keeps it closed on the worker-death paths too
        # (the span-leak rule's own discipline).
        fork_span = timeline.begin(
            "prefix-fork", track="engine", args={"lanes": len(reqs)},
        )
        try:
            return self._prefix_layout_inner(
                reqs, rows, pads, bucket, kv, ws, cow_src, cow_dst, ids_list
            )
        finally:
            timeline.end(fork_span)

    def _prefix_layout_inner(
        self, reqs, rows, pads, bucket, kv, ws, cow_src, cow_dst,
        ids_list=None,
    ):
        from cake_tpu.models.llama.paged_cache import PageExhausted

        for lane, r in enumerate(reqs):
            if r is None:
                # Dummy lanes hold no pages; park their threshold at the
                # window tail so they never stretch the suffix window.
                ws[lane] = bucket - 1
                continue
            try:
                fresh, pair = self._fork_lane(
                    lane, r, int(pads[lane]), bucket,
                    ids=ids_list[lane] if ids_list is not None else None,
                )
            except PageExhausted:
                row = rows[lane]
                self.stats["page_truncations"] += 1
                row.req.handle.finish_reason = "length"
                metrics.flight.record(
                    "page-truncated", r.rid, slot=int(pads[lane]),
                    where="admission", completion_tokens=0,
                )
                row.finish()
                rows[lane] = None
                reqs[lane] = None
                if self._alloc.lane_mapped(lane):
                    self._lane_recycle(lane, insert=False)
                else:
                    self._prefix.release(self._lane_leases.pop(lane, None))
                    self._lane_info.pop(lane, None)
                ws[lane] = bucket - 1
                continue
            ws[lane] = fresh
            if pair is not None:
                cow_src.append(pair[0])
                cow_dst.append(pair[1])
        if cow_src:
            # One batched device copy for every lane's boundary split (a
            # per-lane copy would rewrite the whole pool buffer B times).
            kv = self.backend.cow_copy(kv, cow_src, cow_dst)
        self._pool_counter()
        return kv, ws

    def _admit(self) -> list[_Request]:
        """Take the fair-order head request plus every queued request with
        the same sampling knobs, up to max_batch. Others stay queued.

        The scan order is the fair queue's deficit-weighted round-robin
        across tenants (runtime/admission.py) — per-tenant FIFO inside each
        subqueue, the old global FIFO when a single tenant (or
        ``fair_queue=False``) is in play. Expired-deadline requests are
        dropped here, BEFORE they can occupy a lane or map pages.

        Paged mode admits by FREE-PAGE accounting on top of the knob/lane
        rules: each candidate charges ``ceil(prompt / page_size) + reserve``
        pages against the pool (fresh at epoch start — the previous epoch
        released every lane); candidates that do not fit stay queued while
        smaller later ones may still land, which is exactly how a page pool
        beats slot accounting under short/variable-length load."""
        now = time.monotonic()
        state = {"knobs": None, "avail": None, "ckey": None}

        def radix_key(r: _Request):
            # The request's cached-prefix radix group at its solo-bucket
            # alignment (the same estimate _pages_for prices admission
            # with): requests extending the same cached chain share a key.
            n = len(r.prompt_ids)
            align = (prompt_bucket(n, self.max_seq_len) - n) % (
                self._alloc.page_size
            )
            return self._prefix.radix_key(r.prompt_ids, align)

        def defer(r: _Request, cause: str) -> str:
            # Decision audit (obs/efficiency.py): the verdict AND its
            # structured cause, so /explain answers "why was this queued".
            self.audit.record("defer", cause, rid=r.rid, tenant=r.tenant)
            return "skip"

        def accept(r: _Request) -> str:
            if r.deadline and now > r.deadline:
                self._expire_queued(r)
                return "drop"
            if state["knobs"] is None:
                # Fair-order head: defines the epoch's knobs: always taken
                # (submit() refused prompts over pool size, and the pool is
                # fresh — only cold prefix-cache pages can sit on the free
                # list, reclaimed on demand before charging).
                state["knobs"] = r.knobs()
                if self._prefix is not None and self.cache_aware_order:
                    # Cache-aware ordering (ROADMAP): the head's radix
                    # group defines the epoch's; candidates outside it
                    # defer one epoch so the head's chain is forked while
                    # hot — grouped traffic stops thrashing the cache
                    # between epochs (hit-rate pin in
                    # tests/test_prefix_serving.py). DRR bounds hold: the
                    # deferral is a "skip" inside the fair walk, and the
                    # next epoch's head is taken unconditionally.
                    state["ckey"] = radix_key(r)
                if self._alloc is not None:
                    need = self._pages_for(r)
                    free = self._alloc.pages_free
                    if need > free and self._prefix is not None:
                        free += self._prefix.reclaim(need - free, rid=r.rid)
                    state["avail"] = free - need
                self.audit.record(
                    "admit", "fair_order", rid=r.rid, tenant=r.tenant
                )
                return "take"
            if r.knobs() != state["knobs"]:
                return defer(r, "knob_incompatible")
            if state["ckey"] is not None and radix_key(r) != state["ckey"]:
                return defer(r, "cache_group")
            if state["avail"] is not None:
                need = self._pages_for(r)
                if need > state["avail"] and self._prefix is not None:
                    state["avail"] += self._prefix.reclaim(
                        need - state["avail"], rid=r.rid
                    )
                if need > state["avail"]:
                    return defer(r, "page_pressure")
                state["avail"] -= need
            self.audit.record(
                "admit", "fair_order", rid=r.rid, tenant=r.tenant
            )
            return "take"

        with self._cv:
            if not self._queue:
                return []
            group = self._queue.take(self.max_batch, accept)
            if not group:
                return []
            # Register as live while STILL under the lock that popped them:
            # cancel() must never observe a request as neither queued nor
            # live while it is on its way into an epoch.
            self._live_rids.update(r.rid for r in group)
        t_admit = time.perf_counter()
        for r in group:
            r.t_admit = t_admit  # queue-phase boundary for /explain
        self._record_admissions(group, "admitted")
        return group

    def _record_admissions(
        self, reqs: list[_Request], event: str, **fields
    ) -> None:
        """Queue-wait histogram + lifecycle event for requests leaving the
        queue — epoch admissions and continuous joins share the telemetry."""
        now = time.perf_counter()
        wait_h = metrics.registry.histogram(
            "cake_queue_wait_seconds",
            "Seconds a request waited in the queue before admission.",
        )
        counter = metrics.registry.counter(
            "cake_engine_admitted_total",
            "Requests admitted into a decode epoch (initial or join).",
        )
        for r in reqs:
            wait = now - r.t_submit
            wait_h.observe(wait)
            # Feed the deadline-aware shed estimator (admission.py): the
            # EWMA of these waits is what "estimated queue wait" means.
            self._wait_est.observe(wait)
            counter.inc()
            metrics.flight.record(
                event, r.rid, queue_wait_s=round(wait, 6), **fields
            )

    # -------------------------------------------------- execution (epochs)
    # Continuous batching: see the module docstring. An epoch = fixed lanes +
    # one shared slot counter; joins happen at chunk boundaries.

    def _run_batch(self, batch: list[_Request]) -> None:
        """One epoch, with failure ISOLATION (the taxonomy README documents):

        * ``BackendWorkerError`` (a worker died after the retry/replay budget
          — or an injected fault standing in for one) finishes only the
          epoch's LIVE streams with ``finish_reason="error"``; streams that
          already finished are untouched (their output was bit-identical to
          a fault-free run), pages return to the pool, and the engine keeps
          draining the queue.
        * Any OTHER exception is a bug: it reaches EVERY row admitted so far
          — including continuous-batching joiners that are no longer in
          ``batch`` or the queue — as a raised error, so no consumer can
          hang on a lost request."""
        from cake_tpu.runtime.batch_backend import BackendWorkerError

        rows: list[_RowState | None] = []
        # Fresh failover budget per epoch (count + cumulative migration
        # wall time); _run_epoch's dispatch sites consume it.
        self._fo_count = 0
        self._fo_spent_s = 0.0
        self._epoch_kv_retained = False
        # Fresh attribution scratch: the convoy meter and the blackbox's
        # stall/error captures are per-epoch.
        self._epoch_rows = []
        self._epoch_t0 = time.perf_counter()
        with self._cv:
            head_rid = batch[0].rid if batch else next(
                iter(self._spilled), ""
            )
        self._epoch_head_rid = head_rid
        self._epoch_stalled = False
        try:
            # The epoch span roots this epoch's timeline tree: prefill /
            # decode-chunk / join / page-extend spans nest under it, lane
            # tracks carry each request from admission to finish, and the
            # head request's id keys GET /trace?request_id=... retrieval.
            # Continuous mode calls the same structure a SEGMENT (one
            # contiguous shared-slot run) and nests a `step` span per
            # scheduler iteration inside it.
            with timeline.span(
                "epoch" if self.scheduler != "continuous" else "segment",
                rid=head_rid, track="engine",
                args={
                    "rows": len(batch),
                    "kv_mode": self.kv_mode,
                    "scheduler": self.scheduler,
                    # Kernel vs fallback choice, resolved exactly as the
                    # batched forward resolves it at trace time — so a trace
                    # captured on CPU says "xla" and one on TPU says
                    # "pallas" without reading configs.
                    "attention_impl": M.resolve_attention_impl(
                        self.config.attention_impl
                    ),
                    "fusion_impl": self.config.fusion_impl,
                },
            ):
                self._run_epoch(batch, rows)
        except BackendWorkerError as e:
            # Failure isolation: degrade the affected streams, not the fleet.
            log.warning("epoch lost its worker: %s", e)
            if not self._epoch_stalled and not self._stop:
                # A stall already captured its own bundle a moment ago (and
                # the rate limit would fold this one into it anyway); a
                # plain stop() mid-epoch is an operator action, not an
                # anomaly worth a bundle.
                self._capture("epoch-error", self._epoch_head_rid or None)
            for lane, row in enumerate(rows):
                if row is not None:
                    row.fail(str(e))
                    rows[lane] = None
        except Exception as e:  # noqa: BLE001 — surface to every consumer
            log.exception("epoch failed")
            for row in rows:
                if row is not None:
                    row.req.handle._emit(e)
                    row.req.handle._emit(_DONE)
                    row.close_span(error=str(e))
            # A non-worker exception is a bug: spilled streams must not
            # retry a deterministically failing seed forever — close them
            # with the same error every other consumer sees.
            with self._cv:
                self._fail_spilled_locked(str(e))
            # _loop's handler covers rows that never made it into `rows`.
            raise
        finally:
            # Paged: the epoch is over — EVERY lane's pages go back to the
            # pool (also on the error path, so _admit always sees the whole
            # pool free at the next epoch start). A CLEAN epoch end first
            # adopts each lane's prompt-prefix chain into the prefix cache
            # (insert-on-finish); a failed one must not — its pool bytes are
            # suspect and its buffer was not retained, so the whole cache is
            # cleared instead (chains never outlive their bytes).
            if self._alloc is not None:
                for lane in range(len(rows)):
                    if self._alloc.lane_mapped(lane):
                        self._lane_recycle(lane, insert=self._epoch_kv_retained)
            if self._prefix is not None and not self._epoch_kv_retained:
                self._prefix.clear(reason="epoch-failed")
                self.backend.drop_retained_kv()
            self._lane_leases.clear()
            self._lane_info.clear()
            if hasattr(self.backend, "set_epoch_capacity"):
                # The capacity dies with its epoch: direct backend use
                # between epochs (tests, drains) sees the full table again.
                self.backend.set_epoch_capacity(None)
            # The lockstep tax, measured: rows' convoy shares + lane idle
            # (also on error paths — a failed epoch's tax is still real).
            self._finish_epoch_convoy()
            # Whatever path ended the epoch, nothing in it is live anymore:
            # cancel() must answer False for these rids from here on.
            with self._cv:
                self._live_rids.difference_update(r.rid for r in batch)
                self._cancel_ids.difference_update(r.rid for r in batch)
                for row in rows:
                    if row is not None:
                        self._live_rids.discard(row.req.rid)
                        self._cancel_ids.discard(row.req.rid)

    def _run_epoch(self, batch: list[_Request], rows: list) -> None:
        from cake_tpu.models.llama.batch import (
            first_sample,
            layout_prompts,
            seed_rings,
        )

        seed_spills: list[_SpilledLane] = []
        if not batch:
            # Spill-seeded segment (continuous scheduler): the oldest
            # spill's knob group restores as the seed rows — their page
            # chains rebuild through the prefill arithmetic below, their
            # sampling state rides back from the host copies — and queued
            # requests with the same knobs join per step as usual.
            seed_spills = self._pop_spill_seed()
            if not seed_spills:
                return
            self._note_batch_started(len(seed_spills))
            head = seed_spills[0].row.req
            s, knobs = head.sampling, head.knobs()
        else:
            s, knobs = batch[0].sampling, batch[0].knobs()
        eos = set(self.config.eos_token_ids)
        if hasattr(self.backend, "trace_id"):
            # Wire-frame trace attribution (runtime/proto.py): remote hops of
            # this epoch carry the head request's id. An epoch serves many
            # rows; the head id identifies the epoch in worker-side logs.
            self.backend.trace_id = self._epoch_head_rid
        # Lane count: next pow2 of the group size, doubled once for join
        # headroom, capped at max_batch — light load must not pay
        # max_batch-wide prefill/decode, but continuous joins need free
        # lanes. Compiles stay bounded to log2 variants.
        n_seed = len(batch) or len(seed_spills)
        B = 1
        while B < n_seed:
            B *= 2
        B = min(max(B * 2, 2), self.max_batch)
        window = s.repeat_last_n

        # Lay out the initial group over B fixed lanes; spare lanes carry a
        # 1-token dummy prompt (bos) and are immediately free for joins.
        # A spill-seeded segment lays out each restored row's
        # ``history[:-1]`` instead (the KV the suffix arithmetic rebuilds;
        # ``history[-1]`` is the pending token at the shared slot — the
        # _migrate_kv invariant).
        if seed_spills:
            reqs: list[_Request | None] = [
                sp.row.req for sp in seed_spills
            ] + [None] * (B - n_seed)
            ids_list = [
                sp.row.history[:-1] for sp in seed_spills
            ] + [[self.config.bos_token_id]] * (B - n_seed)
            for lane, sp in enumerate(seed_spills):
                sp.row.lane = lane
                sp.row.t_close = 0.0
                rows.append(sp.row)
            rows.extend([None] * (B - n_seed))
            # (registered live by _pop_spill_seed, under its table lock)
        else:
            reqs = list(batch) + [None] * (B - len(batch))
            ids_list = [
                r.prompt_ids if r is not None else [self.config.bos_token_id]
                for r in reqs
            ]
            rows.extend(
                _RowState(r, eos, self.tokenizer, lane=lane, engine=self)
                if r is not None
                else None
                for lane, r in enumerate(reqs)
            )  # (already registered live by _admit, under its queue lock)
        # One timeline track per lane: the request span opens at admission
        # and closes at finish, so a Perfetto row shows the lane's occupancy
        # from prefill through its last token.
        for row in rows:
            if row is not None:
                row.open_span(slot=None)
        from cake_tpu.runtime.batch_backend import BackendWorkerError

        tokens, pads, bucket = layout_prompts(ids_list, self.max_seq_len)
        # ONE bounded attention capacity for the whole epoch (paged backends
        # only): enough slots for every admitted row's full token budget,
        # bucketed so compiles stay bounded, capped at max_seq_len. Every
        # position grid, kernel grid, and gather view of the epoch then
        # covers the live capacity instead of the padded max_seq — the
        # short-request TTFT win. ``cap`` (the epoch's slot ceiling) clamps
        # to it below, so joins (_take_joins gates budgets on cap), spec
        # verify (slot + K + 1 < cap), decode chunks, and failover
        # re-prefills all stay inside the ONE capacity — vary it mid-epoch
        # and the bit-identity chain breaks (PagedLocalBackend docstring).
        cap = self.max_seq_len
        if self._alloc is not None and hasattr(
            self.backend, "set_epoch_capacity"
        ):
            budgets = (
                [max(1, sp.row.req.max_tokens - sp.row.n)
                 for sp in seed_spills]
                if seed_spills
                else [r.max_tokens for r in batch]
            )
            reach = bucket + max(
                min(t, self.max_seq_len - bucket) for t in budgets
            )
            self.backend.set_epoch_capacity(
                min(
                    self.max_seq_len,
                    -(-reach // _CAPACITY_BUCKET) * _CAPACITY_BUCKET,
                )
            )
            cap = min(self.max_seq_len, self.backend.capacity_slots())
        t_prefill = time.perf_counter()
        while True:
            # The epoch-start prefill has no generated state to migrate: a
            # worker death here retries the whole block through the
            # failed-over route (init_kv refreshes sessions + pool).
            try:
                with timeline.span(
                    "prefill", rid=self._epoch_head_rid, track="engine",
                    args={
                        "bucket": int(bucket), "lanes": B,
                        "restored": len(seed_spills),
                    },
                ):
                    kv = self.backend.init_kv(B)  # paged: resets allocator
                    write_starts = None
                    if self._alloc is not None:
                        if self._prefix is not None:
                            kv, write_starts = self._prefix_layout(
                                reqs, rows, pads, bucket, kv, ids_list
                            )
                        else:
                            # Map each REAL lane's pages over its live window
                            # [pad, bucket); dummy lanes hold no pages (their
                            # writes drop, their reads are garbage nobody
                            # consumes). _admit's reserve accounting
                            # guarantees this cannot exhaust the fresh pool.
                            for lane, r in enumerate(reqs):
                                if r is not None:
                                    self._alloc.map_range(
                                        lane, int(pads[lane]), bucket
                                    )
                    pads_j = jnp.asarray(pads)
                    if write_starts is not None:
                        # Prefix-cache path (cold epochs included): prefill
                        # ONLY the window [start, bucket) covering every
                        # lane's uncached tail (64-bucketed width so
                        # compiles stay bounded); writes below each lane's
                        # threshold drop, so forked shared pages stay
                        # byte-stable. Cold lanes' thresholds are their
                        # pads — full compute through the SAME cached-chunk
                        # arithmetic warm lanes use, which is what makes
                        # warm streams bit-identical to cold ones (the
                        # plain fresh-chunk path reduces in a different
                        # order at the ulp level). Logits land at
                        # bucket - 1, exactly where the cold path reads
                        # them.
                        start = bucket - min(
                            -(-(bucket - int(write_starts.min())) // 64) * 64,
                            bucket,
                        )
                        logits, kv = self._dispatch(
                            "prefill",
                            lambda: self.backend.suffix_prefill(
                                tokens[:, start:], kv, pads_j,
                                write_starts, start,
                            ),
                        )
                    else:
                        logits, kv = self._dispatch(
                            "prefill",
                            lambda: self.backend.prefill(tokens, kv, pads_j),
                        )
                break
            except BackendWorkerError as e:
                self._failover_or_raise(e)
                if self._prefix is not None:
                    # The retry rebuilds the pool from zero (init_kv above):
                    # cached chains would outlive their bytes — drop them,
                    # their pins, and the stale retained buffer first.
                    self._prefix.clear(reason="prefill-retry")
                    self.backend.drop_retained_kv()
                    self._lane_leases.clear()
                    self._lane_info.clear()
        # Attribution: the shared left-padded prefill computes `bucket`
        # positions for every lane — a lane's own share scales with its
        # prompt, the rest is convoy (the padding half of the lockstep tax).
        dt_prefill = time.perf_counter() - t_prefill
        own_tok = 0
        for row in rows:
            if row is not None:
                if seed_spills:
                    own_tok += len(row.history) - 1
                    row.account_restore(dt_prefill, bucket)
                else:
                    own_tok += len(row.req.prompt_ids)
                    row.account_prefill(dt_prefill, bucket)
        # Hardware ledger: the shared window computed B x bucket
        # positions; only the live prompts (or restored histories) were
        # anyone's own work — the rest is pad. A spill-seeded segment's
        # prefill is REDONE work (restore_prefill), the preemption's price.
        self.efficiency.note_prefill(
            dt_prefill, B, bucket, own_tok, restore=bool(seed_spills)
        )
        ring, ring_idx = seed_rings(ids_list, window)
        if seed_spills:
            # Bit-identical resume: the pending token and the sampling
            # state (per-row key, penalty ring) come back from the host
            # copies taken at the spill boundary — nothing is re-sampled,
            # so the restored stream continues the exact token sequence
            # the uninterrupted run would have produced.
            for lane, sp in enumerate(seed_spills):
                if sp.ring is not None and window > 0:
                    ring[lane] = sp.ring
                    ring_idx[lane] = sp.ring_idx
            key0 = np.asarray(jax.random.PRNGKey(0))
            keys = jnp.asarray(
                np.stack(
                    [sp.key for sp in seed_spills]
                    + [key0] * (B - n_seed)
                )
            )
            first = np.asarray(
                [sp.row.history[-1] for sp in seed_spills]
                + [0] * (B - n_seed),
                np.int32,
            )
            for sp in seed_spills:
                sp.row.n_at_restore = sp.row.n
                self._note_restore(sp.row)
        else:
            keys = jnp.stack(
                [
                    jax.random.PRNGKey(
                        r.sampling.seed if r is not None else 0
                    )
                    for r in reqs
                ]
            )
            first, keys, ring, ring_idx = first_sample(
                logits, s, ring, ring_idx, keys
            )
            for lane, row in enumerate(rows):
                if row is not None:
                    row.push(int(first[lane]))
                    if row.done:
                        rows[lane] = None
        self._release_finished(rows)
        memwatch.sample("prefill")

        tok = jnp.asarray(first)
        ring_j = jnp.asarray(ring)
        ring_idx_j = jnp.asarray(ring_idx)
        slot = bucket  # slot of the most recent token, shared by all lanes
        # ``cap`` was fixed above: max_seq_len, or the epoch's bounded
        # capacity — which covers every admitted row's full budget, so the
        # clamp never truncates a stream below what max_seq_len would give.

        while slot < cap - 1:
            if self._stop:
                # stop() must not wait out a long epoch: close every live
                # stream now (consumers see the error, not a hang).
                err = RuntimeError("engine stopped")
                for lane, row in enumerate(rows):
                    if row is not None:
                        row.req.handle._emit(err)
                        row.req.handle._emit(_DONE)
                        row.close_span(error="engine stopped")
                        self._row_finished(row.req.rid)
                        rows[lane] = None
                return
            # Cancellation + deadline sweeps at the chunk boundary: flagged
            # rows finish "cancelled" and over-deadline rows finish
            # "deadline" NOW — their pages return to the pool (release just
            # below) and their lanes are joinable this very round; queued
            # requests past their deadline expire without ever admitting.
            self._apply_cancels(rows)
            self._apply_deadlines(rows)
            self._release_finished(rows)
            # Per-step scheduling (continuous): grant this step's prefill
            # budget (SLO-aware, runtime/admission.StepBudget), restore
            # spilled lanes FIRST (previously admitted work beats new
            # admissions), then admit queued joins the moment lanes and
            # pages are free. Epoch mode keeps the unbudgeted join path.
            # A join failure must not strand the popped requests: anything
            # not yet admitted into `rows` gets the error directly (rows
            # themselves are covered by _run_batch).
            budget = None
            step_span = None
            join_args: list = []
            if self.scheduler == "continuous":
                # A segment under sustained joins may never drain, so the
                # SLO feedback (fair-queue weights, shed scales — and the
                # burning signal the step budget reads) must apply HERE,
                # not only between segments. Rate-limited internally to
                # ~1/s; epoch mode keeps its between-epoch cadence.
                self._apply_slo_feedback()
                budget = {"left": self._grant_step_budget(rows)}
                step_span = timeline.begin(
                    "step", track="engine",
                    args={
                        "slot": int(slot),
                        "live": sum(r is not None for r in rows),
                        "budget": budget["left"],
                    },
                )
            try:
                if budget is not None:
                    (
                        tok, kv, keys, ring_j, ring_idx_j, pads_j
                    ) = self._take_restores(
                        knobs, rows, slot, cap, budget, tok, kv, keys,
                        ring_j, ring_idx_j, pads_j, s,
                    )
                join_args = self._take_joins(knobs, rows, slot, cap, budget)
                joined: set[int] = set()
                try:
                    for lane, req in join_args:
                        while True:
                            try:
                                tok, kv, keys, ring_j, ring_idx_j = self._join(
                                    req, lane, rows, slot, tok, kv, keys,
                                    ring_j, ring_idx_j, s,
                                )
                                break
                            except BackendWorkerError as e:
                                # A join prefill lost its worker: migrate the
                                # epoch's live rows to the new route, then
                                # retry the join there (the joiner saw no side
                                # effects — its first token samples only after
                                # backend.join returns).
                                self._failover_or_raise(e)
                                kv = self._migrate_kv(rows, B, slot)
                        joined.add(id(req))
                        pads_j = pads_j.at[lane].set(
                            slot - len(req.prompt_ids)
                        )
                except Exception as e:
                    for _, req2 in join_args:
                        if id(req2) not in joined:
                            if isinstance(e, BackendWorkerError):
                                # Same isolation as admitted rows: a graceful
                                # "error" finish, not a raised exception.
                                _fail_request(req2, str(e), engine=self)
                            else:
                                req2.handle._emit(e)
                                req2.handle._emit(_DONE)
                            # Popped-but-never-joined: finish() never runs
                            # for these, so deregister here or cancel()
                            # would claim them live forever.
                            self._row_finished(req2.rid)
                    raise
            finally:
                if step_span is not None:
                    timeline.end(
                        step_span, args={"joins": len(join_args)}
                    )
            live = sum(r is not None for r in rows)
            metrics.registry.gauge(
                "cake_batch_occupancy",
                "Live lockstep lanes at the current chunk boundary.",
            ).set(live)
            if not live:
                break
            if self._spec_applicable(s, slot, cap):
                # The verify chunk WRITES slots [slot, slot + K + 1) through
                # the block table — map those pages first (an unmapped slot
                # silently drops the chunk's KV). Dense backends skip this;
                # a page-truncated row degrades exactly like the decode path.
                if self._alloc is not None and not self._extend_pages(
                    rows, slot, self.speculative_k + 1,
                    spill_ctx=(keys, ring_j, ring_idx_j),
                ):
                    break  # every remaining row was truncated or spilled
                try:
                    # Mutable span args: _spec_round stamps the round's
                    # accepted advance + K before the span serializes at
                    # exit, so /explain can split accepted vs wasted time.
                    sargs = {"slot": int(slot)}
                    with timeline.span(
                        "spec-round", track="engine", args=sargs
                    ):
                        res = self._spec_round(
                            rows, kv, tok, slot, pads_j, keys, s,
                            span_args=sargs,
                        )
                except BackendWorkerError as e:
                    # Verify-round worker death: migrate the live streams,
                    # then take this round as a plain decode chunk (the
                    # half-written verify tail on the dead route is gone
                    # with it; sampling state never advanced).
                    self._failover_or_raise(e)
                    kv = self._migrate_kv(rows, B, slot)
                    res = None
                if res is not None:
                    tok, kv, keys, slot = res
                    continue
            n = min(self.decode_chunk_size, cap - 1 - slot)
            if self._alloc is not None and not self._extend_pages(
                rows, slot, n, spill_ctx=(keys, ring_j, ring_idx_j)
            ):
                break  # every remaining row was truncated or spilled
            # The np.asarray readback inside the span blocks on the device,
            # so the slice is real chunk compute, not dispatch time.
            t_chunk = time.perf_counter()
            try:
                with timeline.span(
                    "decode-chunk", track="engine",
                    args={"slot": int(slot), "n": int(n), "live": live},
                ):

                    def _chunk():
                        out = self.backend.decode(
                            kv, tok, slot, pads_j, keys, ring_j,
                            ring_idx_j, n, s,
                        )
                        # The readback rides the watchdog too: a device-
                        # level hang surfaces here, not just a stuck
                        # dispatch.
                        return out, np.asarray(out[0])

                    (
                        (toks, kv, keys, ring_j, ring_idx_j), toks_np
                    ) = self._dispatch("decode", _chunk)
            except BackendWorkerError as e:
                # Transparent recovery: a worker died and a healthy replica
                # exists — rebuild every live stream's KV on the new route
                # and REDO this chunk. The failed chunk's partial steps are
                # discarded with the dead route; tok/keys/rings still hold
                # the pre-chunk state, so the redone chunk samples the
                # exact same tokens (greedy streams stay bit-identical).
                self._failover_or_raise(e)
                kv = self._migrate_kv(rows, B, slot)
                continue
            dt_chunk = time.perf_counter() - t_chunk
            # Feed the step-budget clock (continuous): deadline slack is
            # measured in recent chunk walls.
            self._step_budget.observe_chunk(dt_chunk)
            live_rows = [
                (lane, row) for lane, row in enumerate(rows)
                if row is not None
            ]
            consumed = {
                lane: row.peek_consumed(toks_np[lane])
                for lane, row in live_rows
            }
            # Hardware ledger: the chunk computed B x n positions —
            # consumed ones are decode goodput, live-but-unconsumed tails
            # are convoy, dead lanes are pad. Noted BEFORE the pushes for
            # the same flush-ordering reason as account_decode below.
            self.efficiency.note_decode(
                dt_chunk, len(rows), n, len(live_rows),
                sum(consumed.values()), slot=slot,
            )
            for lane, row in live_rows:
                # Account BEFORE pushing: a row that finishes mid-chunk
                # flushes its attribution from inside push() -> finish(),
                # so the final chunk's decode share (and its unconsumed-
                # tail convoy — the very number the convoy meter exists
                # for) must already be on the row by then.
                row.account_decode(dt_chunk, n, consumed[lane])
                for t in toks_np[lane]:
                    row.push(int(t))
                    if row.done:
                        rows[lane] = None
                        break
            self._release_finished(rows)
            memwatch.sample("decode", min_interval_s=0.05)
            tok = toks[:, -1]
            slot += n

        for row in rows:
            if row is not None:
                row.finish()  # cache edge: stream closes with finish "length"
        memwatch.sample("epoch-end")
        if self._prefix is not None:
            # Persistent pool: the final buffer carries every cached chain's
            # bytes into the next epoch's init_kv.
            self.backend.retain_kv(kv)
        self._epoch_kv_retained = True  # clean end: the finally path inserts
        # (_run_batch's finally returns every lane's pages to the pool.)

    # ------------------------------------------------- paged-pool accounting

    def _release_finished(self, rows: list) -> None:
        """Return every finished (or never-real) lane's pages to the pool —
        AND unmap them, so the lane's continuing lockstep garbage writes drop
        instead of landing in pages a later join may recycle."""
        if self._alloc is None:
            return
        released = False
        for lane, row in enumerate(rows):
            if row is None and self._alloc.lane_mapped(lane):
                self._lane_recycle(lane)
                released = True
        if released:
            self._pool_counter()

    def _lane_recycle(self, lane: int, insert: bool = True) -> None:
        """One lane's pages go back to the pool — in prefix-cache order:
        FIRST adopt the lane's prompt-prefix chain into the cache (the pages
        gain cache references while still alive), THEN unpin the chain the
        lane forked at admission, THEN drop the lane's own mappings. A
        cancelled stream still inserts (its prompt prefill completed and its
        prefix KV is exact); failed epochs pass ``insert=False`` — their
        bytes are suspect and the cache is cleared right after."""
        if self._prefix is not None:
            info = self._lane_info.pop(lane, None)
            if insert and info is not None:
                req, pad = info
                self._prefix.insert(lane, req.prompt_ids, pad, rid=req.rid)
            self._prefix.release(self._lane_leases.pop(lane, None))
        self._alloc.release(lane)

    def _pool_counter(self) -> None:
        """Pool occupancy onto the timeline's counter track — the same view
        as the cake_kv_pages_* gauges, but on the span clock, so page churn
        lines up with the decode/extend spans that caused it."""
        timeline.counter(
            "kv_pages",
            {
                "in_use": float(
                    self._alloc.pages_total - self._alloc.pages_free
                ),
                "free": float(self._alloc.pages_free),
            },
            track="mem",
        )

    def _extend_pages(
        self, rows: list, slot: int, n: int, spill_ctx: tuple | None = None,
    ) -> bool:
        """Grow every live lane's mapping to cover the next decode chunk
        (slots [slot, slot + n)); only page-boundary crossings allocate.

        Pool pressure escalates in order: (1) reclaim cold prefix-cache
        pages, retrying as long as a pass makes progress — a single
        under-freeing pass must never strand a stream the next pass could
        save; (2) under the CONTINUOUS scheduler, PREEMPT — spill the
        lowest-priority lane host-side (history + sampling state; restored
        bit-identically when pages free) rather than killing anything;
        (3) only then force-finish as "length" (epoch mode, or a lane no
        pool state can serve). Degradation costs one stream a pause or a
        truncation, never the epoch. Returns False when no live row
        survived (the epoch has nothing left to decode this step).
        """
        from cake_tpu.models.llama.paged_cache import PageExhausted

        any_live = grew = False
        free0 = self._alloc.pages_free
        with timeline.span(
            "page-extend", track="engine", args={"slot": int(slot), "n": int(n)}
        ):
            for lane, row in enumerate(rows):
                if row is None:
                    continue
                try:
                    try:
                        self._alloc.map_range(lane, slot, slot + n)
                    except PageExhausted:
                        # Evict-then-retry until a reclaim pass frees
                        # nothing new: pool pressure reclaims COLD
                        # prefix-cache pages before degrading a live
                        # stream, and a pass that under-frees (pages still
                        # lane-shared, pins releasing between passes) gets
                        # another chance instead of force-finishing a
                        # stream reclaimable pages could have served.
                        self._reclaim_and_map(lane, slot, n, row.req.rid)
                    any_live = True
                except PageExhausted:
                    if (
                        self.scheduler == "continuous"
                        and spill_ctx is not None
                    ):
                        if self._preempt_for(rows, lane, slot, n, spill_ctx):
                            any_live = True
                        grew = True
                        continue
                    self.stats["page_truncations"] += 1
                    row.req.handle.finish_reason = "length"
                    metrics.flight.record(
                        "page-truncated", row.req.rid, slot=slot,
                        completion_tokens=row.n,
                    )
                    timeline.instant(
                        "page-truncated", rid=row.req.rid,
                        track=f"lane{lane}", args={"slot": int(slot)},
                    )
                    row.finish()
                    rows[lane] = None
                    self._lane_recycle(lane)
                    grew = True
            grew = grew or self._alloc.pages_free != free0
        if grew:
            self._pool_counter()
        return any_live

    def _reclaim_and_map(
        self, lane: int, slot: int, n: int, rid: str
    ) -> None:
        """Map [slot, slot + n) for ``lane``, evicting prefix-cache pages
        between attempts for as long as eviction makes progress. Raises
        PageExhausted only when a whole reclaim pass freed nothing."""
        from cake_tpu.models.llama.paged_cache import PageExhausted

        if self._prefix is None:
            raise PageExhausted(
                f"lane {lane} needs pages for [{slot}, {slot + n}) and no "
                "prefix cache exists to reclaim from"
            )
        while True:
            freed = self._prefix.reclaim(
                self._alloc.pages_needed(n) + 1, rid=rid
            )
            try:
                self._alloc.map_range(lane, slot, slot + n)
                return
            except PageExhausted:
                if not freed:
                    raise

    # ------------------------------------------- preemption (spill/restore)
    # Continuous scheduler only (README "Continuous scheduling"): page
    # pressure PREEMPTS instead of force-finishing. A spilled lane's pages
    # return to the pool; its host-side record (history + per-row PRNG key
    # + penalty ring — everything the chunk-boundary invariant needs) waits
    # in ``_spilled`` until pages free, then a restore re-prefills
    # ``history[:-1]`` into a window ending at the shared slot through the
    # SAME join/suffix arithmetic a continuous-batching join uses — the
    # _migrate_kv proof pattern, so resumed streams are bit-identical to
    # uninterrupted ones (greedy AND sampled; pinned in
    # tests/test_continuous_serving.py).

    def _pick_victim(self, rows: list, lane: int) -> int | None:
        """The lane to preempt so ``lane`` can extend: lowest priority
        first (never a HIGHER priority than the starving lane), then the
        one holding the most pages (maximum relief per spill), then the
        youngest. None = no other lane qualifies (the starving lane spills
        itself — it parks, it does not die)."""
        me = rows[lane].req.priority
        best = None
        best_key = None
        for i, row in enumerate(rows):
            if row is None or i == lane or row.req.priority > me:
                continue
            key = (
                row.req.priority,
                -self._alloc.lane_pages(i),
                -row.t_open,
            )
            if best_key is None or key < best_key:
                best, best_key = i, key
        return best

    def _preempt_for(
        self, rows: list, lane: int, slot: int, n: int, spill_ctx: tuple
    ) -> bool:
        """Spill victims until ``lane``'s next chunk maps (True), or spill
        ``lane`` itself when nothing lower-priority is left to take pages
        from (False — the lane parked; its stream resumes bit-identically
        once a restore finds room)."""
        from cake_tpu.models.llama.paged_cache import PageExhausted

        while True:
            victim = self._pick_victim(rows, lane)
            if victim is None:
                self._spill_lane(rows, lane, slot, spill_ctx, reason="self")
                return False
            self._spill_lane(
                rows, victim, slot, spill_ctx, reason="preempted"
            )
            try:
                try:
                    self._alloc.map_range(lane, slot, slot + n)
                except PageExhausted:
                    # A victim's prompt-prefix pages were adopted by the
                    # prefix cache on recycle — reclaim them (and any other
                    # cold chains) before trying the next victim.
                    self._reclaim_and_map(lane, slot, n, rows[lane].req.rid)
                return True
            except PageExhausted:
                continue

    def _note_cancelled(self, row: "_RowState", where: str) -> None:
        """The one cancellation-bookkeeping sequence (stats + counter +
        flight event), shared by the spilled and raced-preemption paths;
        the caller still owns the row.cancel()/_emit that closes the
        stream. ``stats`` keeps the engine-wide convention — best-effort
        unguarded writes, /stats reads a copy — so this stays consistent
        with every other site instead of making one counter look
        lock-protected."""
        self.stats["cancelled"] += 1
        metrics.registry.counter(
            "cake_cancelled_total", "Requests cancelled (queued or live)."
        ).inc()
        metrics.flight.record(
            "cancelled", row.req.rid, where=where, completion_tokens=row.n,
        )

    def _spill_lane(
        self, rows: list, lane: int, slot: int, spill_ctx: tuple,
        reason: str,
    ) -> None:
        """Preempt one lane at the chunk boundary: host-copy its sampling
        state, return its pages (prompt-prefix chain adopted by the prefix
        cache — the restore may fork it right back), and park it in the
        spill table. A cancel that raced the preemption wins: the stream
        finishes cancelled instead of parking. A lane that made ZERO
        progress since its last restore and is spilling ITSELF again can
        never advance on this pool (its very next chunk needs pages the
        pool cannot supply even fully drained) — it force-finishes
        "length" instead of livelocking through zero-progress
        respill/reseed cycles."""
        keys, ring_j, ring_idx_j = spill_ctx
        row = rows[lane]
        rid = row.req.rid
        if reason == "self" and row.n == row.n_at_restore:
            # The restore re-prefilled the whole history and the first
            # chunk still could not map: re-parking would reseed the
            # IDENTICAL segment forever. Same honest degradation as epoch
            # mode, discovered one re-prefill later.
            self.stats["page_truncations"] += 1
            row.req.handle.finish_reason = "length"
            metrics.flight.record(
                "page-truncated", rid, slot=int(slot), where="respill",
                completion_tokens=row.n,
            )
            rows[lane] = None
            row.finish()
            self._lane_recycle(lane)
            return
        window = int(ring_j.shape[1]) if ring_j.ndim == 2 else 0
        sp = _SpilledLane(
            row=row,
            key=np.asarray(keys[lane]),
            ring=np.asarray(ring_j[lane]) if window > 0 else None,
            ring_idx=int(np.asarray(ring_idx_j[lane])) if window > 0 else 0,
        )
        rows[lane] = None
        cancelled = False
        with self._cv:
            if rid in self._cancel_ids:
                self._cancel_ids.discard(rid)
                cancelled = True
            else:
                self._spilled[rid] = sp
                self._live_rids.discard(rid)
        if cancelled:
            self._note_cancelled(row, "epoch")
            row.cancel()
            self._lane_recycle(lane)
            return
        self.stats["preemptions"] += 1
        # Decision audit: a victim spilled for someone else's pages is a
        # PREEMPT; a starving lane parking itself is a SPILL — both
        # caused by page pressure (the victim choice itself is the
        # priority policy, carried in the detail).
        self.audit.record(
            "preempt" if reason == "preempted" else "spill",
            "page_pressure", rid=rid, tenant=row.req.tenant,
            detail=reason,
        )
        metrics.registry.counter(
            "cake_preemptions_total",
            "Lanes preempted under page pressure (continuous scheduler): "
            "page chain spilled host-side, stream parked for a "
            "bit-identical restore.",
        ).inc()
        metrics.flight.record(
            "preempted", rid, slot=int(slot), reason=reason,
            completion_tokens=row.n, priority=row.req.priority,
        )
        timeline.instant(
            "preempted", rid=rid, track=f"lane{lane}",
            args={"slot": int(slot), "reason": reason},
        )
        row.close_span()
        self._lane_recycle(lane, insert=True)

    def _pop_spill_seed(self) -> list["_SpilledLane"]:
        """Seed rows for a spill-seeded segment: the oldest spill's knob
        group, oldest first, as many as fit the lanes and the (fully free)
        pool. Spills whose history can NEVER be served again — the window
        or the whole pool is too small for it — force-finish "length" here
        instead of parking forever."""
        doomed: list[_SpilledLane] = []
        out: list[_SpilledLane] = []
        with self._cv:
            if not self._spilled:
                return []
            order = sorted(self._spilled.values(), key=lambda e: e.t)
            knobs = order[0].row.req.knobs()
            claimed = 0
            for sp in order:
                if len(out) >= self.max_batch:
                    break
                row = sp.row
                if row.req.knobs() != knobs:
                    continue
                hist = len(row.history) - 1
                if prompt_bucket(hist, self.max_seq_len) >= self.max_seq_len:
                    del self._spilled[row.req.rid]
                    doomed.append(sp)
                    continue
                if self._alloc is not None:
                    need = (
                        self._alloc.pages_needed(hist)
                        + self._alloc.reserve_pages
                    )
                    if need + claimed > self._alloc.pages_total:
                        if need > self._alloc.pages_total:
                            del self._spilled[row.req.rid]
                            doomed.append(sp)
                        continue
                    claimed += need
                del self._spilled[row.req.rid]
                # Live the moment it leaves the spill table, under the SAME
                # lock — cancel() must never observe a request as neither
                # queued, nor spilled, nor live (the _admit no-gap rule).
                self._live_rids.add(row.req.rid)
                out.append(sp)
        for sp in doomed:
            self.stats["page_truncations"] += 1
            sp.row.req.handle.finish_reason = "length"
            metrics.flight.record(
                "page-truncated", sp.row.req.rid, where="spilled",
                completion_tokens=sp.row.n,
            )
            sp.row.finish()
        return out

    def _take_restores(
        self, knobs, rows, slot, cap, budget, tok, kv, keys, ring_j,
        ring_idx_j, pads_j, s,
    ):
        """Step-boundary restores: re-attach spilled lanes (oldest first,
        same knobs) into free lanes while pages and the step's prefill
        budget allow. Restores run BEFORE joins — previously admitted work
        outranks new admissions — and charge the same budget, so a restore
        storm cannot starve decode any more than a join storm can."""
        with self._cv:
            empty = not self._spilled
        if empty:
            return tok, kv, keys, ring_j, ring_idx_j, pads_j
        free = [i for i, r in enumerate(rows) if r is None]
        if not free:
            return tok, kv, keys, ring_j, ring_idx_j, pads_j
        picks: list[tuple[int, _SpilledLane]] = []
        claimed = 0
        with self._cv:
            for sp in sorted(self._spilled.values(), key=lambda e: e.t):
                if not free:
                    break
                row = sp.row
                req = row.req
                hist = len(row.history) - 1
                if req.knobs() != knobs:
                    self.audit.record(
                        "defer", "knob_incompatible", rid=req.rid,
                        tenant=req.tenant, detail="spilled",
                    )
                    continue  # wrong trace for this segment
                if hist > slot or cap - 1 - slot < req.max_tokens - row.n:
                    # needs a taller segment, or restoring here would
                    # truncate below what a solo segment delivers
                    self.audit.record(
                        "defer", "capacity", rid=req.rid,
                        tenant=req.tenant, detail="spilled",
                    )
                    continue
                if budget is not None and budget["left"] < hist:
                    self.audit.record(
                        "defer", "step_budget", rid=req.rid,
                        tenant=req.tenant, detail="spilled",
                    )
                    continue
                if self._alloc is not None:
                    need = (
                        self._alloc.pages_needed(hist)
                        + self._alloc.reserve_pages
                    )
                    avail = self._alloc.pages_free - claimed + (
                        self._prefix.reclaimable()
                        if self._prefix is not None
                        else 0
                    )
                    if need > avail:
                        self.audit.record(
                            "defer", "page_pressure", rid=req.rid,
                            tenant=req.tenant, detail="spilled",
                        )
                        continue
                    claimed += need
                if budget is not None:
                    budget["left"] -= hist
                del self._spilled[req.rid]
                self._live_rids.add(req.rid)
                self.audit.record(
                    "restore", "fair_order", rid=req.rid, tenant=req.tenant
                )
                picks.append((free.pop(0), sp))
        from cake_tpu.models.llama.paged_cache import PageExhausted

        for lane, sp in picks:
            try:
                (
                    tok, kv, keys, ring_j, ring_idx_j, pads_j
                ) = self._restore_lane(
                    sp, lane, rows, slot, tok, kv, keys, ring_j,
                    ring_idx_j, pads_j,
                )
            except PageExhausted:
                # The accounting above raced an eviction estimate: put the
                # spill back (it retries next step) — never fail the step.
                self._unwind_restore(lane, sp)
            except BaseException:
                # Worker death mid-restore: re-park the spill (the next
                # segment retries through the failed-over route) and let
                # the epoch-level isolation handle the live rows.
                self._unwind_restore(lane, sp)
                raise
        return tok, kv, keys, ring_j, ring_idx_j, pads_j

    def _unwind_restore(self, lane: int, sp: "_SpilledLane") -> None:
        rid = sp.row.req.rid
        sp.row.close_span()
        sp.row.t_close = 0.0
        cancelled = False
        with self._cv:
            if rid in self._cancel_ids:
                # A cancel landed while the rid was transiently live for
                # the failed restore: honor it NOW (the documented
                # cancels-reach-spilled-lanes-immediately contract) instead
                # of deferring it to an unboundedly-later restore.
                self._cancel_ids.discard(rid)
                self._live_rids.discard(rid)
                cancelled = True
            else:
                self._spilled[rid] = sp
                self._live_rids.discard(rid)
        if self._alloc is not None and self._alloc.lane_mapped(lane):
            self._lane_recycle(lane, insert=False)
        elif self._prefix is not None:
            self._prefix.release(self._lane_leases.pop(lane, None))
            self._lane_info.pop(lane, None)
        if cancelled:
            self._note_cancelled(sp.row, "spilled")
            sp.row.cancel()

    def _restore_lane(
        self, sp: "_SpilledLane", lane: int, rows, slot, tok, kv, keys,
        ring_j, ring_idx_j, pads_j,
    ):
        """Re-attach one spilled lane at the shared slot: re-prefill
        ``history[:-1]`` into a window ending at ``slot`` (suffix-join
        arithmetic under a prefix cache — the restore may fork the very
        chain its spill inserted — plain join otherwise), then put the
        host-saved sampling state back. The pending token ``history[-1]``
        was already delivered before the spill; nothing is re-sampled."""
        row = sp.row
        req = row.req
        hist = row.history[:-1]
        pad = slot - len(hist)
        row.lane = lane
        row.t_close = 0.0
        row.open_span(slot=slot)
        t0 = time.perf_counter()
        try:
            with timeline.span(
                "restore", rid=req.rid, track="engine",
                args={"lane": lane, "slot": int(slot), "tokens": len(hist)},
            ):
                if self._alloc is not None and self._prefix is not None:
                    fresh, pair = self._fork_lane(
                        lane, req, pad, slot, ids=hist
                    )
                    if pair is not None:
                        kv = self.backend.cow_copy(kv, [pair[0]], [pair[1]])
                    W = min(-(-(slot - fresh) // 64) * 64, slot)
                    start = slot - W
                    row_tokens = np.zeros((1, W), np.int32)
                    lo = max(pad, start)
                    row_tokens[0, lo - start: slot - start] = hist[lo - pad:]
                    _, kv = self._dispatch(
                        "join",
                        lambda: self.backend.suffix_join(
                            kv, row_tokens, np.asarray([pad], np.int32),
                            np.asarray([fresh], np.int32), lane, start,
                        ),
                    )
                else:
                    # Same window arithmetic as a plain join (_join_inner):
                    # W >= slot, pad/slot are absolute.
                    W = min(-(-slot // 64) * 64, self.max_seq_len)
                    row_tokens = np.zeros((1, W), np.int32)
                    row_tokens[0, pad:slot] = hist
                    if self._alloc is not None:
                        self._alloc.map_range(lane, pad, slot)
                    _, kv = self._dispatch(
                        "join",
                        lambda: self.backend.join(
                            kv, row_tokens,
                            jnp.asarray([pad], jnp.int32),
                            jnp.asarray([slot], jnp.int32), lane,
                        ),
                    )
        except BaseException as e:
            row.close_span(error=str(e)[:200])
            raise
        dt_restore = time.perf_counter() - t0
        row.phase["restore"] += dt_restore
        # Hardware ledger: a restore's re-prefill is REDONE work — the
        # preemption's device price, booked to its own bucket.
        self.efficiency.note_prefill(
            dt_restore, 1, W, min(len(hist), W), restore=True
        )
        window = int(ring_j.shape[1]) if ring_j.ndim == 2 else 0
        if window > 0 and sp.ring is not None:
            ring_j = ring_j.at[lane].set(jnp.asarray(sp.ring))
            ring_idx_j = ring_idx_j.at[lane].set(int(sp.ring_idx))
        keys = keys.at[lane].set(jnp.asarray(sp.key))
        tok = tok.at[lane].set(int(row.history[-1]))
        pads_j = pads_j.at[lane].set(pad)
        rows[lane] = row
        row.n_at_restore = row.n
        if self._alloc is not None:
            self._pool_counter()
        self._note_restore(row)
        return tok, kv, keys, ring_j, ring_idx_j, pads_j

    def _note_restore(self, row: "_RowState") -> None:
        self.stats["restores"] += 1
        metrics.registry.counter(
            "cake_restores_total",
            "Spilled lanes re-attached to a running segment "
            "(bit-identical resume).",
        ).inc()
        metrics.flight.record(
            "restored", row.req.rid, completion_tokens=row.n,
            lane=row.lane,
        )
        timeline.instant(
            "restored", rid=row.req.rid, track=f"lane{row.lane}",
        )

    def _grant_step_budget(self, rows: list) -> int:
        """This step's prefill grant in prompt tokens (StepBudget,
        runtime/admission.py): scaled UP while the SLO tracker says some
        tenant is burning (queue waits are missing the TTFT objective —
        drain admissions faster) and DOWN while a live stream's deadline
        slack is inside a few chunk walls (protect running deadlines from
        prefill stalls)."""
        now = time.monotonic()
        slack = None
        for row in rows:
            if row is not None and row.req.deadline:
                left = row.req.deadline - now
                if slack is None or left < slack:
                    slack = left
        burning = bool(self._slo_shed_scale)
        grant = self._step_budget.grant(
            burning=burning, tightest_slack_s=slack,
        )
        if burning or slack is not None:
            # SLO feedback moved this step's prefill-vs-decode split; the
            # audit keeps only state CHANGES (consecutive-dedupe), so a
            # long burning run is one ring entry, not one per step.
            self.audit.record(
                "budget", "slo_feedback",
                detail="burning" if burning else "deadline_slack",
            )
        return grant

    # ------------------------------------------------- batched speculative

    def _spec_applicable(self, s, slot: int, cap: int) -> bool:
        sampled = s.temperature is not None and s.temperature > 0.0
        return (
            self.speculative_k > 0
            # A repeat penalty makes the in-chunk target history-dependent;
            # both acceptance modes gate on it (generator does the same).
            and s.repeat_penalty == 1.0
            # Gate on the method THIS round will call — a backend may grow
            # greedy verify before sampled verify, and the TCP backend
            # shadows both with None when a worker lacks the capability.
            and callable(
                getattr(
                    self.backend,
                    "verify_sampled" if sampled else "verify_greedy",
                    None,
                )
            )
            # The verify chunk writes slots [slot, slot + K].
            and slot + self.speculative_k + 1 < cap
        )

    def _spec_round(self, rows, kv, tok, slot, pads_j, keys, s,
                    span_args: dict | None = None):
        """One batched verify round: every live row drafts K tokens from its
        own history (prompt lookup), one shared cached-chunk forward verifies
        all rows, the epoch advances by the MINIMUM accepted length across
        live rows (rows' surplus accepted tokens are re-verified next round —
        correctness never depends on the drafts, see models/llama/batch.py).

        Returns (tok, kv, keys, slot) or None when NO live row produced a
        draft (the caller falls back to a plain decode chunk). Rows without
        a draft still ride the shared verify (``n_drafts = 0``): the chunk's
        first position scores exactly their plain-decode next token, so a
        non-repetitive co-batched row costs the round its surplus (the
        cross-row MIN advance) but never disables speculation for the rows
        that DO draft — the per-round efficiency stays visible as
        ``spec_tokens / spec_rounds``.
        """
        from cake_tpu.models.llama.speculative import (
            greedy_accept,
            propose_lookup,
        )

        K = self.speculative_k
        B = len(rows)
        t_round = time.perf_counter()
        tok_np = np.asarray(tok)
        drafts = np.zeros((B, K), np.int32)
        n_drafts = np.zeros((B,), np.int32)
        if self.proposer_factory is not None and self._proposer_mode is None:
            probe = self.proposer_factory()
            if hasattr(probe, "propose_batch"):
                self._batched_proposer = probe
                self._proposer_mode = "batched"
            else:
                self._spare_proposer = probe  # first lane claims it below
                self._proposer_mode = "per-lane"
        if self._proposer_mode == "batched":
            bp = self._batched_proposer
            can = getattr(bp, "can_propose", None)
            # Lanes the proposer cannot serve ride the round draft-less
            # (history None skips them) instead of aborting it for everyone.
            lane_drafts = bp.propose_batch(
                [
                    row.history
                    if row is not None
                    and (can is None or can(len(row.history), K))
                    else None
                    for row in rows
                ],
                K,
            )
        else:
            lane_drafts = []
            for lane, row in enumerate(rows):
                if row is None:
                    lane_drafts.append(None)
                    continue
                if self.proposer_factory is not None:
                    if lane not in self._lane_proposers:
                        self._lane_proposers[lane] = (
                            self._spare_proposer or self.proposer_factory()
                        )
                        self._spare_proposer = None
                    prop = self._lane_proposers[lane]
                    can = getattr(prop, "can_propose", None)
                    if can is not None and not can(len(row.history), K):
                        lane_drafts.append(None)  # rides draft-less
                        continue
                    lane_drafts.append(prop.propose(row.history, K) or None)
                else:
                    lane_drafts.append(propose_lookup(row.history, K) or None)
        n_drafting = 0
        for lane, row in enumerate(rows):
            if row is None:
                continue
            d = lane_drafts[lane]
            if d:
                drafts[lane, : len(d)] = d
                n_drafts[lane] = len(d)
                n_drafting += 1
        if n_drafting == 0:
            return None  # nobody drafted: plain decode is strictly cheaper
        tokens = np.concatenate([tok_np[:, None], drafts], axis=1)  # [B, K+1]

        sampled = s.temperature is not None and s.temperature > 0.0
        if sampled:
            n_accs, nxts, kv, keys = self._dispatch(
                "verify",
                lambda: self.backend.verify_sampled(
                    kv, tokens, slot, pads_j, drafts, n_drafts, keys, s
                ),
            )
            n_accs, nxts = np.asarray(n_accs), np.asarray(nxts)
            cand = [
                [*drafts[l, : n_accs[l]].tolist(), int(nxts[l])]
                for l in range(B)
            ]
        else:
            ids, kv = self._dispatch(
                "verify",
                lambda: self.backend.verify_greedy(kv, tokens, slot, pads_j),
            )
            ids = np.asarray(ids)
            cand = []
            for l in range(B):
                n, nxt = greedy_accept(drafts[l], ids[l])
                cand.append([*drafts[l][:n].tolist(), nxt])

        # Shared-slot advance: the minimum candidate length over LIVE rows
        # (dead/dummy lanes are excluded — joins replace their KV wholesale).
        a = min(len(cand[l]) for l, row in enumerate(rows) if row is not None)
        dt_round = time.perf_counter() - t_round
        if span_args is not None:
            span_args["accepted"] = int(a)
            span_args["k"] = int(K)
        live_rows = [
            (lane, row) for lane, row in enumerate(rows) if row is not None
        ]
        used_map = {
            lane: row.peek_consumed(cand[lane][:a]) for lane, row in live_rows
        }
        # Hardware ledger: the verify chunk computed B x (K+1) positions;
        # accepted ones are spec goodput, the live remainder is the wasted
        # half of the speculative split, dead lanes are pad.
        self.efficiency.note_spec(
            dt_round, B, K, len(live_rows), sum(used_map.values()),
            slot=int(slot),
        )
        for lane, row in live_rows:
            # The verify chunk computed K+1 positions; the row consumes
            # `used` of them — the accepted/wasted split of the round.
            # Accounted BEFORE the pushes (a finishing row flushes its
            # attribution from inside push() -> finish()).
            row.account_spec(dt_round, K, used_map[lane])
            for t in cand[lane][:a]:
                row.push(int(t))
                if row.done:
                    rows[lane] = None
                    break
        new_tok = np.asarray(
            [c[a - 1] if len(c) >= a else 0 for c in cand], np.int32
        )
        self.stats["spec_rounds"] += 1
        self.stats["spec_tokens"] += a
        return jnp.asarray(new_tok), kv, keys, slot + a

    def _take_joins(
        self, knobs: tuple, rows: list, slot: int, cap: int,
        budget: dict | None = None,
    ) -> list[tuple[int, _Request]]:
        """Pop queued requests that can join NOW: same sampling knobs, prompt
        short enough to end at the shared slot, a free lane, and enough
        decode budget left that joining is not worse than waiting.
        ``budget`` (continuous scheduler) caps this step's cumulative join
        prefill work in prompt tokens — the SLO-aware prefill-vs-decode
        split; candidates over it stay queued for the next step.

        Candidates walk in the fair queue's DRR order. Two fairness rules
        compose: within a TENANT, scanning stops at its first request with
        DIFFERENT knobs (per-tenant FIFO — a tenant's own requests never
        jump each other); across the EPOCH, no joins are taken at all while
        the OLDEST queued request is knob-incompatible with it, so a
        waiting different-knob request still bounds the epoch (the old
        global-FIFO guarantee) instead of starving behind endless same-knob
        joins from other tenants.
        """
        free = [i for i, r in enumerate(rows) if r is None]
        if not free:
            return []
        now = time.monotonic()
        # Paged: joiners charge prompt pages + reserve against the pool,
        # cumulatively across this round's joins (allocation happens in
        # _join, after this accounting admits them).
        state = {
            "avail": self._alloc.pages_free if self._alloc is not None else None
        }

        def defer(req: _Request, cause: str, verdict: str = "skip") -> str:
            self.audit.record(
                "defer", cause, rid=req.rid, tenant=req.tenant
            )
            return verdict

        def accept(req: _Request) -> str:
            if req.deadline and now > req.deadline:
                self._expire_queued(req)
                return "drop"
            if req.knobs() != knobs:
                # per-tenant FIFO: nothing jumps this request
                return defer(req, "knob_incompatible", verdict="next")
            n_ids = len(req.prompt_ids)
            # A solo epoch would give the request
            # min(max_tokens, max_seq - bucket) tokens — it sizes its
            # OWN bounded capacity from its own max_tokens, NOT this
            # epoch's (possibly much smaller) cap. Join only when the
            # epoch's remaining budget matches that, so joining never
            # truncates below what waiting would deliver. A joiner gets
            # cap - slot tokens: 1 at the join + cap - 1 - slot decoded.
            solo_budget = min(
                req.max_tokens,
                self.max_seq_len - prompt_bucket(n_ids, self.max_seq_len),
            )
            fits = n_ids <= slot and cap - slot >= solo_budget
            if not fits:
                return defer(req, "capacity")
            if budget is not None and budget["left"] < n_ids:
                # over this step's prefill grant: next step
                return defer(req, "step_budget")
            # A join knows its pad exactly (prompt ends at the shared
            # slot), so the cached-prefix discount is exact here — and
            # cold prefix-cache pages reclaim on demand before the
            # free-page accounting refuses the join.
            avail = state["avail"]
            need = (
                self._pages_for(req, end_slot=slot)
                if avail is not None
                else 0
            )
            if avail is not None and need > avail and (
                self._prefix is not None
            ):
                avail = state["avail"] = avail + self._prefix.reclaim(
                    need - avail, rid=req.rid
                )
            if avail is None or need <= avail:
                if avail is not None:
                    state["avail"] = avail - need
                if budget is not None:
                    budget["left"] -= n_ids
                self.audit.record(
                    "join", "fair_order", rid=req.rid, tenant=req.tenant
                )
                return "take"
            return defer(req, "page_pressure")

        with self._cv:
            head = self._queue.oldest_head()
            if (
                head is not None
                and head.knobs() != knobs
                and not (head.deadline and now > head.deadline)
            ):
                # The epoch-bounding rule: the oldest queued request wants a
                # DIFFERENT trace — stop extending this epoch so it gets
                # its own, instead of waiting out other tenants' joins.
                self.audit.record(
                    "defer", "fairness_skip", rid=head.rid,
                    tenant=head.tenant, detail="epoch_bound",
                )
                return []
            taken = self._queue.take(len(free), accept)
            out = [(free[i], req) for i, req in enumerate(taken)]
            # Same no-gap rule as _admit: live the moment they leave the
            # queue, so cancel() always finds them somewhere.
            self._live_rids.update(req.rid for _, req in out)
        t_admit = time.perf_counter()
        for _, req in out:
            req.t_admit = t_admit  # join prefill is lane time, not queue
        return out

    def _join(self, req, lane, rows, slot, tok, kv, keys, ring_j, ring_idx_j, s):
        """Prefill one request into a free lane of the RUNNING epoch.

        The prompt is left-padded to end exactly at the epoch's shared slot;
        its KV row (computed in a fresh single-row cache) replaces the lane's
        row wholesale. The first token samples from the row's own fresh PRNG
        stream — identical to what a solo run would produce.
        """
        row = _RowState(
            req, set(self.config.eos_token_ids), self.tokenizer, lane=lane,
            engine=self,
        )
        # Open the lane-track span BEFORE the join prefill: the prefill IS
        # lane time (the /explain decomposition attributes it to the
        # joiner), and every failure path below still closes the span —
        # finish() on the page-truncated return, the except on a re-raise.
        row.open_span(slot=slot)
        t_join = time.perf_counter()
        try:
            return self._join_inner(
                req, row, lane, rows, slot, tok, kv, keys, ring_j,
                ring_idx_j, s, t_join,
            )
        except BaseException as e:
            # The caller retries (worker death) or strands the request —
            # either way THIS _RowState's span will never finish; close it
            # so the ring holds no orphan B for a lane that never served.
            row.close_span(error=str(e)[:200])
            raise

    def _join_inner(
        self, req, row, lane, rows, slot, tok, kv, keys, ring_j, ring_idx_j,
        s, t_join,
    ):
        from cake_tpu.models.llama.batch import first_sample, seed_rings

        ids = req.prompt_ids
        with timeline.span(
            "join", rid=req.rid, track="engine",
            args={"lane": lane, "slot": int(slot)},
        ):
            pad = slot - len(ids)
            if self._alloc is not None and self._prefix is not None:
                from cake_tpu.models.llama.paged_cache import PageExhausted

                # Prefix-cache join: fork the longest cached chain, map only
                # the tail, and prefill the window [start, slot) through the
                # SAME cached-chunk arithmetic as suffix_prefill — writes
                # below the fresh threshold drop, shared pages stay
                # byte-stable, and a warm join is bit-identical to a cold
                # one because hit and miss walk one arithmetic.
                try:
                    with timeline.span(
                        "prefix-fork", track="engine",
                        args={"lane": lane, "slot": int(slot)},
                    ):
                        fresh, pair = self._fork_lane(lane, req, pad, slot)
                except PageExhausted:
                    # _take_joins priced this join exactly, but the chain it
                    # was priced against can be reclaimed by an earlier
                    # joiner's own eviction before this fork runs — the same
                    # stale-estimate degradation as _prefix_layout: pool
                    # pressure costs this one stream, never the epoch.
                    self.stats["page_truncations"] += 1
                    req.handle.finish_reason = "length"
                    metrics.flight.record(
                        "page-truncated", req.rid, slot=int(slot),
                        where="join", completion_tokens=0,
                    )
                    if self._alloc.lane_mapped(lane):
                        self._lane_recycle(lane, insert=False)
                    else:
                        self._prefix.release(self._lane_leases.pop(lane, None))
                        self._lane_info.pop(lane, None)
                    row.finish()
                    self._pool_counter()
                    return tok, kv, keys, ring_j, ring_idx_j
                if pair is not None:
                    kv = self.backend.cow_copy(kv, [pair[0]], [pair[1]])
                W = min(-(-(slot - fresh) // 64) * 64, slot)
                start = slot - W
                row_tokens = np.zeros((1, W), np.int32)
                lo = max(pad, start)
                row_tokens[0, lo - start : slot - start] = ids[lo - pad :]
                logits, kv = self._dispatch(
                    "join",
                    lambda: self.backend.suffix_join(
                        kv, row_tokens, np.asarray([pad], np.int32),
                        np.asarray([fresh], np.int32), lane, start,
                    ),
                )
            else:
                # Window width bucketed to bound compiles; the prompt ends
                # at `slot`.
                W = min(-(-slot // 64) * 64, self.max_seq_len)
                row_tokens = np.zeros((1, W), np.int32)
                row_tokens[0, pad:slot] = ids
                if self._alloc is not None:
                    # Map the joiner's pages over its prompt window BEFORE
                    # the join prefill writes through them (_take_joins
                    # already charged the pool). The lane was released when
                    # its previous row finished.
                    self._alloc.map_range(lane, pad, slot)
                logits, kv = self._dispatch(
                    "join",
                    lambda: self.backend.join(
                        kv,
                        row_tokens,
                        jnp.asarray([pad], jnp.int32),
                        jnp.asarray([slot], jnp.int32),
                        lane,
                    ),
                )

            # Same first-token arithmetic as every entry point (batch.py).
            window = s.repeat_last_n
            row_ring, row_ring_idx = seed_rings([ids], window)
            key0 = jax.random.PRNGKey(req.sampling.seed)
            first_arr, key_next, row_ring, row_ring_idx = first_sample(
                logits, s, row_ring, row_ring_idx, key0[None]
            )
            first = int(first_arr[0])
        if window > 0:
            ring_j = ring_j.at[lane].set(jnp.asarray(row_ring[0]))
            ring_idx_j = ring_idx_j.at[lane].set(int(row_ring_idx[0]))
        keys = keys.at[lane].set(key_next[0])
        tok = tok.at[lane].set(first)

        dt_join = time.perf_counter() - t_join
        row.account_join(dt_join)
        # Hardware ledger: one lane x W window, the prompt's share is
        # useful prefill, the left-padding is pad.
        self.efficiency.note_prefill(dt_join, 1, W, min(len(ids), W))
        self._record_admissions([req], "joined", lane=lane, slot=slot)
        metrics.registry.counter(
            "cake_engine_joins_total",
            "Requests that joined a RUNNING epoch at a chunk boundary.",
        ).inc()
        row.push(first)
        rows[lane] = None if row.done else row
        self.stats["joins"] += 1
        self.stats["rows"] += 1
        return tok, kv, keys, ring_j, ring_idx_j


def _fail_request(
    req: _Request, error: str, engine: "BatchEngine | None" = None
) -> None:
    """Finish a never-admitted request gracefully as ``"error"`` (a joiner
    stranded by a worker failure): same taxonomy as admitted rows, without
    raising into the consumer."""
    req.handle.finish_reason = "error"
    metrics.registry.counter(
        "cake_stream_errors_total",
        "Streams finished with finish_reason=error after a worker failure.",
    ).inc()
    metrics.flight.record("stream-error", req.rid, error=error[:200])
    metrics.flight.record(
        "finished", req.rid, finish_reason="error", completion_tokens=0
    )
    if engine is not None:
        # SLO view (obs/slo.py): an error death with zero tokens — counts
        # against the tenant's error rate AND (no first token within any
        # bound) its TTFT objective, same as the _RowState.finish path.
        engine.slo.observe_finish(
            req.tenant, "error",
            had_deadline=bool(req.deadline), got_first_token=False,
        )
        engine._record_request(req, finish="error")
    req.handle._emit(_DONE)


@dataclasses.dataclass
class _SpilledLane:
    """Host-side record of a preempted lane (continuous scheduler): the
    full chunk-boundary state a bit-identical restore needs. ``row`` keeps
    history / budget / phase accounting; ``key``/``ring``/``ring_idx`` are
    the device sampling state copied out at the spill boundary. No device
    memory, no pages — a spilled lane costs a few KB of host RAM."""

    row: "_RowState"
    key: np.ndarray
    ring: np.ndarray | None
    ring_idx: int
    t: float = dataclasses.field(default_factory=time.perf_counter)


class _RowState:
    """Engine-side per-row bookkeeping: budget, EOS, incremental detok, events."""

    def __init__(
        self, req: _Request, eos: set[int], tokenizer: Tokenizer,
        lane: int = 0, engine: "BatchEngine | None" = None,
    ):
        self.req = req
        self._eos = eos
        self._engine = engine
        self._tokenizer = tokenizer
        self._ids: list[int] = []
        # Full prompt+output history, grown incrementally by push() — the
        # speculative drafter reads it every round, so rebuilding it by
        # concatenation there would be O(history) per round.
        self.history: list[int] = list(req.prompt_ids)
        self._decoded_len = 0
        self.n = 0
        self.done = False
        self._finished = False
        self._backpressured = False
        self.lane = lane
        self._span: int | None = None
        # Latency attribution (obs/critpath.py taxonomy): per-phase wall
        # seconds accumulated by the engine's dispatch accounting. The
        # convoy bucket is the lockstep tax — epoch work this row rode
        # along for but did not need.
        self.phase: dict[str, float] = {
            "prefill": 0.0, "decode": 0.0, "spec_accepted": 0.0,
            "spec_wasted": 0.0, "convoy": 0.0, "restore": 0.0,
        }
        self.t_open = 0.0
        self.t_close = 0.0
        self.ttft_s: float | None = None
        # Token count at the last restore (-1 = never restored): a lane
        # that self-spills again at the SAME count made zero progress —
        # its next chunk can never map on this pool, so re-parking would
        # livelock (the respill doom check in _spill_lane).
        self.n_at_restore = -1

    # ---- lane-track timeline span (admission -> finish) ------------------

    def open_span(self, slot: int | None) -> None:
        """Open this request's lane-track span: one Perfetto row per lane,
        occupied from admission (or join) until the stream finishes. The
        queue/admission stamps ride the B args so GET /explain can
        decompose submit-to-lane time without the flight recorder."""
        self.t_open = time.perf_counter()
        queue_wait = max(
            0.0, (self.req.t_admit or self.t_open) - self.req.t_submit
        )
        args: dict = {
            "prompt_tokens": len(self.req.prompt_ids),
            "queue_wait_s": round(queue_wait, 6),
            "admit_s": round(self.req.admit_s, 6),
        }
        if slot is not None:
            args["join_slot"] = int(slot)
        self._span = timeline.begin(
            "request", rid=self.req.rid, track=f"lane{self.lane}", args=args,
            parent=None,  # lane-track root: not a child of the epoch span
        )
        if self._engine is not None and self not in self._engine._epoch_rows:
            # Epoch convoy meter input: lane occupancy intervals (a
            # restored row re-opens its span in the same segment; one
            # entry keeps its occupancy from double-counting).
            self._engine._epoch_rows.append(self)

    def close_span(self, error: str | None = None) -> None:
        if self.t_close == 0.0:
            self.t_close = time.perf_counter()
        if self._span is None:
            return
        args: dict = {
            "finish_reason": self.req.handle.finish_reason,
            "completion_tokens": self.n,
        }
        if error is not None:
            args["error"] = error[:200]
        timeline.end(self._span, args=args)
        self._span = None

    # ---- dispatch-time attribution (engine thread) -----------------------

    def account_prefill(self, dt: float, bucket: int) -> None:
        """Epoch-start prefill: own share scales with the prompt's fraction
        of the shared left-padded bucket; the padding's compute is convoy."""
        share = min(1.0, len(self.req.prompt_ids) / max(1, bucket))
        self.phase["prefill"] += dt * share
        self.phase["convoy"] += dt * (1.0 - share)

    def account_join(self, dt: float) -> None:
        """A join prefill computes exactly this row's window: all own."""
        self.phase["prefill"] += dt

    def account_restore(self, dt: float, bucket: int) -> None:
        """A spill-seeded restore prefill: redone work the preemption
        cost this stream — its own phase (so /explain can price the
        preemption), the shared bucket's padding split like prefill."""
        share = min(1.0, (len(self.history) - 1) / max(1, bucket))
        self.phase["restore"] += dt * share
        self.phase["convoy"] += dt * (1.0 - share)

    def account_decode(self, dt: float, n: int, used: int) -> None:
        """One decode chunk: n tokens computed, ``used`` consumed; the
        unconsumed tail (EOS/budget mid-chunk) is convoy."""
        frac = min(1.0, used / max(1, n))
        self.phase["decode"] += dt * frac
        self.phase["convoy"] += dt * (1.0 - frac)

    def account_spec(self, dt: float, k: int, used: int) -> None:
        """One verify round: K+1 positions computed, ``used`` accepted into
        this row's stream; the rest (rejected drafts + co-batched shape)
        is the wasted half of the speculative split."""
        frac = min(1.0, used / (k + 1))
        self.phase["spec_accepted"] += dt * frac
        self.phase["spec_wasted"] += dt * (1.0 - frac)

    def peek_consumed(self, toks) -> int:
        """How many of ``toks`` push() will consume before this row
        finishes — mirrors push()'s termination exactly (EOS token, or
        the budget filling on a non-EOS append), so dispatch accounting
        can run BEFORE the pushes that may finish the row."""
        if self.done:
            return 0
        used = 0
        n = self.n
        for t in toks:
            used += 1
            if int(t) in self._eos:
                break
            n += 1
            if n >= self.req.max_tokens:
                break
        return used

    def push(self, tid: int) -> None:
        """Accept one decoded id; emits a Token event unless already done.

        The moment a row is done (EOS or budget) its stream is CLOSED — the
        consumer unblocks immediately even though the row's lockstep lane keeps
        computing until the whole batch drains.
        """
        if self.done:
            return
        self._ids.append(tid)
        self.history.append(tid)
        self.n += 1
        now = time.perf_counter()
        if self.n == 1:
            ttft = now - self.req.t_submit
            self.ttft_s = ttft
            metrics.registry.histogram(
                "cake_ttft_seconds",
                "Submit-to-first-token latency (queue wait + prefill).",
            ).observe(ttft)
            metrics.flight.record(
                "first-token", self.req.rid, ttft_s=round(ttft, 6)
            )
            timeline.instant(
                "first-token", rid=self.req.rid, track=f"lane{self.lane}",
                args={"ttft_s": round(ttft, 6)},
            )
            if self._engine is not None:
                # Per-tenant TTFT SLI (obs/slo.py): the burn-rate input
                # for the declared --slo-ttft-ms objective. The rolling
                # time-series (obs/timeseries.py) takes the same sample
                # for the /timeseries p50/p99 window points.
                self._engine.slo.observe_ttft(self.req.tenant, ttft)
                self._engine.timeseries.observe_ttft(ttft)
        else:
            metrics.registry.histogram(
                "cake_inter_token_seconds",
                "Wall-clock gap between consecutive tokens of one stream.",
            ).observe(now - self.req.t_last_token)
        self.req.t_last_token = now
        if self._engine is not None:
            # Window tok/s (obs/timeseries.py): one tally per emitted
            # token — same cost class as the inter-token histogram above.
            self._engine.timeseries.observe_tokens()
        # Streaming backpressure watermark: a consumer that stopped
        # draining the handle gets the stream cancelled (next chunk
        # boundary) instead of an unbounded buffer. Checked before this
        # token's emit so the flagged stream still delivers it.
        eng = self._engine
        if (
            eng is not None
            and eng.stream_buffer_tokens
            and not self._backpressured
            and self.req.handle.buffered() >= eng.stream_buffer_tokens
        ):
            self._backpressured = True
            eng._shed_backpressure(self)
        is_eos = tid in self._eos
        if is_eos:
            self.req.handle.finish_reason = "stop"
            self.done = True
            text = ""
        else:
            text = self._delta()
        self.req.handle.completion_tokens = self.n
        self.req.handle._emit(Token(id=tid, text=text, is_end_of_stream=is_eos))
        if not is_eos and self.n >= self.req.max_tokens:
            self.req.handle.finish_reason = "length"
            self.done = True
        if self.done:
            self.finish()

    def _delta(self) -> str:
        delta, self._decoded_len = decode_delta(
            self._tokenizer, self._ids, self._decoded_len
        )
        return delta

    def fail(self, error: str) -> None:
        """Worker-failure isolation: finish this stream with
        ``finish_reason="error"`` — the consumer sees a clean end-of-stream
        with the error reason, NOT a raised exception (the tokens already
        delivered were bit-identical to a fault-free run's prefix)."""
        if self._finished:
            return
        self.done = True
        self.req.handle.finish_reason = "error"
        if self._engine is not None:
            self._engine.stats["stream_errors"] += 1
        metrics.registry.counter(
            "cake_stream_errors_total",
            "Streams finished with finish_reason=error after a worker "
            "failure.",
        ).inc()
        metrics.flight.record(
            "stream-error", self.req.rid,
            error=error[:200], completion_tokens=self.n,
        )
        timeline.instant(
            "stream-error", rid=self.req.rid, track=f"lane{self.lane}",
        )
        self.close_span(error=error)
        self.finish()

    def cancel(self) -> None:
        """Mid-epoch cancellation (engine.cancel): clean finish with
        ``finish_reason="cancelled"``; the lane and its pages recycle at
        this chunk boundary."""
        if self._finished:
            return
        self.done = True
        self.req.handle.finish_reason = "cancelled"
        timeline.instant(
            "cancelled", rid=self.req.rid, track=f"lane{self.lane}",
        )
        self.finish()

    def expire(self) -> None:
        """End-to-end deadline passed mid-decode (engine._apply_deadlines):
        clean finish with ``finish_reason="deadline"`` at this chunk
        boundary — the tokens already streamed stand, the lane and its
        pages recycle, and the consumer learns the SLO verdict instead of
        a silently late completion."""
        if self._finished:
            return
        self.done = True
        self.req.handle.finish_reason = "deadline"
        metrics.registry.counter(
            "cake_deadline_expired_total",
            "Requests past their end-to-end deadline (where=queued expired "
            "before admission; where=running at a chunk boundary).",
        ).inc(where="running")
        metrics.flight.record(
            "deadline-expired", self.req.rid, where="running",
            completion_tokens=self.n,
        )
        timeline.instant(
            "deadline-expired", rid=self.req.rid, track=f"lane{self.lane}",
        )
        self.finish()

    def finish(self) -> None:
        if self._finished:
            return
        self._finished = True
        metrics.registry.counter(
            "cake_engine_completed_total", "Streams closed (any finish reason)."
        ).inc()
        metrics.flight.record(
            "finished", self.req.rid,
            finish_reason=self.req.handle.finish_reason,
            completion_tokens=self.n,
        )
        self.close_span()
        if self._engine is not None:
            # Per-tenant SLO SLIs (obs/slo.py): deadline hit/miss, error
            # and goodput accounting — a zero-token deadline/error finish
            # also counts as a TTFT miss (no first token within any bound).
            self._engine.slo.observe_finish(
                self.req.tenant, self.req.handle.finish_reason,
                tokens=self.n,
                had_deadline=bool(self.req.deadline),
                got_first_token=self.n > 0,
            )
            # Goodput ledger (obs/efficiency.py): class every emitted
            # token next to the SLO tracker's per-tenant goodput SLI —
            # same finish event, so the two views always agree.
            self._engine.efficiency.note_finish(
                self.req.tenant, self.req.handle.finish_reason, self.n
            )
            # Latency attribution: fold the row's measured phases into the
            # aggregate histograms and run the blackbox triggers.
            self._engine._observe_request(self)
            # Traffic observatory: the canonical completion record
            # (obs/requestlog.py) — same finish event as the SLO/goodput
            # observations above, so all three views always agree.
            self._engine._record_request(self.req, row=self)
        self.req.handle._emit(_DONE)
        if self._engine is not None:
            self._engine._row_finished(self.req.rid)
