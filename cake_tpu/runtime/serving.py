"""Concurrent batched serving: a request queue feeding lockstep batch decode.

The reference serializes API requests behind a global write lock (api/mod.rs:76)
— SURVEY.md §2.6 calls that a quirk, not a contract. This module replaces the
lock with a scheduler: HTTP handler threads ``submit()`` requests into a queue;
one engine thread drains it, groups requests whose sampling knobs compile to
the same fused-decode trace, left-pads the group into ONE batch (the
models/llama/batch.py layout), and decodes all rows in lockstep — streaming
each row's tokens to its own consumer as every chunk lands.

Per-request correctness is exact, not approximate:
  * Every row carries its OWN PRNG key (ops/sampling.sample_per_row), split
    per step exactly like LlamaGenerator's host loop — so row r's token stream
    is bit-identical to a single-request run with row r's seed, regardless of
    what else happens to share the batch. Tests pin this oracle.
  * Per-row repeat-penalty rings, budgets (max_tokens), and EOS: a finished
    row's lockstep lane computes discarded garbage until the batch drains
    (bounded by the chunk size times remaining rows' budgets).
  * Requests whose knobs differ (temperature/top-k/top-p/penalty — compiled
    into the trace) are NOT merged; they run as separate consecutive batches.

Decode FLOPs grow ~linearly with rows while weight HBM traffic stays constant,
so on TPU a batch of B requests streams at nearly the single-request rate for
each of them — aggregate throughput scales until the MXU saturates.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from collections import deque
from typing import Iterator

import jax
import jax.numpy as jnp

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.batch import lockstep_decode, prompt_bucket
from cake_tpu.models.llama.chat import Message, encode_dialog_to_prompt
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import SamplingConfig, Token, decode_delta
from cake_tpu.models.llama.tokenizer import Tokenizer

log = logging.getLogger("cake_tpu.serving")

_DONE = "__done__"


@dataclasses.dataclass
class _Request:
    prompt_ids: list[int]
    max_tokens: int
    sampling: SamplingConfig
    handle: "StreamHandle"

    def knobs(self) -> tuple:
        # Trace compatibility = batch compatibility (SamplingConfig.trace_knobs).
        return self.sampling.trace_knobs()


class StreamHandle:
    """Consumer side of one submitted request.

    ``tokens()`` yields Token objects as the engine produces them and returns
    once the stream finishes; ``text()`` blocks to completion. An engine-side
    failure re-raises here.
    """

    def __init__(self, n_prompt: int):
        self.prompt_tokens = n_prompt
        self.completion_tokens = 0
        self.finish_reason: str = "length"
        self._events: deque = deque()
        self._cv = threading.Condition()

    # -- engine side -------------------------------------------------------
    def _emit(self, item) -> None:
        with self._cv:
            self._events.append(item)
            self._cv.notify()

    # -- consumer side -----------------------------------------------------
    def tokens(self) -> Iterator[Token]:
        while True:
            with self._cv:
                while not self._events:
                    self._cv.wait()
                item = self._events.popleft()
            if item is _DONE:
                return
            if isinstance(item, Exception):
                raise item
            yield item

    def text(self) -> str:
        return "".join(t.text for t in self.tokens())


class BatchEngine:
    """One device-owning thread serving many concurrent requests.

    Single-process, local params (the batch layout needs direct cache access);
    distributed backends keep the serialized generator path.
    """

    def __init__(
        self,
        config: LlamaConfig,
        params: M.Params,
        tokenizer: Tokenizer,
        *,
        max_seq_len: int | None = None,
        cache_dtype: jnp.dtype = jnp.bfloat16,
        decode_chunk_size: int = 8,
        max_batch: int = 8,
        admission_window: float = 0.01,
    ):
        self.config = config
        self.params = params
        self.tokenizer = tokenizer
        self.max_seq_len = int(max_seq_len or config.max_position_embeddings)
        self.cache_dtype = cache_dtype
        self.decode_chunk_size = max(1, decode_chunk_size)
        self.max_batch = max(1, max_batch)
        self.admission_window = admission_window
        self._queue: deque[_Request] = deque()
        self._cv = threading.Condition()
        self._stop = False
        self._thread: threading.Thread | None = None
        # Observability (also lets tests assert real batching happened).
        self.stats = {"batches": 0, "rows": 0, "max_rows": 0}

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="batch-engine", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    # ------------------------------------------------------------ submission

    def submit(
        self,
        messages: list[Message],
        max_tokens: int,
        sampling: SamplingConfig,
    ) -> StreamHandle:
        """Queue one chat completion; returns immediately with its stream.

        Raises ValueError for over-length prompts (the server maps it to 400
        BEFORE any streaming headers go out).
        """
        ids = self.tokenizer.encode(encode_dialog_to_prompt(messages))
        # Left-pad bucket rounding can add slots ahead of the prompt; require
        # room for the bucket plus at least one generated token. Same helper
        # as the actual layout (models/llama/batch.py) so they cannot drift.
        bucket_ceiling = prompt_bucket(len(ids), self.max_seq_len)
        if bucket_ceiling >= self.max_seq_len:
            raise ValueError(
                f"prompt is {len(ids)} tokens but the context window "
                f"is {self.max_seq_len}"
            )
        handle = StreamHandle(n_prompt=len(ids))
        req = _Request(ids, max_tokens, sampling, handle)
        with self._cv:
            if self._stop:
                raise RuntimeError("engine is stopped")
            self._queue.append(req)
            self._cv.notify_all()
        return handle

    # ------------------------------------------------------------ scheduler

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._stop:
                    self._cv.wait()
                if self._stop:
                    for r in self._queue:
                        r.handle._emit(RuntimeError("engine stopped"))
                    self._queue.clear()
                    return
            # Admission window: let a burst of concurrent submissions land so
            # they batch together instead of trickling into 1-row batches.
            if self.admission_window > 0:
                time.sleep(self.admission_window)
            batch = self._admit()
            if not batch:
                continue
            self.stats["batches"] += 1
            self.stats["rows"] += len(batch)
            self.stats["max_rows"] = max(self.stats["max_rows"], len(batch))
            try:
                self._run_batch(batch)
            except Exception as e:  # noqa: BLE001 — surface to every consumer
                log.exception("batch failed")
                for r in batch:
                    r.handle._emit(e)
                    r.handle._emit(_DONE)

    def _admit(self) -> list[_Request]:
        """Take the head-of-line request plus every queued request with the
        same sampling knobs (in order), up to max_batch. Others stay queued."""
        with self._cv:
            if not self._queue:
                return []
            first = self._queue.popleft()
            group = [first]
            rest: deque[_Request] = deque()
            while self._queue and len(group) < self.max_batch:
                r = self._queue.popleft()
                if r.knobs() == first.knobs():
                    group.append(r)
                else:
                    rest.append(r)
            rest.extend(self._queue)
            self._queue = rest
            return group

    # ------------------------------------------------------------ execution

    def _run_batch(self, batch: list[_Request]) -> None:
        s = batch[0].sampling
        ids_list = [r.prompt_ids for r in batch]
        eos = set(self.config.eos_token_ids)
        # max_tokens is additionally clamped by the cache edge the driver
        # enforces; rows report finish_reason="length" either way.
        rows = [_RowState(r, eos, self.tokenizer) for r in batch]
        # Per-row PRNG keys: the reproducibility contract (module docstring).
        keys = jnp.stack([jax.random.PRNGKey(r.sampling.seed) for r in batch])

        def on_tokens(toks) -> bool:
            for row, row_toks in zip(rows, toks):
                for t in row_toks:
                    if row.done:
                        break
                    row.push(int(t))
            return not all(r.done for r in rows)

        lockstep_decode(
            self.config,
            self.params,
            ids_list,
            s,
            max_seq_len=self.max_seq_len,
            cache_dtype=self.cache_dtype,
            decode_chunk_size=self.decode_chunk_size,
            on_tokens=on_tokens,
            row_keys=keys,
        )
        for row in rows:
            row.finish()  # idempotent; closes cache-edge-truncated rows


class _RowState:
    """Engine-side per-row bookkeeping: budget, EOS, incremental detok, events."""

    def __init__(self, req: _Request, eos: set[int], tokenizer: Tokenizer):
        self.req = req
        self._eos = eos
        self._tokenizer = tokenizer
        self._ids: list[int] = []
        self._decoded_len = 0
        self.n = 0
        self.done = False
        self._finished = False

    def push(self, tid: int) -> None:
        """Accept one decoded id; emits a Token event unless already done.

        The moment a row is done (EOS or budget) its stream is CLOSED — the
        consumer unblocks immediately even though the row's lockstep lane keeps
        computing until the whole batch drains.
        """
        if self.done:
            return
        self._ids.append(tid)
        self.n += 1
        is_eos = tid in self._eos
        if is_eos:
            self.req.handle.finish_reason = "stop"
            self.done = True
            text = ""
        else:
            text = self._delta()
        self.req.handle.completion_tokens = self.n
        self.req.handle._emit(Token(id=tid, text=text, is_end_of_stream=is_eos))
        if not is_eos and self.n >= self.req.max_tokens:
            self.req.handle.finish_reason = "length"
            self.done = True
        if self.done:
            self.finish()

    def _delta(self) -> str:
        delta, self._decoded_len = decode_delta(
            self._tokenizer, self._ids, self._decoded_len
        )
        return delta

    def finish(self) -> None:
        if self._finished:
            return
        self._finished = True
        self.req.handle._emit(_DONE)
