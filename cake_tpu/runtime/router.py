"""Health-driven replica routing for the TCP deployment.

The topology may declare N workers over the SAME layer range (replica
groups, parallel/topology.py ``replica_groups``); this module decides which
member serves each group for a given epoch. The policy is deliberately
small and fully observable:

  * **round-robin among healthy** — ``refresh()`` (called at epoch start:
    ``DistributedBatchBackend.init_kv`` / ``DistributedForwardStep.reset``)
    advances each group's cursor to the next healthy member, so epochs
    spread across replicas. A route is STABLE within an epoch: the epoch's
    replay session (sid/seq) lives on the routed worker, so mid-epoch
    re-routing without KV migration would be wrong — migration is the
    engine's job (runtime/serving.py failover).
  * **eject on failure** — ``report_failure``/``failover`` remove a member
    from rotation after the wire retry budget was exhausted on it.
    ``failover(node)`` additionally re-picks the group's route NOW and
    returns the replacement (None when no healthy member remains — the
    caller falls back to PR 6's ``finish_reason="error"`` isolation).
  * **standby rejoin** — an ejected member becomes eligible again once its
    ``cooldown_s`` probation has elapsed AND the heartbeat monitor (when
    attached) reports it healthy; the first pick after re-eligibility is a
    ``rejoin`` event. Without a monitor the cooldown alone governs: the
    next pick is a live probe, and a failure re-ejects.

Health is the union of two signals: the ejection ledger (hop outcomes) and
the attached ``HeartbeatMonitor`` (proactive PING liveness,
``cake_worker_healthy``) — a member the monitor marks down is skipped even
if it never failed a hop.

Observability: ``cake_replica_routed_total{node}`` per routed pick,
``cake_failover_total{node}`` per ejection-with-reroute, ``failover`` /
``rejoin`` flight events, and timeline instants on a ``router`` track.
"""

from __future__ import annotations

import logging
import threading
import time

from cake_tpu.obs.timeline import timeline
from cake_tpu.utils import metrics

log = logging.getLogger("cake_tpu.router")


class ReplicaRouter:
    """Per-epoch route selection over replica groups.

    ``groups`` maps each stage-plan primary to the ordered member list
    (primary first — ``Topology.replica_groups``). Single-member groups are
    legal and routed trivially, so every deployment runs through one code
    path. Thread-safe: the engine thread refreshes/fails-over while the
    serialized path and heartbeat threads may query concurrently.
    """

    def __init__(
        self,
        groups: dict[str, list[str]],
        *,
        monitor=None,
        cooldown_s: float = 5.0,
    ):
        self.groups = {p: list(members) for p, members in groups.items()}
        for primary, members in self.groups.items():
            if primary not in members:
                raise ValueError(
                    f"replica group for {primary!r} must contain it: {members}"
                )
        self.monitor = monitor
        self.cooldown_s = cooldown_s
        self._lock = threading.Lock()
        self._rr = {p: 0 for p in self.groups}  # next-pick cursor per group
        self._routes = {p: members[0] for p, members in self.groups.items()}
        self._ejected: dict[str, float] = {}  # node -> monotonic eject time

    def attach_monitor(self, monitor) -> None:
        """Late-bind the heartbeat monitor (the engine builds it at start())."""
        self.monitor = monitor

    # ------------------------------------------------------------- health

    def healthy(self, node: str) -> bool:
        """Routable NOW: not under ejection probation, and not marked down
        by the heartbeat monitor."""
        with self._lock:
            ok, rejoined = self._healthy_locked(node)
        if rejoined:
            self._record_rejoin(node)
        return ok

    def _healthy_locked(self, node: str) -> tuple[bool, bool]:
        """(healthy, rejoined): clears an expired ejection as a side effect
        so the caller can emit the rejoin event outside the lock."""
        if self.monitor is not None and not self.monitor.healthy(node):
            return False, False
        t0 = self._ejected.get(node)
        if t0 is None:
            return True, False
        if time.monotonic() - t0 < self.cooldown_s:
            return False, False
        # Probation served (and the monitor, when present, says alive):
        # the standby rejoins the rotation. A failed probe re-ejects.
        del self._ejected[node]
        return True, True

    # ------------------------------------------------------------- routing

    def route(self, primary: str) -> str:
        """The current epoch's member for ``primary`` (stable until the next
        ``refresh``/``failover``). Unknown primaries route to themselves —
        a master-local stage never reaches here, but the identity keeps the
        call total."""
        with self._lock:
            return self._routes.get(primary, primary)

    def refresh(self) -> dict[str, str]:
        """Epoch start: advance each group's round-robin cursor to the next
        healthy member and return the full route map. Groups with no healthy
        member keep their previous route (the hop will fail fast and the
        failure path decides)."""
        rejoins: list[str] = []
        with self._lock:
            for primary, members in self.groups.items():
                pick = self._pick_locked(primary, members, rejoins)
                if pick is not None:
                    self._routes[primary] = pick
            routes = dict(self._routes)
        for node in rejoins:
            self._record_rejoin(node)
        for node in routes.values():
            metrics.registry.counter(
                "cake_replica_routed_total",
                "Epoch routes handed out per worker by the replica router.",
            ).inc(node=node)
        return routes

    def _pick_locked(
        self, primary: str, members: list[str], rejoins: list[str]
    ) -> str | None:
        start = self._rr[primary]
        for i in range(len(members)):
            node = members[(start + i) % len(members)]
            ok, rejoined = self._healthy_locked(node)
            if rejoined:
                rejoins.append(node)
            if ok:
                # Callers hold self._lock (the _locked suffix contract).
                # cake-lint: disable-next-line=unlocked-shared-mutation
                self._rr[primary] = (start + i + 1) % len(members)
                return node
        return None

    def prefer(self, node: str) -> None:
        """Pin the NEXT ``refresh`` pick of ``node``'s group to ``node``
        (subject to health) — an operational hook for draining a peer or
        rehearsing a failover deterministically (chaos tests use it to know
        which member the epoch under test will route)."""
        with self._lock:
            for primary, members in self.groups.items():
                if node in members:
                    self._rr[primary] = members.index(node)

    # ------------------------------------------------------------- failures

    def report_failure(self, node: str) -> None:
        """Eject a member after a hop exhausted its retry budget on it: it
        leaves the rotation until its cooldown (and heartbeat, when
        monitored) readmits it."""
        with self._lock:
            self._ejected[node] = time.monotonic()
        log.warning("replica %s ejected from rotation", node)

    def report_success(self, node: str) -> None:
        """A hop completed on ``node``: clear any probation early (the node
        is demonstrably serving again)."""
        with self._lock:
            rejoined = self._ejected.pop(node, None) is not None
        if rejoined:
            self._record_rejoin(node)

    def failover(self, node: str) -> str | None:
        """Eject ``node`` and re-route every group it currently serves.

        Returns the replacement for ``node``'s own group — None when no
        healthy member remains (the caller degrades to error isolation).
        The replacement is recorded as a ``failover`` flight event + the
        ``cake_failover_total{node}`` counter keyed by the FAILED node.
        """
        self.report_failure(node)
        replacement: str | None = None
        rejoins: list[str] = []
        with self._lock:
            for primary, members in self.groups.items():
                if node not in members:
                    continue
                pick = self._pick_locked(primary, members, rejoins)
                if pick is not None:
                    self._routes[primary] = pick
                    replacement = pick
        for n in rejoins:
            self._record_rejoin(n)
        if replacement is None or replacement == node:
            return None
        metrics.registry.counter(
            "cake_failover_total",
            "Failovers away from a worker (labelled by the FAILED node).",
        ).inc(node=node)
        metrics.flight.record("failover", node=node, to=replacement)
        timeline.instant(
            "failover", track="router",
            args={"from": node, "to": replacement},
        )
        log.warning("failover: %s -> %s", node, replacement)
        return replacement

    # -------------------------------------------------------- observability

    def _record_rejoin(self, node: str) -> None:
        metrics.registry.counter(
            "cake_replica_rejoin_total",
            "Ejected replicas readmitted to rotation (standby rejoin).",
        ).inc(node=node)
        metrics.flight.record("rejoin", node=node)
        timeline.instant("rejoin", track="router", args={"node": node})
        log.info("replica %s rejoined the rotation", node)

    def snapshot(self) -> dict:
        """Routing state for /stats-style surfaces and tests."""
        with self._lock:
            return {
                "routes": dict(self._routes),
                "ejected": sorted(self._ejected),
                "groups": {p: list(m) for p, m in self.groups.items()},
            }
