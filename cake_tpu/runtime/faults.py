"""Deterministic fault injection for the distributed serving stack.

The source system has no failure story at all (SURVEY §5: no reconnect, no
retry — one worker hiccup kills the run). This module is the other half of
fixing that: the recovery machinery (client retry/replay, worker sessions,
engine failure isolation — runtime/{client,worker,serving}.py) is only
trustworthy if failures can be *produced on demand, deterministically*. A
``FaultPlan`` is a seeded list of fault specs; production code calls
``faults.check(site, node=...)`` at a handful of named checkpoints and acts
on whatever spec fires. No plan installed = one ``is None`` test per
checkpoint, so the hooks are free in production.

Checkpoint sites (grep for ``faults.check`` to audit):

  ``client.send``    before a FORWARD frame leaves StageClient.forward
                     (kinds: drop / delay / truncate / kill — kill tears
                     the client socket down pre-send; with ``count=0`` +
                     ``node=`` the worker is unreachable for good, the
                     deterministic driver of the replica-failover path)
  ``client.recv``    before the reply read (kind: delay)
  ``worker.op``      a worker op about to execute (kinds: stall / kill =
                     tear down the connection mid-op, session survives /
                     crash = tear down AND drop all session state — a
                     process death, replay impossible)
  ``worker.reply``   a computed reply about to be sent (drop / truncate —
                     the op applied but the reply is lost: the idempotent-
                     replay case)
  ``worker.ping``    a PING about to be answered (kind: stall — what a
                     wedged worker looks like to the heartbeat monitor)
  ``backend.prefill`` / ``backend.decode`` / ``backend.join`` /
  ``backend.verify``  an engine-side backend op about to dispatch (kinds:
                     stall / crash = raise BackendWorkerError — worker
                     death as the engine sees it, on any backend; a
                     ``stall`` here is ALSO what the stuck-epoch watchdog
                     converts to error isolation within ``epoch_stall_s``
                     — runtime/admission.StallGuard; verify covers the
                     batched speculative verify round)
  ``api.stream``     an SSE chunk about to be written (kind: stall — a
                     consumer that stopped reading)

Every fired fault is observable three ways: the
``cake_faults_injected_total{kind,site}`` counter, a ``fault-injected``
flight-recorder event, and a timeline instant on the ``faults`` track — so a
chaos run is replayable in Perfetto next to the spans it perturbed.

Plans come from three places, same grammar everywhere:

  * programmatic (tests): ``faults.install(FaultPlan([FaultSpec(...)], seed=7))``
  * the CLI: ``--faults 'kill@worker.op:after=5'``
  * the environment: ``CAKE_FAULTS='seed=7;drop@client.send:p=0.1;...'``

DSL: ``;``-separated entries; ``seed=N`` sets the plan seed; every other
entry is ``kind@site[:key=value]*`` with keys ``node`` (fnmatch pattern,
default any), ``after`` (skip the first N matching checkpoints), ``count``
(fire at most N times; 0 = unlimited), ``p`` (per-checkpoint probability,
decided by the plan's seeded RNG), ``delay_s`` (sleep for delay/stall),
``frac`` (fraction of the frame kept by truncate). Determinism: with
``p=1`` a plan is a pure function of the checkpoint order; with ``p<1`` it
is a pure function of checkpoint order + seed.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import logging
import os
import random
import threading
import time

log = logging.getLogger("cake_tpu.faults")

KINDS = ("drop", "delay", "truncate", "kill", "crash", "stall")


@dataclasses.dataclass
class FaultSpec:
    """One injectable fault: what (kind), where (site/node), when (after/
    count/p), and how hard (delay_s/frac)."""

    kind: str
    site: str                 # fnmatch pattern over checkpoint site labels
    node: str | None = None   # fnmatch pattern over node names; None = any
    after: int = 0            # skip the first `after` matching checkpoints
    count: int = 1            # fire at most `count` times; 0 = unlimited
    p: float = 1.0            # per-checkpoint probability (seeded RNG)
    delay_s: float = 0.05     # sleep length for delay/stall
    frac: float = 0.5         # fraction of the encoded frame truncate keeps
    seen: int = 0             # matching checkpoints observed (mutated)
    fired: int = 0            # times this spec actually fired (mutated)

    def __post_init__(self) -> None:
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r} (have {KINDS})"
            )
        if not self.site:
            raise ValueError("fault site pattern must be non-empty")

    def matches(self, site: str, node: str | None) -> bool:
        if not fnmatch.fnmatchcase(site, self.site):
            return False
        if self.node is not None:
            return fnmatch.fnmatchcase(node or "", self.node)
        return True

    def describe(self) -> str:
        where = f"{self.site}" + (f":node={self.node}" if self.node else "")
        return f"{self.kind}@{where}"


class FaultPlan:
    """A seeded, ordered set of fault specs consulted at checkpoints.

    Thread-safe: checkpoints fire from engine/worker/handler threads; the
    lock serializes the seen/fired bookkeeping and the RNG draw so the
    decision sequence is reproducible for a given checkpoint order.
    """

    def __init__(self, specs: list[FaultSpec], seed: int = 0):
        self.specs = list(specs)
        self.seed = seed
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def check(self, site: str, node: str | None = None) -> FaultSpec | None:
        """Return the first spec that fires at this checkpoint, else None.

        A spec consumes one "seen" tick per matching checkpoint whether or
        not it fires, so ``after=N`` means "the N+1th matching event".
        """
        with self._lock:
            for spec in self.specs:
                if not spec.matches(site, node):
                    continue
                spec.seen += 1
                if spec.seen <= spec.after:
                    continue
                if spec.count and spec.fired >= spec.count:
                    continue
                if spec.p < 1.0 and self._rng.random() >= spec.p:
                    continue
                spec.fired += 1
                self._record(spec, site, node)
                return spec
        return None

    @staticmethod
    def _record(spec: FaultSpec, site: str, node: str | None) -> None:
        """Every injected fault is a first-class observable event."""
        from cake_tpu.obs.timeline import timeline
        from cake_tpu.utils import metrics

        log.warning(
            "fault injected: %s at %s (node=%s, fired %d)",
            spec.kind, site, node, spec.fired,
        )
        metrics.registry.counter(
            "cake_faults_injected_total",
            "Faults fired by the active fault plan (runtime/faults.py).",
        ).inc(kind=spec.kind, site=site)
        metrics.flight.record(
            "fault-injected", kind=spec.kind, site=site,
            node=node or "", spec=spec.describe(),
        )
        timeline.instant(
            "fault", track="faults",
            args={"kind": spec.kind, "site": site, "node": node or ""},
        )


def parse(text: str) -> FaultPlan:
    """Parse the compact plan DSL (module docstring). Raises ValueError on
    malformed input — a chaos run with a typo'd plan must fail loudly, not
    run fault-free and "pass"."""
    specs: list[FaultSpec] = []
    seed = 0
    for raw in text.split(";"):
        entry = raw.strip()
        if not entry:
            continue
        if entry.startswith("seed="):
            seed = int(entry[len("seed="):])
            continue
        if "@" not in entry:
            raise ValueError(
                f"fault entry {entry!r} is not kind@site[:key=value]*"
            )
        kind, rest = entry.split("@", 1)
        parts = rest.split(":")
        site, kvs = parts[0], parts[1:]
        kw: dict[str, object] = {}
        for kv in kvs:
            if "=" not in kv:
                raise ValueError(f"fault option {kv!r} is not key=value")
            k, v = kv.split("=", 1)
            if k == "node":
                kw[k] = v
            elif k in ("after", "count"):
                kw[k] = int(v)
            elif k in ("p", "delay_s", "frac"):
                kw[k] = float(v)
            else:
                raise ValueError(f"unknown fault option {k!r} in {entry!r}")
        specs.append(FaultSpec(kind=kind.strip(), site=site.strip(), **kw))
    return FaultPlan(specs, seed=seed)


# ---------------------------------------------------------------- module API
#
# One process-global active plan. ``check`` is the hot-path entry: a single
# attribute test when no plan is installed.

_active: FaultPlan | None = None


def install(plan: FaultPlan | None) -> None:
    """Install (or clear, with None) the process-global fault plan."""
    global _active
    _active = plan


def clear() -> None:
    install(None)


def active() -> FaultPlan | None:
    return _active


def check(site: str, node: str | None = None) -> FaultSpec | None:
    """Consult the active plan at a checkpoint; None when no plan or no hit."""
    plan = _active
    if plan is None:
        return None
    return plan.check(site, node)


def sleep(spec: FaultSpec) -> None:
    """The delay/stall action (a helper so call sites stay one line)."""
    time.sleep(spec.delay_s)


def install_from_env() -> FaultPlan | None:
    """Install a plan from ``CAKE_FAULTS`` if set; returns it. Called once at
    import so `CAKE_FAULTS='...' cake-tpu --api ...` needs no code change."""
    text = os.environ.get("CAKE_FAULTS")
    if not text:
        return None
    plan = parse(text)
    install(plan)
    log.warning(
        "CAKE_FAULTS active: %d spec(s), seed=%d — this process will "
        "deliberately misbehave", len(plan.specs), plan.seed,
    )
    return plan


install_from_env()
