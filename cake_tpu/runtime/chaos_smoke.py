"""Chaos smoke gate: kill workers mid-serve, check the failure semantics.

``make chaos-smoke`` (wired into ``make verify`` after trace-smoke) runs
seeded fault plans against REAL loopback TCP clusters on the CPU backend
with tiny random weights. Two scenarios gate:

**Isolation** (no replica — PR 6): two concurrent streams through the
BatchEngine over DistributedBatchBackend, the single worker crashing
(session state dropped + connection torn) mid-decode. Exits nonzero unless:

  * the short co-batched stream finished BEFORE the crash, bit-identical to
    a fault-free oracle run,
  * the long stream finished with ``finish_reason="error"`` — a clean
    degradation, not a raised exception or a hang,
  * the engine survived: a follow-up request completes normally,
  * the fault and the hop failure are observable (counters + flight events).

**Failover** (replica present — PR 7): the same workload over a two-member
replica group, the primary made unreachable mid-decode
(``kill@client.send``). Exits nonzero unless EVERY stream finishes
``stop``/``length`` bit-identically to the fault-free run (the live
streams migrate to the standby), zero streams finish ``"error"``, and
``cake_failover_total`` moved.

**Shared prefix** (prefix cache — PR 8): two streams sharing a system
prompt served twice through a paged local engine with ``prefix_cache=True``
(runtime/prefix_cache.py), then a seeded crash mid-decode while the warm
streams hold FORKED shared pages. Exits nonzero unless the warm (cache-hit)
streams are bit-identical to the cold run, the hit counters moved, the
crash degrades cleanly (``"error"`` + cache cleared, a follow-up cold
request still bit-identical), and the pool drains to fully free after
``clear()``.

**Overload storm** (admission SLOs — ISSUE 11): one abusive tenant floods
a paged engine past its token-bucket quota while a compliant tenant
submits a single request, run A/B with the deficit-weighted fair queue ON
and OFF (runtime/admission.py). Exits nonzero unless with fairness ON the
compliant stream finishes among the first few (bounded factor of its
isolated latency, clean finish, bit-identical), the flood's overflow is
refused with consistent Retry-After hints (the HTTP 429 path), a
deadline-doomed request expires without a token or a page, and the pool
drains to fully free — AND with fairness OFF the very same storm
demonstrably starves the compliant stream to the back of the flood (the
A/B is the proof the fair queue earns its complexity).

**Continuous preemption** (ISSUE 15): two long streams through a paged
CONTINUOUS-scheduler engine whose pool cannot hold both — one lane spills
host-side — then the same run with a seeded backend death landing WHILE
the lane sits spilled (``failover_local`` migrates the live stream; the
restore walks the recovered backend). Exits nonzero unless both streams
are bit-identical to the fault-free run (zero ``"error"`` finishes), the
flight tail reads preempted → failover → restored, the pool drains, and
no spilled chain leaks past quiesce.

Usage: ``python -m cake_tpu.runtime.chaos_smoke [--tokens N]``
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import threading
import time


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="cake-tpu chaos-smoke")
    p.add_argument("--tokens", type=int, default=16)
    args = p.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from cake_tpu.io.safetensors_io import save_tiny_checkpoint
    from cake_tpu.models.llama import model as M
    from cake_tpu.models.llama.chat import Message
    from cake_tpu.models.llama.config import LlamaConfig
    from cake_tpu.models.llama.generator import SamplingConfig
    from cake_tpu.models.llama.tokenizer import ByteTokenizer
    from cake_tpu.parallel.topology import Topology
    from cake_tpu.runtime import faults
    from cake_tpu.runtime.batch_backend import DistributedBatchBackend
    from cake_tpu.runtime.master import DistributedForwardStep
    from cake_tpu.runtime.serving import BatchEngine, ServeConfig
    from cake_tpu.runtime.worker import Worker
    from cake_tpu.utils import metrics

    problems: list[str] = []
    greedy = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
    model_dir = os.path.join(
        tempfile.mkdtemp(prefix="cake-chaos-smoke-"), "model"
    )
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(7), jnp.float32)
    save_tiny_checkpoint(model_dir, params, cfg)

    topo = Topology.from_dict(
        {"w0": {"host": "placeholder", "layers": ["model.layers.0-1"]}}
    )
    worker = Worker(
        "w0", model_dir, topo, ("127.0.0.1", 0),
        dtype=jnp.float32, max_seq_len=128,
    )
    worker.start()
    topo.nodes["w0"].host = f"127.0.0.1:{worker.address[1]}"
    step = DistributedForwardStep(
        cfg, model_dir, topo, dtype=jnp.float32, max_seq_len=128,
        op_deadline_s=5.0, op_retries=2,
        reconnect_attempts=3, reconnect_backoff_s=0.05,
    )

    def engine() -> BatchEngine:
        eng = BatchEngine(
            cfg, None, ByteTokenizer(),
            max_seq_len=128, cache_dtype=jnp.float32,
            backend=DistributedBatchBackend(
                step, max_seq_len=128, cache_dtype=jnp.float32
            ),
            serve=ServeConfig(
                max_batch=4, decode_chunk_size=4, admission_window=0.02
            ),
        )
        eng.start()
        return eng

    def serve_two(eng):
        h_short = eng.submit([Message.user("survivor stream")], 2, greedy)
        h_long = eng.submit(
            [Message.user("the long victim stream")], args.tokens, greedy
        )
        return (
            [t.id for t in h_short.tokens()],
            [t.id for t in h_long.tokens()],
            h_short, h_long,
        )

    try:
        # Fault-free oracle.
        eng = engine()
        want_short, want_long, _, _ = serve_two(eng)
        eng.stop()

        # The seeded crash: prefill + first 4-token chunk apply (the 2-token
        # survivor finishes inside it), then the worker dies on op 6.
        faults.install(
            faults.parse("seed=7;crash@worker.op:after=5:count=1")
        )
        eng = engine()
        got_short, got_long, h_short, h_long = serve_two(eng)

        if got_short != want_short:
            problems.append(
                f"survivor stream diverged: {got_short} != {want_short}"
            )
        if h_long.finish_reason != "error":
            problems.append(
                f"victim finish_reason={h_long.finish_reason!r}, "
                "expected 'error'"
            )
        if got_long != want_long[: len(got_long)] or len(got_long) >= len(
            want_long
        ):
            problems.append(
                "victim did not get a clean fault-free prefix: "
                f"{got_long} vs {want_long}"
            )
        # Engine survived the crash: next epoch serves normally.
        h = eng.submit([Message.user("survivor stream")], 2, greedy)
        if [t.id for t in h.tokens()] != want_short:
            problems.append("post-crash request diverged (engine damaged?)")
        eng.stop()

        faulted = metrics.registry.counter(
            "cake_faults_injected_total"
        ).value(kind="crash", site="worker.op")
        if faulted != 1:
            problems.append(f"expected exactly 1 injected crash, saw {faulted}")
        if not metrics.registry.counter(
            "cake_hop_failures_total"
        ).value(node="w0"):
            problems.append("cake_hop_failures_total{node=w0} never moved")
        if not any(
            e["event"] == "fault-injected" for e in metrics.flight.snapshot()
        ):
            problems.append("no fault-injected flight event recorded")
    finally:
        faults.clear()
        step.close()
        worker.stop()

    # ---------------------------------------------- failover (replica) gate

    topo_r = Topology.from_dict(
        {
            "w0": {"host": "placeholder", "layers": ["model.layers.0-1"]},
            "w0b": {"host": "placeholder", "layers": ["model.layers.0-1"]},
        }
    )
    workers_r = []
    for name in ("w0", "w0b"):
        w = Worker(
            name, model_dir, topo_r, ("127.0.0.1", 0),
            dtype=jnp.float32, max_seq_len=128,
        )
        w.start()
        topo_r.nodes[name].host = f"127.0.0.1:{w.address[1]}"
        workers_r.append(w)

    def replica_step() -> DistributedForwardStep:
        return DistributedForwardStep(
            cfg, model_dir, topo_r, dtype=jnp.float32, max_seq_len=128,
            op_deadline_s=5.0, op_retries=1,
            reconnect_attempts=2, reconnect_backoff_s=0.05,
        )

    def replica_engine(step_r) -> BatchEngine:
        step_r.router.prefer("w0")  # the epoch under test routes the primary
        eng = BatchEngine(
            cfg, None, ByteTokenizer(),
            max_seq_len=128, cache_dtype=jnp.float32,
            backend=DistributedBatchBackend(
                step_r, max_seq_len=128, cache_dtype=jnp.float32
            ),
            serve=ServeConfig(
                max_batch=4, decode_chunk_size=4, admission_window=0.02
            ),
        )
        eng.start()
        return eng

    try:
        step_r = replica_step()
        eng = replica_engine(step_r)
        want_short_f, want_long_f, _, _ = serve_two(eng)
        eng.stop()
        step_r.close()

        # The primary becomes unreachable on its 4th send and stays dead
        # (count=0): retries exhaust, the router fails over to w0b, and the
        # engine migrates the live streams there.
        faults.install(
            faults.parse("seed=7;kill@client.send:node=w0:after=3:count=0")
        )
        step_r = replica_step()
        eng = replica_engine(step_r)
        got_short_f, got_long_f, h_short, h_long = serve_two(eng)

        if (got_short_f, got_long_f) != (want_short_f, want_long_f):
            problems.append(
                "failover: streams diverged from the fault-free run: "
                f"{(got_short_f, got_long_f)} != "
                f"{(want_short_f, want_long_f)}"
            )
        for h, label in ((h_short, "short"), (h_long, "long")):
            if h.finish_reason not in ("stop", "length"):
                problems.append(
                    f"failover: {label} stream finished "
                    f"{h.finish_reason!r}, expected stop/length"
                )
        if eng.stats["stream_errors"]:
            problems.append(
                f"failover: {eng.stats['stream_errors']} stream(s) finished "
                "'error' despite a healthy replica"
            )
        if not eng.stats["failovers"]:
            problems.append("failover: engine reports zero failovers")
        if not metrics.registry.counter(
            "cake_failover_total"
        ).value(node="w0"):
            problems.append("cake_failover_total{node=w0} never moved")
        eng.stop()
        step_r.close()
    finally:
        faults.clear()
        for w in workers_r:
            w.stop()

    # ------------------------------------------ shared-prefix (cache) gate

    sysprompt = "A shared system preamble on pages."
    prompts = [sysprompt + " stream1", sysprompt + " stream2"]

    def prefix_engine() -> BatchEngine:
        eng = BatchEngine(
            cfg, params, ByteTokenizer(),
            max_seq_len=128, cache_dtype=jnp.float32,
            serve=ServeConfig(
                max_batch=4, decode_chunk_size=2, admission_window=0.02,
                kv_mode="paged", page_size=16, prefix_cache=True,
            ),
        )
        eng.start()
        return eng

    def serve_shared(eng):
        hs = [
            eng.submit([Message.user(p)], args.tokens, greedy)
            for p in prompts
        ]
        return [[t.id for t in h.tokens()] for h in hs], hs

    try:
        eng = prefix_engine()
        alloc = eng.backend.allocator
        cold, _ = serve_shared(eng)  # cold: misses, chains insert on finish
        # Cold chains insert on stream FINISH, which races the consumer's
        # iterator close: a warm pass submitted in that gap misses the
        # cache legitimately. Bounded-deadline poll (the convoy A/B
        # pattern): re-run the warm pass until the forks land, and only a
        # still-cold cache at the deadline is a real failure.
        warm = cold
        deadline = time.monotonic() + 10.0
        while True:
            warm, _ = serve_shared(eng)  # warm: forks the cached chains
            if warm != cold:
                problems.append(
                    f"prefix: warm streams diverged from cold: "
                    f"{warm} != {cold}"
                )
                break
            if eng.stats["prefix_hits"] >= 2:
                break
            if time.monotonic() >= deadline:
                problems.append(
                    "prefix: warm passes forked fewer than 2 cached chains "
                    f"(prefix_hits={eng.stats['prefix_hits']})"
                )
                break
            time.sleep(0.2)
        # A crash while the NEXT warm pass holds forked shared pages:
        # clean "error" degradation, cache cleared, engine keeps serving.
        faults.install(
            faults.parse("seed=7;crash@backend.decode:after=2:count=1")
        )
        crashed, hs = serve_shared(eng)
        faults.clear()
        if any(h.finish_reason not in ("error", "stop", "length") for h in hs):
            problems.append(
                "prefix: crash finish reasons "
                f"{[h.finish_reason for h in hs]}"
            )
        if not any(h.finish_reason == "error" for h in hs):
            problems.append("prefix: seeded crash never fired")
        for c, w in zip(crashed, warm):
            if c != w[: len(c)]:
                problems.append(
                    f"prefix: crashed stream not a clean prefix: {c} vs {w}"
                )
        again, _ = serve_shared(eng)  # cold rebuild after the clear
        if again != cold:
            problems.append(
                f"prefix: post-crash streams diverged: {again} != {cold}"
            )
        eng.stop()
        eng._prefix.clear()
        if alloc.pages_free != alloc.pages_total:
            problems.append(
                "prefix: pool did not drain after clear(): "
                f"{alloc.pages_free}/{alloc.pages_total} free"
            )
    finally:
        faults.clear()

    # ------------------------------------------ overload storm (A/B) gate

    from cake_tpu.runtime.admission import QuotaExceeded

    def run_storm(fair: bool) -> dict:
        """One plug epoch + an abusive 10-request flood + one compliant
        request through a fair/FIFO paged engine; returns the outcome the
        gates below judge. A seeded per-chunk stall slows decode so the
        epoch reliably outlives the doomed request's deadline on a warm
        jit cache."""
        eng = BatchEngine(
            cfg, params, ByteTokenizer(),
            max_seq_len=128, cache_dtype=jnp.float32,
            serve=ServeConfig(
                max_batch=2, decode_chunk_size=4, admission_window=0.02,
                kv_mode="paged", page_size=16,
                # Burst sized so ~8 of the 10 flood requests are ADMITTED
                # (the FIFO starvation baseline needs a real queue) and
                # the tail is refused (the 429 gate needs refusals).
                tenant_rate=40.0, tenant_burst=300.0, fair_queue=fair,
            ),
        )
        eng.start()
        alloc = eng.backend.allocator
        out: dict = {"fair": fair}
        done: list[str] = []
        toks: dict[str, list[int]] = {}
        lock = threading.Lock()

        def consume(tag, h):
            got = [t.id for t in h.tokens()]
            with lock:
                done.append(tag)
                toks[tag] = got

        def timed_solo(tenant: str):
            t0 = time.monotonic()
            h = eng.submit(
                [Message.user("compliant request")], 3, greedy, tenant=tenant
            )
            toks = [t.id for t in h.tokens()]
            return time.monotonic() - t0, toks

        try:
            timed_solo("warm")  # compiles land outside every clock
            out["iso_s"], out["want_good"] = timed_solo("good-iso")
            faults.install(
                faults.parse("stall@backend.decode:count=0:delay_s=0.01")
            )
            plug = eng.submit(
                [Message.user("storm plug stream")], 40, greedy,
                tenant="plug",
            )
            threads = [
                threading.Thread(
                    target=consume, args=("plug", plug), daemon=True
                )
            ]
            threads[0].start()
            deadline = time.monotonic() + 10.0
            while eng.stats["batches"] < 3 and time.monotonic() < deadline:
                time.sleep(0.002)
            abuse, refusals = [], []
            for i in range(10):
                try:
                    abuse.append(
                        eng.submit(
                            [Message.user(f"abusive flood request {i:02d}")],
                            3, greedy, tenant="abuser",
                        )
                    )
                except QuotaExceeded as e:
                    refusals.append(e.retry_after_s)
            doomed = None
            try:
                doomed = eng.submit(
                    [Message.user("doomed by deadline")], 8,
                    SamplingConfig(
                        temperature=0.8, repeat_penalty=1.0, seed=3
                    ),
                    tenant="late", deadline_s=0.05,
                )
            except Exception as e:  # deadline-aware shed (503 path)
                out["doomed_shed"] = "deadline" in str(e)
            t0 = time.monotonic()
            hg = eng.submit(
                [Message.user("compliant request")], 3, greedy,
                tenant="good",
            )
            for tag, h in [("good", hg)] + [
                (f"abuse{i}", h) for i, h in enumerate(abuse)
            ]:
                t = threading.Thread(
                    target=consume, args=(tag, h), daemon=True
                )
                t.start()
                threads.append(t)
            for t in threads:
                t.join(60.0)
            out["hung"] = any(t.is_alive() for t in threads)
            with lock:
                if "good" in done:
                    before = done[: done.index("good")]
                    out["abusers_before_good"] = sum(
                        1 for d in before if d.startswith("abuse")
                    )
                out["good_toks"] = toks.get("good")
            out["good_finish"] = hg.finish_reason
            out["abuse_finishes"] = [h.finish_reason for h in abuse]
            out["n_admitted"] = len(abuse)
            out["refusals"] = refusals
            if doomed is not None:
                for _ in doomed.tokens():
                    pass
                out["doomed_finish"] = doomed.finish_reason
                out["doomed_tokens"] = doomed.completion_tokens
            faults.clear()
            out["drained"] = (
                eng.quiesce(10.0)
                and alloc.pages_free == alloc.pages_total
            )
        finally:
            faults.clear()
            eng.stop()
        return out

    try:
        storm_fair = run_storm(True)
        storm_fifo = run_storm(False)
        for s in (storm_fair, storm_fifo):
            tag = "fair" if s["fair"] else "fifo"
            if s["hung"]:
                problems.append(f"storm[{tag}]: a stream hung")
            if s["good_finish"] not in ("stop", "length"):
                problems.append(
                    f"storm[{tag}]: compliant finished "
                    f"{s['good_finish']!r}"
                )
            if s["good_toks"] != s["want_good"]:
                problems.append(
                    f"storm[{tag}]: compliant stream diverged under load: "
                    f"{s['good_toks']} != {s['want_good']}"
                )
            if any(
                f not in ("stop", "length") for f in s["abuse_finishes"]
            ):
                problems.append(
                    f"storm[{tag}]: admitted abuser streams degraded: "
                    f"{s['abuse_finishes']}"
                )
            if not s["refusals"]:
                problems.append(
                    f"storm[{tag}]: the flood never hit the quota (429)"
                )
            elif not all(r > 0 for r in s["refusals"]) or (
                max(s["refusals"]) - min(s["refusals"]) >= 2.0
            ):
                problems.append(
                    f"storm[{tag}]: inconsistent Retry-After hints: "
                    f"{s['refusals']}"
                )
            if "doomed_finish" in s:
                if s["doomed_finish"] != "deadline" or s["doomed_tokens"]:
                    problems.append(
                        f"storm[{tag}]: doomed request finished "
                        f"{s['doomed_finish']!r} with "
                        f"{s['doomed_tokens']} tokens"
                    )
            elif not s.get("doomed_shed"):
                problems.append(
                    f"storm[{tag}]: doomed request neither expired nor "
                    "deadline-shed"
                )
            if not s["drained"]:
                problems.append(
                    f"storm[{tag}]: pool did not drain to fully-free"
                )
        if storm_fair.get("abusers_before_good", 99) > 3:
            problems.append(
                "storm[fair]: compliant finished after "
                f"{storm_fair.get('abusers_before_good')} abuser streams "
                "— fairness is not isolating the flood"
            )
        if storm_fifo.get("abusers_before_good", 0) < storm_fifo[
            "n_admitted"
        ]:
            problems.append(
                "storm[fifo]: compliant finished after only "
                f"{storm_fifo.get('abusers_before_good')}/"
                f"{storm_fifo['n_admitted']} abuser streams — the FIFO "
                "baseline no longer demonstrates starvation, so the A/B "
                "proves nothing"
            )
    finally:
        faults.clear()

    # ------------------------------ continuous preemption + failover gate

    # This scenario's weights are seeded apart from the cluster ones: the
    # pressure geometry (two ~92-token prompts outgrowing a 14-page pool)
    # needs streams that run their full budget, and seed 7's greedy head
    # stream emits EOS on its first token.
    params_p = M.init_params(cfg, jax.random.PRNGKey(31), jnp.float32)

    def run_preempt(plan: str | None) -> dict:
        """Two long streams through a paged CONTINUOUS engine whose pool is
        too small for both — one lane spills host-side. With ``plan`` the
        backend dies while the lane sits spilled (failover_local migrates
        the live stream in place; the restore then walks the recovered
        backend). Returns the outcome the gates below judge."""
        faults.clear()
        if plan:
            faults.install(faults.parse(plan))
        eng = BatchEngine(
            cfg, params_p, ByteTokenizer(),
            max_seq_len=256, cache_dtype=jnp.float32,
            serve=ServeConfig(
                max_batch=4, decode_chunk_size=4, admission_window=0.1,
                scheduler="continuous", kv_mode="paged", page_size=16,
                max_pages=14, failover_local=True,
            ),
        )
        eng.start()
        out: dict = {}
        try:
            handles = [
                eng.submit([Message.user(p)], 48, greedy)
                for p in (
                    "alpha prompt padded out to be long " * 2,
                    "row two also made quite long here " * 2,
                )
            ]
            out["toks"] = [[t.id for t in h.tokens()] for h in handles]
            out["finishes"] = [h.finish_reason for h in handles]
            out["stats"] = dict(eng.stats)
            out["drained"] = eng.quiesce(10.0) and (
                eng.backend.allocator.pages_free
                == eng.backend.allocator.pages_total
            )
            with eng._cv:
                out["spill_leak"] = len(eng._spilled)
            out["order"] = [
                e["event"]
                for e in metrics.flight.snapshot()
                if e["event"] in ("preempted", "failover", "restored")
            ]
        finally:
            faults.clear()
            eng.stop()
        return out

    try:
        pre_clean = run_preempt(None)
        pre_kill = run_preempt("crash@backend.decode:after=10:count=1")
        if pre_clean["stats"]["preemptions"] < 1:
            problems.append(
                "preempt: the pressure scenario never preempted — the "
                "gate is not exercising the spill path"
            )
        if pre_kill["toks"] != pre_clean["toks"]:
            problems.append(
                "preempt: streams diverged when the backend died while a "
                "lane sat spilled (restore did not ride the failover "
                "bit-identically)"
            )
        if pre_kill["stats"]["stream_errors"] or any(
            f not in ("stop", "length") for f in pre_kill["finishes"]
        ):
            problems.append(
                f"preempt: degraded finishes {pre_kill['finishes']} "
                f"({pre_kill['stats']['stream_errors']} stream errors)"
            )
        if (
            pre_kill["stats"]["failovers"] != 1
            or pre_kill["stats"]["preemptions"] < 1
            or pre_kill["stats"]["restores"] < 1
        ):
            problems.append(
                "preempt: expected 1 failover + >=1 preemption/restore, "
                f"got {pre_kill['stats']['failovers']}/"
                f"{pre_kill['stats']['preemptions']}/"
                f"{pre_kill['stats']['restores']}"
            )
        if pre_kill["order"][-3:] != ["preempted", "failover", "restored"]:
            problems.append(
                "preempt: the kill did not land while the lane sat "
                f"spilled (event tail {pre_kill['order'][-3:]})"
            )
        for tag, s in (("clean", pre_clean), ("kill", pre_kill)):
            if not s["drained"]:
                problems.append(f"preempt[{tag}]: pool did not drain")
            if s["spill_leak"]:
                problems.append(
                    f"preempt[{tag}]: {s['spill_leak']} spilled chain(s) "
                    "leaked past quiesce"
                )
    finally:
        faults.clear()

    for prob in problems:
        print(f"chaos-smoke: FAIL: {prob}", file=sys.stderr)
    if problems:
        return 1
    print(
        "chaos-smoke: OK — worker crash mid-decode: survivor bit-identical, "
        f"victim errored cleanly at {len(got_long)}/{len(want_long)} tokens, "
        "engine kept serving; with a replica the primary's death migrated "
        f"{len(got_long_f)}-token streams bit-identically (zero errors); "
        f"shared-prefix cache served {eng.stats['prefix_hits']} forked "
        "chains bit-identically through a mid-decode crash; overload "
        f"storm: fair queue held the compliant stream to "
        f"{storm_fair.get('abusers_before_good')} abuser finishes ahead "
        f"(FIFO: {storm_fifo.get('abusers_before_good')}/"
        f"{storm_fifo['n_admitted']}), "
        f"{len(storm_fair['refusals'])} quota 429s, doomed deadline "
        "request ran zero tokens, pool drained; continuous preemption: "
        f"{pre_kill['stats']['preemptions']} spill(s) + "
        f"{pre_kill['stats']['restores']} restore(s) rode a seeded "
        "backend death bit-identically, no leaked spilled chains"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
