"""SLO-hardened admission control: tenants, fair queueing, deadlines, stalls.

PR 6/7 made the engine survive WORKER failures; this module protects it from
TRAFFIC. Four pieces, all consumed by runtime/serving.py:

  * ``TenantMeter`` — per-tenant accounting: a token-bucket rate limit
    (tokens/s + burst, where a "token" is a unit of requested work:
    prompt tokens + max_tokens) and a concurrent-stream cap. A refusal is
    ``QuotaExceeded`` — mapped to HTTP **429 + Retry-After** by the API
    layer, deliberately distinct from the 503 ``EngineOverloaded`` shed:
    429 means *you* are over budget (back off per the hint), 503 means the
    *server* is saturated (anyone may retry).
  * ``FairQueue`` — the engine's request queue, replacing the global FIFO:
    one FIFO subqueue per tenant, drained by deficit-weighted round-robin
    (DRR). Each tenant accumulates ``quantum`` cost-tokens of deficit per
    scheduling visit and may dequeue while its head's cost fits the
    deficit, so a tenant flooding ten thousand requests still hands the
    next admission slot to the tenant who queued one. Priority classes
    compose by scaling COST (a high-priority request consumes half the
    fair-share budget, low twice), so priorities bias service without
    breaking isolation. With one tenant (or ``fair=False``) the schedule
    reduces exactly to the old global FIFO.
  * ``WaitEstimator`` — an EWMA of observed queue waits powering
    deadline-aware shedding: a request whose ``deadline_s`` is already
    smaller than the estimated queue wait is refused NOW (503) instead of
    queueing into a guaranteed timeout.
  * ``StepBudget`` — the continuous scheduler's per-step prefill grant
    (README "Continuous scheduling"): how many prompt tokens of join /
    restore prefill one engine step may dispatch before decode resumes,
    scaled up by SLO burn (queue missing TTFT) and down by running-stream
    deadline pressure.
  * ``StallGuard`` — the stuck-epoch watchdog. A backend that stalls
    WITHOUT raising (the PR 6 ``stall`` fault kind, a wedged device, a
    hung collective) would park the engine thread forever — heartbeats
    only see dead *sockets*. The guard runs each backend dispatch on a
    watchdog thread while the engine waits with a bounded timeout
    (``epoch_stall_s``); on expiry the dispatch is ABANDONED (the thread
    is disposable; a late result is discarded, observable as
    ``cake_epoch_stalls_resolved_total``) and the engine sees the same
    typed ``BackendWorkerError`` a dead worker produces — so a silent hang
    flows through the existing failover/error-isolation path and costs
    one epoch, not the engine.

Observability: ``cake_tenant_*`` counters/gauges, ``cake_quota_refusals_
total{tenant,kind}``, ``cake_deadline_expired_total{where}``,
``cake_epoch_stalls_total``, ``quota-refused``/``deadline-expired``/
``epoch-stall`` flight events, and timeline instants on the engine track.
README "Admission control & SLOs" documents the model end to end.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque

from cake_tpu.utils import metrics

DEFAULT_TENANT = "default"

# Keep the per-tenant label space bounded: past this many distinct tenants
# the meter evicts the least-recently-seen tenant with no open streams (its
# bucket state resets — a returning tenant starts from a full bucket, which
# errs on the side of admitting).
MAX_TENANTS = 1024


class QuotaExceeded(RuntimeError):
    """Per-tenant quota refusal (rate limit or stream cap) — HTTP **429**.

    Distinct from ``EngineOverloaded`` (503): a 429 is attributable to the
    CALLER's traffic and carries a Retry-After computed from their own
    bucket arithmetic; a 503 is server saturation.
    """

    def __init__(self, message: str, retry_after_s: float = 1.0,
                 tenant: str = DEFAULT_TENANT, kind: str = "rate"):
        super().__init__(message)
        self.retry_after_s = retry_after_s
        self.tenant = tenant
        self.kind = kind  # "rate" | "streams"


class TokenBucket:
    """Classic token bucket over monotonic time (caller holds the lock).

    A request larger than the burst is granted whenever the bucket is at
    least at its ``min(cost, burst)`` mark and charged in full — the level
    goes NEGATIVE (debt), delaying later grants — so oversized requests
    eventually pass while the long-run rate still converges to ``rate``.
    """

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self.level = float(burst)
        self.t = time.monotonic()

    def try_take(self, cost: float, now: float | None = None) -> float:
        """0.0 when granted (and charged); else seconds until it would be."""
        now = time.monotonic() if now is None else now
        self.level = min(self.burst, self.level + (now - self.t) * self.rate)
        self.t = now
        need = min(cost, self.burst) if self.burst > 0 else cost
        if self.level >= need:
            self.level -= cost
            return 0.0
        if self.rate <= 0:
            return float("inf")
        return (need - self.level) / self.rate

    def refund(self, cost: float) -> None:
        """Credit back a charge whose request never ran (a 503 shed after
        the quota grant): without this, server overload would drain the
        caller's own bucket on zero-work submissions and convert into
        spurious 429s — inverting the 429-vs-503 attribution contract."""
        self.level = min(self.burst, self.level + cost)


class _Tenant:
    __slots__ = ("bucket", "open_rids", "tokens", "submitted", "refusals")

    def __init__(self, rate: float, burst: float):
        self.bucket = TokenBucket(rate, burst) if rate > 0 else None
        self.open_rids: set[str] = set()
        self.tokens = 0.0
        self.submitted = 0
        self.refusals = 0


class TenantMeter:
    """Per-tenant quota enforcement + accounting (thread-safe: submissions
    arrive from many API handler threads).

    ``rate``/``burst`` are in work tokens (prompt + max_tokens);
    ``max_streams`` caps a tenant's QUEUED + LIVE streams. 0 disables each
    gate; the meter still tracks per-tenant counters for ``/stats`` either
    way. ``admit`` is atomic: it either registers the stream and returns,
    or raises ``QuotaExceeded`` leaving no state behind.
    """

    def __init__(self, rate: float = 0.0, burst: float = 0.0,
                 max_streams: int = 0):
        self.rate = float(rate)
        self.burst = float(burst) if burst > 0 else (
            2.0 * rate if rate > 0 else 0.0
        )
        self.max_streams = int(max_streams)
        self._lock = threading.Lock()
        self._tenants: OrderedDict[str, _Tenant] = OrderedDict()
        self._rid_tenant: dict[str, tuple[str, float]] = {}

    def _tenant(self, tenant: str) -> _Tenant:
        t = self._tenants.get(tenant)
        if t is None:
            t = self._tenants[tenant] = _Tenant(self.rate, self.burst)
            while len(self._tenants) > MAX_TENANTS:
                for key, cand in self._tenants.items():
                    if not cand.open_rids and key != tenant:
                        del self._tenants[key]
                        break
                else:
                    break  # every tenant has open streams: over-cap but live
        else:
            self._tenants.move_to_end(tenant)
        return t

    def admit(self, tenant: str, rid: str, cost: float) -> None:
        """Charge one submission; raises QuotaExceeded (429) on refusal."""
        with self._lock:
            t = self._tenant(tenant)
            if self.max_streams and len(t.open_rids) >= self.max_streams:
                self._refused(t, tenant, "streams")
                raise QuotaExceeded(
                    f"tenant {tenant!r} has {len(t.open_rids)} concurrent "
                    f"streams (cap {self.max_streams})",
                    retry_after_s=1.0, tenant=tenant, kind="streams",
                )
            if t.bucket is not None:
                wait = t.bucket.try_take(cost)
                if wait > 0:
                    self._refused(t, tenant, "rate")
                    raise QuotaExceeded(
                        f"tenant {tenant!r} over its token rate "
                        f"({self.rate:g} tok/s, burst {self.burst:g}); "
                        f"{cost:g} tokens available in {wait:.2f}s",
                        retry_after_s=max(0.1, wait), tenant=tenant,
                        kind="rate",
                    )
            t.open_rids.add(rid)
            self._rid_tenant[rid] = (tenant, float(cost))
            t.tokens += cost
            t.submitted += 1
            metrics.registry.counter(
                "cake_tenant_submitted_total",
                "Submissions accepted past the per-tenant quota gates.",
            ).inc(tenant=tenant)
            metrics.registry.counter(
                "cake_tenant_tokens_total",
                "Work tokens (prompt + max_tokens) admitted per tenant.",
            ).inc(cost, tenant=tenant)
            metrics.registry.gauge(
                "cake_tenant_active_streams",
                "Queued + live streams per tenant (quota view).",
            ).set(len(t.open_rids), tenant=tenant)

    @staticmethod
    def _refused(t: _Tenant, tenant: str, kind: str) -> None:
        t.refusals += 1
        metrics.registry.counter(
            "cake_quota_refusals_total",
            "Submissions refused by per-tenant quotas (HTTP 429 + "
            "Retry-After; kind=rate|streams).",
        ).inc(tenant=tenant, kind=kind)
        metrics.flight.record("quota-refused", tenant=tenant, kind=kind)

    def close(self, rid: str, refund: bool = False) -> None:
        """A stream finished (any reason) — idempotent. ``refund=True`` is
        for submissions that were quota-granted but then REFUSED by a later
        gate (the 503 shed): the charge is credited back so the server's
        overload never drains the caller's bucket."""
        with self._lock:
            entry = self._rid_tenant.pop(rid, None)
            if entry is None:
                return
            tenant, cost = entry
            t = self._tenants.get(tenant)
            if t is not None:
                t.open_rids.discard(rid)
                if refund:
                    t.tokens -= cost
                    if t.bucket is not None:
                        t.bucket.refund(cost)
                metrics.registry.gauge(
                    "cake_tenant_active_streams",
                    "Queued + live streams per tenant (quota view).",
                ).set(len(t.open_rids), tenant=tenant)

    def snapshot(self) -> dict:
        """Per-tenant accounting for the ``/stats`` tenants block."""
        with self._lock:
            return {
                name: {
                    "active_streams": len(t.open_rids),
                    "submitted": t.submitted,
                    "tokens": round(t.tokens, 1),
                    "quota_refusals": t.refusals,
                    "bucket_level": (
                        round(t.bucket.level, 1)
                        if t.bucket is not None
                        else None
                    ),
                }
                for name, t in self._tenants.items()
            }


class FairQueue:
    """Deficit-weighted round-robin request queue over tenant subqueues.

    NOT thread-safe by design: every call runs under the engine's condition
    variable, exactly like the deque it replaces. With ``fair=False`` (or a
    single tenant) all requests share one subqueue and the scan order is
    the old global FIFO, byte for byte.

    ``take(limit, accept)`` is the one scheduling entry point: it walks
    candidates in fair order and asks ``accept(req)`` for a verdict —

      * ``"take"``  — dequeue it (counts toward ``limit``; its cost is
        charged against the tenant's deficit),
      * ``"skip"``  — leave it queued, keep scanning the SAME tenant
        (a candidate that doesn't fit this epoch's knobs/pages),
      * ``"next"``  — leave it queued, stop scanning this tenant for this
        call (the per-tenant FIFO no-jump rule at joins),
      * ``"drop"``  — dequeue WITHOUT counting it (an expired request the
        caller just finished).

    The deficit check runs before ``accept``: a head costlier than its
    tenant's deficit blocks that tenant until the next visit. When a full
    round-robin cycle takes nothing but some head was deficit-blocked,
    every active tenant receives the minimum unblocking number of quanta
    at once — mathematically the textbook DRR loop fast-forwarded, so one
    ``take`` call terminates in O(tenants × queue) instead of spinning
    cycles 256 tokens at a time.
    """

    def __init__(self, fair: bool = True, quantum: int = 256, cost=None):
        self.fair = bool(fair)
        self.quantum = max(1, int(quantum))
        self._cost = cost or (lambda req: 1.0)
        self._q: dict[str, deque] = {}
        self._rr: deque[str] = deque()  # active (non-empty) tenants, RR order
        self._deficit: dict[str, float] = {}
        # Per-tenant quantum weights (the SLO feedback seam, obs/slo.py):
        # a tenant at weight w accrues w x quantum deficit per round-robin
        # visit, draining ahead of weight-1 tenants without breaking the
        # DRR isolation math. Absent = 1.0. Bounded: only SLO-tracked
        # tenants (obs/slo.py max_tenants) ever get an entry, and weight
        # 1.0 deletes it.
        self._weights: dict[str, float] = {}
        self._total = 0
        self.deadline_count = 0  # queued requests carrying a deadline

    def set_weight(self, tenant: str, weight: float) -> None:
        """Scale ``tenant``'s per-visit quantum (SLO burn feedback);
        1.0 restores the unweighted share. With ``fair=False`` there are
        no per-tenant subqueues for a weight to act on — a silent no-op
        here (and no gauge) beats exporting a weight that does nothing."""
        if not self.fair:
            return
        weight = max(1.0, float(weight))
        if weight == 1.0:
            self._weights.pop(tenant, None)
        else:
            self._weights[tenant] = weight
        metrics.registry.gauge(
            "cake_tenant_quantum_weight",
            "DRR quantum multiplier per tenant (SLO burn feedback; "
            "1 = unweighted fair share).",
        ).set(weight, tenant=tenant or DEFAULT_TENANT)

    def weight(self, tenant: str) -> float:
        return self._weights.get(tenant, 1.0)

    def _key(self, req) -> str:
        return getattr(req, "tenant", DEFAULT_TENANT) if self.fair else ""

    # ------------------------------------------------------------- mutation

    def append(self, req) -> None:
        key = self._key(req)
        dq = self._q.get(key)
        if dq is None:
            dq = self._q[key] = deque()
        if not dq:
            if key not in self._rr:
                self._rr.append(key)
            self._deficit.setdefault(key, 0.0)
        dq.append(req)
        self._total += 1
        if getattr(req, "deadline", 0.0):
            self.deadline_count += 1
        self._gauge(key)

    def extend(self, reqs) -> None:
        for req in reqs:
            self.append(req)

    def remove(self, req) -> bool:
        key = self._key(req)
        dq = self._q.get(key)
        if dq is None:
            return False
        try:
            dq.remove(req)
        except ValueError:
            return False
        self._dropped(key, req)
        return True

    def clear(self) -> None:
        for key, dq in self._q.items():
            dq.clear()
            self._gauge(key)
        self._q.clear()
        self._rr.clear()
        self._deficit.clear()
        self._total = 0
        self.deadline_count = 0

    def _dropped(self, key: str, req) -> None:
        self._total -= 1
        if getattr(req, "deadline", 0.0):
            self.deadline_count -= 1
        self._gauge(key)
        if not self._q[key]:
            # Hostile tenant-id churn must not grow these dicts without
            # bound: an emptied subqueue's entries are DELETED, not parked
            # (which also gives classic DRR's no-idle-credit rule — a
            # re-appearing tenant starts from deficit 0).
            del self._q[key]
            self._deficit.pop(key, None)
            try:
                self._rr.remove(key)
            except ValueError:
                pass

    def _gauge(self, key: str) -> None:
        metrics.registry.gauge(
            "cake_tenant_queued", "Requests queued per tenant."
        ).set(len(self._q.get(key, ())), tenant=key or DEFAULT_TENANT)

    # -------------------------------------------------------------- queries

    def __len__(self) -> int:
        return self._total

    def __iter__(self):
        for key in list(self._rr):
            yield from list(self._q[key])

    def oldest_head(self):
        """The earliest-submitted request among tenant heads — with
        per-tenant FIFO subqueues this IS the oldest queued request, the
        one the join path's epoch-bounding rule watches."""
        heads = [self._q[k][0] for k in self._rr if self._q.get(k)]
        if not heads:
            return None
        return min(heads, key=lambda r: getattr(r, "t_submit", 0.0))

    def queued_by_tenant(self) -> dict[str, int]:
        return {
            (k or DEFAULT_TENANT): len(dq)
            for k, dq in self._q.items()
            if dq
        }

    # ------------------------------------------------------------ scheduling

    def take(self, limit: int, accept) -> list:
        taken: list = []
        if limit <= 0 or not self._total:
            return taken
        stopped: set[str] = set()
        while len(taken) < limit:
            took = False
            shortfall: float | None = None
            if not any(
                self._q[k] and k not in stopped for k in self._rr
            ):
                break
            # One full rotation = one DRR round: every active tenant is
            # visited exactly once (stopped/emptied keys burn a rotation
            # slot, so the bound is the FULL rr length).
            for _ in range(len(self._rr)):
                if len(taken) >= limit:
                    break
                if not self._rr:
                    break
                key = self._rr[0]
                self._rr.rotate(-1)
                if key in stopped or not self._q.get(key):
                    continue
                self._deficit[key] += (
                    self.quantum * self._weights.get(key, 1.0)
                )
                dq = self._q[key]
                i = 0
                while i < len(dq) and len(taken) < limit:
                    req = dq[i]
                    c = max(1.0, float(self._cost(req)))
                    if c > self._deficit[key]:
                        gap = c - self._deficit[key]
                        if shortfall is None or gap < shortfall:
                            shortfall = gap
                        break
                    verdict = accept(req)
                    if verdict == "take":
                        del dq[i]
                        self._deficit[key] -= c
                        self._dropped(key, req)
                        taken.append(req)
                        took = True
                    elif verdict == "drop":
                        del dq[i]
                        self._dropped(key, req)
                    elif verdict == "skip":
                        i += 1
                    else:  # "next"
                        stopped.add(key)
                        break
                # (an emptied subqueue was already deleted by _dropped)
            if not took:
                if shortfall is None:
                    break  # nothing blocked on deficit: accept() refused all
                # Fast-forward the blocked cycles: the same number of
                # quanta to everyone, each tenant's scaled by its weight
                # (so weighted shares survive the fast-forward too).
                boost = -(-shortfall // self.quantum) * self.quantum
                for key in self._rr:
                    self._deficit[key] += boost * self._weights.get(key, 1.0)
        return taken


class WaitEstimator:
    """EWMA of observed queue waits → the deadline-aware shed estimate.

    ``estimate`` scales the smoothed wait by queue depth relative to the
    batch width: with an empty queue the estimate decays toward the last
    observed waits; a deep queue multiplies it. Honest about cold start —
    zero until the first admission is observed, so a fresh engine never
    deadline-sheds.
    """

    def __init__(self, alpha: float = 0.3):
        self.alpha = alpha
        self.ewma = 0.0
        self.samples = 0

    def observe(self, wait_s: float) -> None:
        self.samples += 1
        if self.samples == 1:
            self.ewma = wait_s
        else:
            self.ewma += self.alpha * (wait_s - self.ewma)

    def estimate(
        self, depth: int, max_batch: int, scale: float = 1.0
    ) -> float:
        """``scale`` (>= 1) is the SLO feedback seam (obs/slo.py): a tenant
        burning error budget gets its estimate inflated, so its deadline-
        doomed submissions shed earlier — work that would miss anyway never
        queues, which is what protects goodput under SLO pressure."""
        if not self.samples:
            return 0.0
        return (
            self.ewma * (1.0 + depth / max(1, max_batch)) * max(1.0, scale)
        )


class StepBudget:
    """SLO-aware prefill-vs-decode split for the continuous scheduler.

    Each engine step grants at most ``grant()`` prompt tokens of join /
    restore prefill work before decode resumes (runtime/serving.py
    ``_take_restores`` / ``_take_joins``). Two feedback signals move it:

      * **Burn** (the PR 11 seam): while some tenant's SLO burn is high —
        queue waits are missing the TTFT objective — the grant DOUBLES, so
        admissions drain faster at the cost of slightly slower decode.
      * **Deadline slack** (the PR 10 seam): while a RUNNING stream's
        deadline slack is inside ``SLACK_CHUNKS`` recent chunk walls, the
        grant QUARTERS (floor ``MIN_TOKENS``) — a stream about to miss
        needs decode steps, not prefill stalls.

    Engine-thread only (no locks): ``observe_chunk`` feeds the chunk-wall
    EWMA the slack comparison is measured in.
    """

    AUTO_TOKENS = 512     # default base grant (~ a few joins per step)
    MIN_TOKENS = 64       # never starve admission entirely
    SLACK_CHUNKS = 8.0    # deadline pressure threshold in chunk walls

    def __init__(self, base_tokens: int = 0, alpha: float = 0.3):
        self.base = int(base_tokens)
        self.alpha = alpha
        self.chunk_ewma = 0.0

    def observe_chunk(self, wall_s: float) -> None:
        if self.chunk_ewma <= 0.0:
            self.chunk_ewma = wall_s
        else:
            self.chunk_ewma += self.alpha * (wall_s - self.chunk_ewma)

    def grant(
        self, burning: bool = False, tightest_slack_s: float | None = None
    ) -> int:
        out = self.base or self.AUTO_TOKENS
        if burning:
            out *= 2
        if (
            tightest_slack_s is not None
            and self.chunk_ewma > 0.0
            and tightest_slack_s < self.SLACK_CHUNKS * self.chunk_ewma
        ):
            out = max(self.MIN_TOKENS, out // 4)
        return out


class StallGuard:
    """Stuck-epoch watchdog: bound every backend dispatch by ``stall_s``.

    The engine calls ``call(fn, op)``; ``fn`` runs on the guard's watchdog
    thread while the engine waits under a timeout. A dispatch that neither
    returns nor raises within the bound is abandoned — the watchdog thread
    is disposable (a fresh one spawns for the next call; the stalled one
    discards its eventual result and exits) — and the engine receives the
    same typed ``BackendWorkerError`` a dead worker produces, flowing
    through the existing failover/error-isolation machinery. A dispatch
    that truly never completes leaks exactly one daemon thread: the price
    of one epoch, not the engine.
    """

    NODE = "<stalled>"

    # A dispatch family's FIRST call usually carries an XLA compile, which
    # can legitimately dwarf a steady-state dispatch — the first call per
    # op gets this multiple of the bound so a cold compile never reads as
    # a stall (the engine's bucketed shapes keep the family set small, so
    # the grace is paid a handful of times, early).
    FIRST_CALL_GRACE = 10.0

    def __init__(self, stall_s: float, on_stall=None):
        self.stall_s = float(stall_s)
        self.on_stall = on_stall
        self.stalls = 0
        self._cv = threading.Condition()
        self._stop = False
        self._gen = 0
        self._job = None  # (gen, fn) awaiting pickup
        self._done: dict[int, tuple[bool, object]] = {}
        self._worker: threading.Thread | None = None
        # Ops that have completed a dispatch at the 1x bound at least once.
        # NOTE the grace is per OP NAME, not per compiled shape: a new
        # shape bucket appearing mid-run (an 8k prompt after short warmup)
        # recompiles under the 1x bound — set ``epoch_stall_s`` comfortably
        # above your worst-case compile; the grace only softens cold start.
        # A stall re-grants the op's grace so a retry blocking on a still-
        # in-progress compile does not cascade into repeated abandonments.
        self._seen_ops: set[str] = set()

    # ---- engine side -----------------------------------------------------

    def call(self, fn, op: str, rid: str = ""):
        from cake_tpu.runtime.batch_backend import BackendWorkerError

        with self._cv:
            self._gen += 1
            gen = self._gen
            self._job = (gen, fn)
            if self._worker is None:
                self._worker = threading.Thread(
                    target=self._run, name="stall-guard", daemon=True
                )
                self._worker.start()
            self._cv.notify_all()
            bound = self.stall_s * (
                1.0 if op in self._seen_ops else self.FIRST_CALL_GRACE
            )
            self._seen_ops.add(op)
            deadline = time.monotonic() + bound
            while gen not in self._done and not self._stop:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._cv.wait(timeout=left)
            if gen in self._done:
                ok, val = self._done.pop(gen)
                if ok:
                    return val
                raise val
            if self._stop:
                # Shutdown woke the wait, not a stall: surface the same
                # typed error (the epoch unwinds through isolation and the
                # scheduler loop exits on its own stop flag) WITHOUT the
                # stall bookkeeping — a plain stop() must not count as an
                # epoch stall in anyone's dashboards.
                self._job = None
                raise BackendWorkerError(self.NODE, op)
            # Stall: abandon the watchdog thread (it may still be inside the
            # hung dispatch; its late result is discarded) and surface the
            # worker-death error the isolation path already handles. The
            # op's first-call grace is re-granted: if this "stall" was
            # really a late recompile, the retry blocks on the SAME compile
            # and must not be abandoned again at the 1x bound.
            self._worker = None
            self._job = None
            self._seen_ops.discard(op)
            self.stalls += 1
        if self.on_stall is not None:
            self.on_stall(op)
        metrics.registry.counter(
            "cake_epoch_stalls_total",
            "Backend dispatches abandoned by the stuck-epoch watchdog "
            "(no progress within epoch_stall_s).",
        ).inc()
        metrics.flight.record("epoch-stall", rid, op=op, stall_s=bound)
        from cake_tpu.obs.timeline import timeline

        timeline.instant(
            "epoch-stall", rid=rid or None, track="engine",
            args={"op": op, "stall_s": bound},
        )
        raise BackendWorkerError(self.NODE, op)

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._worker = None
            self._cv.notify_all()

    # ---- watchdog thread -------------------------------------------------

    def _run(self) -> None:
        me = threading.current_thread()
        while True:
            with self._cv:
                if self._worker is not me or self._stop:
                    return
                job, self._job = self._job, None
                if job is None:
                    # Bounded idle wait so an abandoned-then-forgotten
                    # worker never parks forever (the unbounded-wait rule's
                    # own discipline).
                    self._cv.wait(timeout=0.5)
                    continue
            gen, fn = job
            try:
                result = (True, fn())
            except BaseException as e:  # noqa: BLE001 — relayed to the caller
                result = (False, e)
            with self._cv:
                if self._worker is not me:
                    # The engine gave up on this dispatch while it ran: the
                    # stall RESOLVED late. Record it (operators watch this
                    # to tell a slow backend from a dead one) and retire.
                    metrics.registry.counter(
                        "cake_epoch_stalls_resolved_total",
                        "Stalled dispatches that completed after the "
                        "watchdog had already abandoned them.",
                    ).inc()
                    return
                self._done[gen] = result
                self._cv.notify_all()
