"""Persistent cross-request prefix cache: a radix tree over page chains.

PR 4 built the refcounted CoW seam in the page allocator
(models/llama/paged_cache.py ``fork``/``make_private``) but never wired it
into the engine: every epoch prefilled every prompt from scratch. This module
cashes the seam in. Finished prompts leave their prefix KV pages behind as a
**radix tree keyed on token-id chunks whose leaves own physical page chains**
in the paged pool; a later request whose prompt shares that prefix ``fork``s
the chain into its lane's block table and prefills only the uncached suffix
(runtime/serving.py admission + batch.paged_suffix_prefill). A shared system
prompt is prefilled once; every later request attaches to the same physical
pages — the redundant-shared-prefix prefill work the multi-core-NPU serving
study (PAPERS.md) measures is deleted, and the attention over the shared
chains is exactly the ragged-paged read path (PAPERS.md RPA) the pool
already serves.

Layout and alignment
--------------------
The lockstep batch layout is LEFT-padded: prompt token ``j`` of a lane with
pad ``P`` lives at absolute slot ``P + j``, i.e. at in-page offset
``(P + j) % page_size``. KV *values* are pad-invariant (rope positions are
relative), but their *packing into pages* is not — a chain recorded at pad
``P`` is byte-reusable only by lanes whose pad is congruent to ``P`` modulo
the page size. The cache therefore keeps one radix tree per **alignment
class** ``a = pad % page_size``: within a class, chains splice zero-copy;
across classes a prompt simply misses (and inserts into its own class).
Same-shaped traffic — the shared-system-prompt workload this subsystem
exists for — lands in one class and hits every time.

Tree shape
----------
Each node owns exactly ONE physical page and the token ids written into it
(up to ``page_size``, or ``page_size - a`` for a class's depth-0 nodes,
whose page also carries the sub-pad zero region). A root-to-node path is a
page chain covering a token prefix. Nodes hold one allocator reference per
page (``retain_pages``); forking a chain into a lane adds the lane's own
reference, so eviction can never free a page a live lane still maps. A
node's page may be PARTIAL (fewer tokens than its span — the tail of an
inserted prompt): forking it serves its tokens but leaves the lane's fresh
region mid-page, which the engine resolves with ``make_private`` + a device
page copy — the first divergent write is a copy-on-write split, never a
scribble on a shared page (the chaos tests pin survivor bit-identity).

Bounded + observable
--------------------
The cache is bounded in PAGES (``max_pages``): inserts evict least-recently
used unpinned leaves first (a node referenced by a live lane is pinned via
leases). The engine also evicts on demand — admission, join accounting, the
decode page-extend path, and the shed gate all count reclaimable cache
pages as available before refusing work. Everything is observable:
``cake_prefix_*`` counters and gauges, the shared-page gauge twin on the
``prefix`` timeline counter track, ``prefix-*`` flight events, and a
``prefix`` block on ``/stats``.

Locking: one RLock owns every tree/LRU/pin mutation. The allocator it
manipulates is only ever touched from inside that lock while the engine
thread holds the epoch (the allocator itself is engine-thread-owned); the
submit-side readers (shed gate, admission estimates) take the same lock.
"""

from __future__ import annotations

import itertools
import threading

from cake_tpu.models.llama.paged_cache import PageAllocator
from cake_tpu.obs.timeline import timeline
from cake_tpu.utils import metrics

_C_HIT = "cake_prefix_hits_total"
_C_MISS = "cake_prefix_misses_total"
_C_TOK = "cake_prefix_hit_tokens_total"
_C_INS = "cake_prefix_inserts_total"
_C_EVICT = "cake_prefix_evictions_total"
_G_PAGES = "cake_prefix_pages"
_G_NODES = "cake_prefix_nodes"


def _common_prefix(a, b) -> int:
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return i
    return n


class _Node:
    """One page chain link: a physical page + the token ids written into it."""

    __slots__ = (
        "page", "tokens", "span", "children", "parent", "last_used", "pins"
    )

    def __init__(self, page: int, tokens: tuple, span: int, parent):
        self.page = page
        self.tokens = tokens
        self.span = span  # token capacity of this page (ps, or ps - a at depth 0)
        self.children: list[_Node] = []
        self.parent = parent
        self.last_used = 0
        self.pins = 0

    @property
    def full(self) -> bool:
        return len(self.tokens) == self.span


class _Root:
    """Per-alignment-class tree root (owns no page)."""

    __slots__ = ("children",)

    def __init__(self):
        self.children: list[_Node] = []


class PrefixLease:
    """A live lane's pin on the chain it forked: while held, the matched
    nodes cannot be evicted (LRU passes over pinned nodes). Released by the
    engine when the lane's pages return to the pool; idempotent, and a
    no-op after ``clear()`` (generation check)."""

    __slots__ = ("_nodes", "_generation", "_released")

    def __init__(self, nodes: list[_Node], generation: int):
        self._nodes = nodes
        self._generation = generation
        self._released = False


class ForkPlan:
    """Result of a successful ``fork``: how much of the prompt the spliced
    chain serves, and whether the lane's fresh region starts mid-page (the
    engine must then ``make_private`` + copy that page before any write)."""

    __slots__ = ("served", "cow_logical", "lease")

    def __init__(self, served: int, cow_logical: int | None, lease: PrefixLease):
        self.served = served  # prompt tokens covered by forked pages
        self.cow_logical = cow_logical  # logical page needing a CoW split
        self.lease = lease


class PrefixCache:
    """Lock-owning, bounded, persistent prefix cache over the page pool."""

    def __init__(
        self,
        allocator: PageAllocator,
        *,
        max_pages: int,
        min_tokens: int = 0,
    ):
        if max_pages < 1:
            raise ValueError(f"max_pages must be >= 1, got {max_pages}")
        self.allocator = allocator
        self.page_size = allocator.page_size
        self.max_pages = max_pages
        self.min_tokens = max(0, min_tokens)
        self._lock = threading.RLock()
        self._roots: dict[int, _Root] = {}
        self._pages_held = 0
        self._n_nodes = 0
        self._generation = 0
        self._tick = itertools.count(1)
        self.counters = {
            "hits": 0, "misses": 0, "hit_tokens": 0,
            "inserts": 0, "evictions": 0, "clears": 0,
        }
        self._update_gauges()

    # ------------------------------------------------------------- internals

    def _span0(self, align: int) -> int:
        """Token capacity of a class's depth-0 page (it also holds the
        sub-pad zero region below the alignment offset)."""
        return self.page_size - align

    def _best_child(self, node, ids, offset: int, span: int):
        """The child sharing the longest token prefix with ``ids[offset:]``
        over this span. Children may share leading tokens (divergent inserts
        land as siblings), so the walk scans rather than hashes — fan-out per
        node is small in practice."""
        best, best_m = None, 0
        chunk = ids[offset: offset + span]
        for c in node.children:
            m = _common_prefix(c.tokens, chunk)
            if m > best_m:
                best, best_m = c, m
        return best, best_m

    def _bump(self, nodes: list[_Node]) -> None:
        t = next(self._tick)
        for n in nodes:
            n.last_used = t

    def _iter_nodes(self):
        stack = [c for r in self._roots.values() for c in r.children]
        while stack:
            n = stack.pop()
            yield n
            stack.extend(n.children)

    def _update_gauges(self) -> None:
        reg = metrics.registry
        reg.gauge(
            _G_PAGES, "KV pages held by the persistent prefix cache."
        ).set(self._pages_held)
        reg.gauge(_G_NODES, "Prefix-cache radix nodes (one page each).").set(
            self._n_nodes
        )
        # The shared-page gauge's timeline twin: cache footprint next to the
        # CoW-shared page count, on the span clock, so a Perfetto track shows
        # cache growth/eviction lining up with the epochs that caused it.
        timeline.counter(
            "prefix_pages",
            {
                "held": float(self._pages_held),
                "shared": float(self.allocator.pages_shared),
            },
            track="prefix",
        )

    # ------------------------------------------------------------------ read

    def match_tokens(self, ids: list[int], align: int) -> int:
        """Advisory longest-served-prefix length for admission accounting:
        how many tokens a ``fork`` at this alignment would cover right now.
        Read-only (no pins, no LRU bump) and capped at ``len(ids) - 1`` —
        the last prompt token is always recomputed so the epoch has a fresh
        hidden state to sample from."""
        with self._lock:
            root = self._roots.get(align % self.page_size)
            if root is None or len(ids) < 2:
                return 0
            served, offset, span = 0, 0, self._span0(align % self.page_size)
            cur: _Root | _Node = root
            cap = len(ids) - 1
            while served < cap:
                c, m = self._best_child(cur, ids, offset, span)
                if c is None or m == 0:
                    break
                take = min(m, cap - served)
                served += take
                if take < span or not c.full or m < len(c.tokens):
                    break
                offset += span
                cur = c
                span = self.page_size
            return served if served >= max(self.min_tokens, 1) else 0

    def radix_key(self, ids: list[int], align: int) -> tuple | None:
        """Transient grouping key for cache-aware admission ordering
        (runtime/serving.py ``_admit``): requests whose prompts extend the
        SAME cached chain — the same first radix node at this alignment
        class — share a key; a miss is None. Read-only (no pins, no LRU
        bump); the key is only meaningful within one scheduling decision
        (node identity is not stable across eviction)."""
        with self._lock:
            align %= self.page_size
            root = self._roots.get(align)
            if root is None or len(ids) < 2:
                return None
            c, m = self._best_child(root, ids, 0, self._span0(align))
            if c is None or m == 0:
                return None
            return (align, id(c))

    def reclaimable(self) -> int:
        """Pages eviction could free RIGHT NOW: unpinned-subtree nodes whose
        page has no reference besides the cache's own. The shed gate counts
        these as available before 503ing — a full-but-cold cache must never
        permanently shed (runtime/serving.py)."""
        with self._lock:
            total = 0

            def walk(node) -> bool:
                free_sub = node.pins == 0
                for c in node.children:
                    free_sub &= walk(c)
                nonlocal total
                if free_sub and self.allocator.refcount[node.page] == 1:
                    total += 1
                return free_sub

            for root in self._roots.values():
                for c in root.children:
                    walk(c)
            return total

    def stats(self) -> dict:
        with self._lock:
            return {
                "pages": self._pages_held,
                "max_pages": self.max_pages,
                "nodes": self._n_nodes,
                "alignment_classes": len(self._roots),
                "reclaimable_pages": self.reclaimable(),
                **self.counters,
            }

    # ------------------------------------------------------------------ fork

    def fork(
        self, lane: int, ids: list[int], pad: int, rid: str = ""
    ) -> ForkPlan | None:
        """Splice the longest cached chain matching ``ids`` into ``lane``'s
        block table (shared pages, +1 ref each) and pin it.

        Returns None on a miss (nothing mapped). On a hit, ``served`` prompt
        tokens are covered by the forked pages and the suffix prefill starts
        at absolute slot ``pad + served``; when that lands mid-page,
        ``cow_logical`` names the shared page the engine must ``make_private``
        (+ device copy) before the first divergent write.
        """
        align = pad % self.page_size
        with self._lock:
            root = self._roots.get(align)
            matched: list[_Node] = []
            served, offset, span = 0, 0, self._span0(align)
            cap = len(ids) - 1  # always recompute the last prompt token
            cur: _Root | _Node = root if root is not None else None
            while cur is not None and served < cap:
                c, m = self._best_child(cur, ids, offset, span)
                if c is None or m == 0:
                    break
                take = min(m, cap - served)
                matched.append(c)
                served += take
                if take < span or m < len(c.tokens) or not c.full:
                    break  # partial page coverage: chain ends mid-page
                offset += span
                cur = c
                span = self.page_size
            if served < max(self.min_tokens, 1):
                self.counters["misses"] += 1
                metrics.registry.counter(
                    _C_MISS, "Prompt admissions with no usable cached prefix."
                ).inc()
                return None
            first_logical = pad // self.page_size
            self.allocator.fork_chain(
                lane, [n.page for n in matched], first_logical
            )
            cow = (align + served) % self.page_size != 0
            cow_logical = (
                first_logical + len(matched) - 1 if cow else None
            )
            for n in matched:
                n.pins += 1
            self._bump(matched)
            lease = PrefixLease(matched, self._generation)
            self.counters["hits"] += 1
            self.counters["hit_tokens"] += served
            metrics.registry.counter(
                _C_HIT, "Prompt admissions served a cached prefix chain."
            ).inc()
            metrics.registry.counter(
                _C_TOK, "Prompt tokens served from cached prefix pages."
            ).inc(served)
            metrics.flight.record(
                "prefix-hit", rid, lane=lane, tokens=served,
                pages=len(matched), cow=bool(cow),
            )
            timeline.instant(
                "prefix-hit", rid=rid, track="prefix",
                args={"tokens": served, "pages": len(matched)},
            )
            self._update_gauges()
            return ForkPlan(served, cow_logical, lease)

    def release(self, lease: PrefixLease | None) -> None:
        """Unpin a fork's chain (engine: lane released its pages)."""
        if lease is None:
            return
        with self._lock:
            if lease._released or lease._generation != self._generation:
                return
            lease._released = True
            for n in lease._nodes:
                n.pins -= 1

    # ---------------------------------------------------------------- insert

    def insert(
        self, lane: int, ids: list[int], pad: int, rid: str = ""
    ) -> int:
        """Adopt a finished lane's prompt-prefix pages into the tree
        (zero-copy: +1 cache reference per newly adopted page; pages shared
        with an existing chain just refresh its LRU stamp). Returns the
        number of pages newly retained. Partial tail pages are cached too —
        a later insert providing MORE tokens for the same span replaces the
        partial page (readers holding forks of the old page are unaffected:
        refcounts keep it alive until they release)."""
        align = pad % self.page_size
        if len(ids) < max(self.min_tokens, 2):
            return 0
        with self._lock:
            root = self._roots.setdefault(align, _Root())
            adopted = 0
            offset, span = 0, self._span0(align)
            logical = pad // self.page_size
            cur: _Root | _Node = root
            path: list[_Node] = []
            while offset < len(ids):
                chunk = tuple(ids[offset: offset + span])
                phys = int(self.allocator.block_tables[lane][logical])
                if phys < 0:
                    break  # lane holds no storage here (shouldn't happen)
                c, m = self._best_child(cur, ids, offset, span)
                if c is not None and m == len(chunk) and len(c.tokens) >= m:
                    # Chunk already covered (possibly by a longer partial).
                    path.append(c)
                    if len(chunk) < span or not c.full:
                        break
                elif (
                    c is not None
                    and m == len(c.tokens)
                    and not c.full
                    and len(chunk) > m
                ):
                    # Extend a partial node: swap in the lane's page, which
                    # holds strictly more of this span.
                    self.allocator.retain_pages([phys])
                    self.allocator.release_pages([c.page])
                    c.page = phys
                    c.tokens = chunk
                    path.append(c)
                    adopted += 1
                    if not c.full:
                        break
                else:
                    # New branch (empty span, or divergence mid-span: the
                    # new chain lands as a sibling — duplicated shared bytes
                    # within one page are bounded and beat a device copy).
                    self.allocator.retain_pages([phys])
                    node = _Node(
                        phys, chunk, span,
                        cur if isinstance(cur, _Node) else None,
                    )
                    (cur.children).append(node)
                    self._n_nodes += 1
                    self._pages_held += 1
                    path.append(node)
                    adopted += 1
                    if not node.full:
                        break
                cur = path[-1]
                offset += span
                logical += 1
                span = self.page_size
            if not path:
                return 0
            self._bump(path)
            self.counters["inserts"] += 1
            metrics.registry.counter(
                _C_INS, "Prompt-prefix chains inserted/refreshed on finish."
            ).inc()
            metrics.flight.record(
                "prefix-insert", rid, lane=lane,
                pages=adopted, chain_pages=len(path),
            )
            self._evict_to_budget()
            self._update_gauges()
            return adopted

    # -------------------------------------------------------------- eviction

    def _evictable_leaves(self) -> list[_Node]:
        return [
            n for n in self._iter_nodes() if not n.children and n.pins == 0
        ]

    def _evict_one(self, node: _Node) -> int:
        """Drop one unpinned leaf; returns pages actually FREED (0 when a
        lane still maps the page — the ref drops but the bytes stay).
        Callers already hold the (reentrant) lock; taken again here so the
        invariant is locally checkable."""
        with self._lock:
            parent = node.parent
            siblings = (
                parent.children
                if parent is not None
                else self._roots_containing(node)
            )
            siblings.remove(node)
            free0 = self.allocator.pages_free
            self.allocator.release_pages([node.page])
            self._n_nodes -= 1
            self._pages_held -= 1
            self.counters["evictions"] += 1
            metrics.registry.counter(
                _C_EVICT, "Prefix-cache nodes evicted (LRU or on-demand)."
            ).inc()
            return self.allocator.pages_free - free0

    def _roots_containing(self, node: _Node) -> list[_Node]:
        for root in self._roots.values():
            if node in root.children:
                return root.children
        raise ValueError("orphan prefix-cache node")

    def _evict_to_budget(self) -> None:
        while self._pages_held > self.max_pages:
            leaves = self._evictable_leaves()
            if not leaves:
                return  # everything pinned: live lanes hold the budget
            self._evict_one(min(leaves, key=lambda n: n.last_used))

    def reclaim(self, n_pages: int, rid: str = "") -> int:
        """Evict LRU-first until ``n_pages`` pages actually hit the free
        list (or nothing evictable remains). The engine calls this from
        admission, join accounting, the decode page-extend path, and the
        shed gate — pool pressure reclaims cold cache before degrading
        traffic. Returns pages freed."""
        if n_pages <= 0:
            return 0
        with self._lock:
            freed = 0
            while freed < n_pages:
                leaves = self._evictable_leaves()
                if not leaves:
                    break
                freed += self._evict_one(
                    min(leaves, key=lambda n: n.last_used)
                )
            if freed:
                metrics.flight.record(
                    "prefix-evict", rid, pages=freed, wanted=n_pages
                )
                timeline.instant(
                    "prefix-evict", track="prefix", args={"pages": freed}
                )
            self._update_gauges()
            return freed

    def clear(self, reason: str = "") -> int:
        """Drop every chain (pool rebuild, engine shutdown, tests). Pages
        still mapped by live lanes survive via their lane refs; everything
        else returns to the free list. Outstanding leases die with the
        generation."""
        with self._lock:
            free0 = self.allocator.pages_free
            pages = [n.page for n in self._iter_nodes()]
            if pages:
                self.allocator.release_pages(pages)
            self._roots = {}
            self._pages_held = 0
            self._n_nodes = 0
            self._generation += 1
            self.counters["clears"] += 1
            freed = self.allocator.pages_free - free0
            if pages:
                metrics.flight.record(
                    "prefix-clear", pages=len(pages), freed=freed,
                    reason=reason,
                )
            self._update_gauges()
            return freed
