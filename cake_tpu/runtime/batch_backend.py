"""Batch execution backends: the device seam under the serving engine.

The reference serves one request at a time behind a global lock
(api/mod.rs:76); runtime/serving.py replaces that with a continuous-batching
engine. This module is the engine's ONE device interface — four operations
(init_kv / prefill / decode / join) over the left-padded lockstep batch
layout (models/llama/batch.py) — with three implementations:

  * ``LocalBatchBackend`` — single-device, full params resident (the round-2
    behavior, now behind the seam).
  * ``TPBatchBackend`` — Megatron tensor parallelism: every batch op runs as
    one ``shard_map`` over a 1-D tp mesh (heads/intermediate split, psums at
    the two partial-sum points), the same sharding recipe as
    parallel/tensor.TensorParallelRunner but over the pad-aware batched
    bodies (batch.batched_blocks_forward).
  * ``PipelineBatchBackend`` — in-mesh pipeline parallelism (optionally
    x tp on a 2-D mesh): the stage-loop + ppermute walk of
    parallel/pipeline.PipelineRunner, again over the pad-aware batched
    bodies with ragged-stage valid masks; decode defaults to the 1F1B
    interleaved microbatch walk (see the class docstring).
  * ``DistributedBatchBackend`` — the TCP topology (master <-> workers over
    StageClient spans): the lockstep layout rides a ``batch`` extension of
    the FORWARD header; workers run the same pad-aware bodies
    (batch.make_lockstep_range_ops) on their ranges.

This is what makes ``--api-batch`` compose with ``--backend mesh``, ``--tp``,
AND ``--backend tcp``: continuous batching and model distribution were
mutually exclusive in round 2 (the engine closed over the local model); now
the engine drives whichever backend owns the devices, token-exactly
(tests/test_serving.py pins engine-over-tp/pipeline against
engine-over-local; tests/test_distributed_batch.py pins the live-cluster
TCP path).

All four share the sampling arithmetic (fused.sample_step) and the batch
layout helpers, so the per-row PRNG/ring/first-token arithmetic exists once
regardless of backend.
"""

from __future__ import annotations

import functools
import uuid
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.batch import (
    _decode_fn,
    _prefill_jit,
    batched_blocks_forward,
    batched_prefill,
    decode_positions,
    make_lockstep_range_ops,
    prefill_positions,
)
from cake_tpu.models.llama.cache import KVCache, init_cache
from cake_tpu.models.llama.paged_cache import (
    PageAllocator,
    init_paged_cache,
)
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.fused import sample_step, sampled_decode_scan
from cake_tpu.ops.rope import model_rope_tables
from cake_tpu.parallel.pipeline import STAGE_AXIS, place_stage_model
from cake_tpu.parallel.tensor import (
    TP_AXIS,
    checked_shard_map,
    place_tp_model,
    validate_tp,
)

# Compiled fused-decode scans per (n_steps, sampling knobs): bounded like the
# local path's lru_cache'd _decode_fn — per-request sampling overrides on a
# long-lived server must not leak executables without bound.
_DECODE_CACHE_MAX = 16


class BackendWorkerError(RuntimeError):
    """A backend op failed because a worker (or an injected fault standing in
    for one) died after the retry/replay budget was exhausted.

    The serving engine treats this as a RECOVERABLE serving event, not a bug:
    the epoch's live streams finish with ``finish_reason="error"`` (pages
    released, lanes recycled), already-finished co-batched streams are
    untouched, and the engine keeps serving the queue
    (runtime/serving.py failure isolation). Any other exception still
    surfaces to every consumer as a raised error.
    """

    def __init__(self, node: str, op: str, cause: Exception | None = None):
        super().__init__(
            f"worker {node!r} failed during batch {op} "
            f"({cause if cause is not None else 'fault injected'})"
        )
        self.node = node
        self.op = op


def _note_fusion_kernels(backend, s) -> None:
    """Timeline breadcrumbs for the decode-fusion kernel family (the PR 9
    ``kernel:<op>`` convention): one ``kernel:fused_<name>`` instant per
    enabled fusion at every decode dispatch, carrying the impl the fused
    entry will actually resolve — plus a ONE-TIME ``kernel-fallback``
    flight event when the sampling tail wants pallas but must take the XLA
    sort path (top-p set, or an untileable vocab). The ``make trace-smoke
    --fused-pallas`` gate reads these instants, so a silent fallback to the
    unfused path fails CI instead of shipping."""
    from cake_tpu.obs.timeline import timeline
    from cake_tpu.ops.fuse import resolve_fusion
    from cake_tpu.ops.pallas.fused_ingest import ingest_supported
    from cake_tpu.ops.pallas.fused_sample_tail import sample_tail_supported
    from cake_tpu.utils import metrics

    fusions, fimpl = resolve_fusion(
        backend.config, getattr(backend, "allow_pallas", True)
    )
    if not fusions:
        return
    # Per-fusion ACTUAL dispatch, not just the resolved wish: a breadcrumb
    # claiming impl=pallas while the twin ran would let the trace-smoke gate
    # pass on a config where no kernel can engage. Norm: the decode sites
    # need a PLAIN 128-lane-tileable projection (quantized trees keep the
    # twin); ingest: additionally gated off for q_norm (Qwen3) trees and
    # unfused (no wqkv) weights; tail: top_p / untileable vocab take the
    # sort twin (fused.sample_step downgrades through the same
    # sample_tail_supported rule, so note and dispatch cannot drift).
    lp = getattr(backend, "params", {}).get("layers", {})
    wqkv = lp.get("wqkv")
    norm_ok = (
        isinstance(wqkv, jnp.ndarray) and wqkv.shape[-1] % 128 == 0
    )
    ingest_ok = (
        wqkv is not None
        and "q_norm" not in lp
        and ingest_supported(backend.config.head_dim)
    )
    impls = {
        "fused_norm_matmul": ("norm", fimpl if norm_ok else "xla"),
        "fused_qkv_ingest": ("ingest", fimpl if ingest_ok else "xla"),
        "fused_sample_tail": (
            "tail",
            fimpl
            if sample_tail_supported(backend.config.vocab_size, s.top_p)
            else "xla",
        ),
    }
    for kernel, (name, impl) in impls.items():
        if name not in fusions:
            continue
        if (
            impl != fimpl
            and fimpl == "pallas"
            and not getattr(backend, "_fusion_fallback_noted", False)
        ):
            backend._fusion_fallback_noted = True
            metrics.flight.record(
                "kernel-fallback", op=kernel,
                reason=(
                    "top_p needs the XLA sort path"
                    if kernel == "fused_sample_tail" and s.top_p is not None
                    else "shape not a multiple of the 128-lane tile"
                ),
            )
        timeline.instant(
            f"kernel:{kernel}", track="engine", args={"impl": impl}
        )


def _cache_get_or_build(cache: OrderedDict, key, build):
    fn = cache.get(key)
    if fn is None:
        fn = cache[key] = build()
        while len(cache) > _DECODE_CACHE_MAX:
            cache.popitem(last=False)
    else:
        cache.move_to_end(key)
    return fn


@functools.lru_cache(maxsize=32)
def _local_join_fn(config, width, max_seq_len, cache_dtype):
    """Jit one continuous-batching join: single-row prefill whose prompt ends
    at the epoch's shared slot, scattered wholesale into the free lane's KV
    row (stale lane contents are fully replaced). One compile per 64-bucketed
    window width."""

    def run(params, kv, tokens, pads1, ends1, lane):
        kv_row = init_cache(
            config.num_hidden_layers,
            1,
            max_seq_len,
            config.num_key_value_heads,
            config.head_dim,
            cache_dtype,
        )
        logits, kv_row = batched_prefill(
            params, tokens, kv_row, pads1, config, ends=ends1, seq_len=ends1[0]
        )
        k = jax.lax.dynamic_update_slice(kv.k, kv_row.k, (0, lane, 0, 0, 0))
        v = jax.lax.dynamic_update_slice(kv.v, kv_row.v, (0, lane, 0, 0, 0))
        return logits, KVCache(k=k, v=v)

    from cake_tpu.obs.jitwatch import tracked_jit

    return tracked_jit(
        run, name=f"batch.join[w={width}]", donate_argnums=(1,)
    )


class LocalBatchBackend:
    """Single-device batch ops: the engine's default."""

    def __init__(
        self,
        config: LlamaConfig,
        params: M.Params,
        *,
        max_seq_len: int,
        cache_dtype: jnp.dtype,
    ):
        from cake_tpu.ops.fuse import fuse_params

        self.config = config
        self.params = fuse_params(params)  # ops/fuse.py, column-identical
        self.max_seq_len = max_seq_len
        self.cache_dtype = cache_dtype

    def init_kv(self, b: int) -> KVCache:
        return init_cache(
            self.config.num_hidden_layers,
            b,
            self.max_seq_len,
            self.config.num_key_value_heads,
            self.config.head_dim,
            self.cache_dtype,
        )

    def prefill(self, tokens, kv, pads, ends=None):
        # ``ends`` (per-row absolute end slot < width) serves failover
        # migration (runtime/serving.py): live streams' accumulated tokens
        # re-prefill into a window ENDING at the epoch's shared slot.
        kw = {}
        if ends is not None:
            ends = jnp.asarray(ends, jnp.int32)
            kw = {"ends": ends, "seq_len": ends[0]}
        return _prefill_jit(
            self.params, jnp.asarray(tokens), kv, jnp.asarray(pads),
            self.config, **kw,
        )

    def decode(self, kv, tok, slot, pads, keys, ring, ring_idx, n, s):
        _note_fusion_kernels(self, s)
        fn = _decode_fn(
            self.config, self.max_seq_len, n,
            s.temperature, s.top_k, s.top_p, s.repeat_penalty,
        )
        return fn(
            self.params, kv, tok, jnp.int32(slot), pads, keys, ring, ring_idx
        )

    def join(self, kv, row_tokens, pads1, ends1, lane):
        fn = _local_join_fn(
            self.config, row_tokens.shape[1], self.max_seq_len, self.cache_dtype
        )
        return fn(
            self.params, kv, jnp.asarray(row_tokens), pads1, ends1,
            jnp.int32(lane),
        )

    # Speculative verify (engine-side batched prompt-lookup decoding): the
    # presence of these two methods is the engine's capability gate.

    def verify_greedy(self, kv, tokens, slot, pads):
        from cake_tpu.models.llama.batch import _verify_greedy_fn

        fn = _verify_greedy_fn(self.config, tokens.shape[1])
        return fn(
            self.params, jnp.asarray(tokens), kv, jnp.asarray(pads),
            jnp.int32(slot),
        )

    def verify_sampled(self, kv, tokens, slot, pads, drafts, n_drafts, keys, s):
        from cake_tpu.models.llama.batch import _verify_sampled_fn

        fn = _verify_sampled_fn(
            self.config, tokens.shape[1], s.temperature, s.top_k, s.top_p
        )
        return fn(
            self.params, jnp.asarray(tokens), kv, jnp.asarray(pads),
            jnp.int32(slot), jnp.asarray(drafts),
            jnp.asarray(n_drafts, jnp.int32), keys,
        )


@functools.lru_cache(maxsize=32)
def _paged_join_fn(config, width, allow_pallas=True):
    """Jit one PAGED continuous-batching join: the single-row prefill writes
    straight through the joining lane's block-table row into the shared pool
    (no detached row cache, no wholesale scatter — the lane's freshly mapped
    pages ARE the destination). One compile per 64-bucketed window width."""
    from cake_tpu.models.llama.batch import paged_prefill

    def run(params, kv, tokens, pads1, ends1, lane_table):
        return paged_prefill(
            params, tokens, kv, pads1, lane_table, config,
            ends=ends1, seq_len=ends1[0], allow_pallas=allow_pallas,
        )

    from cake_tpu.obs.jitwatch import tracked_jit

    return tracked_jit(
        run, name=f"batch.paged_join[w={width}]", donate_argnums=(1,)
    )


class PagedLocalBackend:
    """Single-device batch ops over the paged KV pool (``kv_mode="paged"``).

    Same four-operation seam as LocalBatchBackend, with storage routed
    through a page pool + host-side PageAllocator (models/llama/paged_cache):
    HBM is committed per live page, not per ``batch * max_seq`` strip, so the
    pool can be sized well below the dense footprint and the serving engine
    admits by free pages (runtime/serving.py). The engine owns the allocation
    protocol (map at layout/join, extend at page boundaries, release on
    finish); this backend reads ``self.allocator.block_tables`` at each
    dispatch and ships it as a small traced int32 operand.

    With a prefix cache attached (``attach_prefix_cache``,
    runtime/prefix_cache.py) the pool becomes PERSISTENT: ``init_kv`` keeps
    the retained device pool (``retain_kv`` at epoch end) and releases only
    the lane mappings, so cached chains' pages — and their bytes — survive
    across epochs; ``suffix_prefill`` computes just a prompt's uncached tail
    over forked chains, and ``cow_copy`` is the device half of the
    make-private split.

    Speculative verify runs through the paged cached-chunk arithmetic
    (batch.paged_verify_logits — the same grids as ``suffix_prefill``), so
    the engine's capability gate no longer auto-disables speculation under
    ``kv_mode="paged"``.

    **Bounded capacity** (``set_epoch_capacity``): the serving engine
    computes ONE bucketed live capacity per epoch — enough slots for every
    admitted row's maximum reach plus a chunk of slack — and every dispatch
    slices the block-table operand to it. Attention grids, position masks,
    and the XLA gather view then cover the live capacity instead of the
    padded ``max_seq`` table width. The capacity is deliberately backend
    STATE set once per epoch, not a per-op argument: every cache-enabled
    prefill (epoch suffix prefill, joins, failover re-prefills) MUST run
    under the same capacity or the bit-identity chain across joins and
    failover breaks at the ulp level on real hardware (reduction shapes
    change with the gather width) — and a per-op "local" capacity smaller
    than the epoch's silently truncates live keys
    (tests/test_paged_prefill.py pins the trap). None = the full table.
    """

    kv_mode = "paged"

    def __init__(
        self,
        config: LlamaConfig,
        params: M.Params,
        *,
        max_seq_len: int,
        cache_dtype: jnp.dtype,
        page_size: int = 128,
        max_pages: int | None = None,
        page_reserve: int = 1,
        allow_pallas: bool = True,
    ):
        from cake_tpu.ops.fuse import fuse_params

        self.config = config
        self.params = fuse_params(params)
        self.max_seq_len = max_seq_len
        self.cache_dtype = cache_dtype
        if page_size <= 0:
            raise ValueError(f"page_size must be positive, got {page_size}")
        self.page_size = page_size
        self.pages_per_seq = -(-max_seq_len // page_size)
        # The paged analogue of the dense cache's SEQ_MULTIPLE padding: every
        # position grid sizes to the block-table capacity.
        self.padded_seq = self.pages_per_seq * page_size
        # Default pool = one dense-equivalent 8-lane footprint; servers size
        # it DOWN (that is the capacity win) via ServeConfig.max_pages.
        self.max_pages = max_pages or 8 * self.pages_per_seq
        self.allocator = PageAllocator(
            self.max_pages, page_size, batch=1,
            max_pages_per_seq=self.pages_per_seq,
            reserve_pages=page_reserve,
        )
        self.prefix_cache = None
        self._retained_kv = None
        self.allow_pallas = allow_pallas
        # Epoch-bounded table capacity in PAGES (None = full table).
        self._cap_pages: int | None = None
        self._fallback_noted = False

    # --------------------------------------------------- kernel dispatch

    def kernel_impl(self) -> str:
        """Which attention impl the paged prefill/verify family will use:
        "pallas" iff the resolved attention_impl wants it AND the pool
        layout supports the kernels (page = whole lane tiles)."""
        from cake_tpu.ops.pallas.paged_prefill import paged_kernel_supported

        wants = (
            self.allow_pallas
            and M.resolve_attention_impl(self.config.attention_impl)
            == "pallas"
        )
        if not wants:
            return "xla"
        if not paged_kernel_supported(self.page_size):
            return "fallback"
        return "pallas"

    def _kernel_note(self, op: str) -> None:
        """Timeline breadcrumb per paged dispatch (the trace-smoke gate
        reads these to prove the kernel path engaged) plus a ONE-TIME
        ``kernel-fallback`` flight event when a paged path silently
        downgrades to XLA (attention_impl wanted pallas, pool layout says
        no)."""
        from cake_tpu.obs.timeline import timeline
        from cake_tpu.utils import metrics

        impl = self.kernel_impl()
        if impl == "fallback" and not self._fallback_noted:
            self._fallback_noted = True
            metrics.flight.record(
                "kernel-fallback", op=op, page_size=self.page_size,
                reason="page_size not a multiple of the 128-lane tile",
            )
        timeline.instant(
            f"kernel:{op}", track="engine",
            args={"impl": "pallas" if impl == "pallas" else "xla"},
        )

    # --------------------------------------------------- bounded capacity

    def set_epoch_capacity(self, capacity_slots: int | None) -> None:
        """Bound every dispatch's block-table operand to ``capacity_slots``
        (rounded up to whole pages); None restores the full table. The
        serving engine calls this ONCE per epoch — or once per SEGMENT
        under the continuous scheduler, whose per-step dispatches (joins,
        restores of spilled lanes, decode chunks) all run under the same
        bound — see the class docstring for why the capacity must not vary
        within one."""
        if capacity_slots is None:
            self._cap_pages = None
            return
        pages = -(-int(capacity_slots) // self.page_size)
        self._cap_pages = max(1, min(pages, self.pages_per_seq))

    def capacity_slots(self) -> int:
        """The slot capacity every position grid currently sizes to."""
        if self._cap_pages is None:
            return self.padded_seq
        return self._cap_pages * self.page_size

    def _check_write_bound(self, op: str, end_slot: int) -> None:
        # A write past the sliced table would DROP silently (take_along_axis
        # fill) and corrupt the stream — fail loudly instead: the engine's
        # capacity formula is supposed to make this unreachable.
        if end_slot > self.capacity_slots():
            raise ValueError(
                f"paged {op} writes through slot {end_slot} but the epoch "
                f"capacity is {self.capacity_slots()} slots — the engine's "
                "one-capacity-per-epoch bound was violated"
            )

    def _tables(self) -> jnp.ndarray:
        tables = self.allocator.block_tables
        if self._cap_pages is not None:
            tables = tables[:, : self._cap_pages]
        return jnp.asarray(tables)

    def _lane_table(self, lane: int) -> jnp.ndarray:
        tables = self.allocator.block_tables[lane : lane + 1]
        if self._cap_pages is not None:
            tables = tables[:, : self._cap_pages]
        return jnp.asarray(tables)

    def attach_prefix_cache(self, cache) -> None:
        """Switch the pool to PERSISTENT mode for the engine's prefix cache
        (runtime/prefix_cache.py): epochs stop zeroing it."""
        self.prefix_cache = cache

    def retain_kv(self, kv) -> None:
        """Epoch end (persistent mode): keep the final pool buffer so the
        next epoch's ``init_kv`` hands it back with cached chains intact."""
        self._retained_kv = kv

    def drop_retained_kv(self) -> None:
        self._retained_kv = None

    def init_kv(self, b: int):
        """New-epoch pool. Default: allocator reset + fresh zeroed pages.
        Persistent (prefix cache attached): lane mappings release — cached
        chains keep their pages — and the retained device pool is reused;
        the pool is rebuilt zeroed only when nothing was retained (first
        epoch, or a failed one that dropped the buffer — the engine clears
        the cache on that path, so chains never outlive their bytes). The
        pool's HBM footprint is ``max_pages`` pages regardless of ``b`` —
        lanes only consume pages the engine actually maps."""
        if self.prefix_cache is not None:
            self.allocator.release_lanes(batch=b)
            kv, self._retained_kv = self._retained_kv, None
            if kv is not None:
                return kv
        else:
            self.allocator.reset(batch=b)
        return init_paged_cache(
            self.config.num_hidden_layers,
            self.max_pages,
            self.config.num_key_value_heads,
            self.page_size,
            self.config.head_dim,
            self.cache_dtype,
        )

    def prefill(self, tokens, kv, pads, ends=None):
        from cake_tpu.models.llama.batch import _paged_prefill_jit

        kw = {}
        if ends is not None:
            ends = jnp.asarray(ends, jnp.int32)
            kw = {"ends": ends, "seq_len": ends[0]}
        self._kernel_note("prefill")
        self._check_write_bound("prefill", int(jnp.shape(tokens)[1]))
        return _paged_prefill_jit(
            self.params, jnp.asarray(tokens), kv, jnp.asarray(pads),
            self._tables(), self.config,
            allow_pallas=self.allow_pallas, **kw,
        )

    def suffix_prefill(self, tokens, kv, pads, write_starts, start):
        """Prefix-cache prefill: compute only the window [start, start + W)
        over the live pool prefix, each row's writes below its fresh
        threshold dropped (batch.paged_suffix_prefill). EVERY cache-enabled
        prefill routes here — cold epochs included, with start at the
        youngest pad — so warm and cold runs share ONE attention arithmetic
        and greedy streams stay bit-identical (the fresh-chunk path's
        reduction differs at the ulp level). One compile per 64-bucketed
        width."""
        from cake_tpu.models.llama.batch import _paged_suffix_jit

        self._kernel_note("suffix_prefill")
        self._check_write_bound(
            "suffix_prefill", int(start) + int(jnp.shape(tokens)[1])
        )
        return _paged_suffix_jit(
            self.params, jnp.asarray(tokens), kv,
            jnp.asarray(pads, jnp.int32),
            jnp.asarray(write_starts, jnp.int32),
            self._tables(), self.config, jnp.int32(start),
            allow_pallas=self.allow_pallas,
        )

    def suffix_join(self, kv, row_tokens, pads1, write_starts1, lane, start):
        """The continuous-batching join on the prefix-cache arithmetic: one
        row's window [start, slot) over ITS lane table, same cached-chunk
        attention as suffix_prefill — so a cache-enabled join is
        bit-identical whether its prefix was forked (writes below the
        threshold drop) or computed fresh. The lane table is sliced to the
        SAME epoch capacity as every other dispatch (the one-capacity rule,
        class docstring)."""
        from cake_tpu.models.llama.batch import _paged_suffix_jit

        self._kernel_note("suffix_join")
        self._check_write_bound(
            "suffix_join", int(start) + int(jnp.shape(row_tokens)[1])
        )
        return _paged_suffix_jit(
            self.params, jnp.asarray(row_tokens), kv,
            jnp.asarray(pads1, jnp.int32),
            jnp.asarray(write_starts1, jnp.int32),
            self._lane_table(lane), self.config, jnp.int32(start),
            allow_pallas=self.allow_pallas,
        )

    def cow_copy(self, kv, src: list[int], dst: list[int]):
        """Device half of the copy-on-write split: duplicate shared pages
        before a lane's first divergent write (paged_cache.copy_pages)."""
        from cake_tpu.models.llama.paged_cache import copy_pages

        return copy_pages(
            kv, np.asarray(src, np.int32), np.asarray(dst, np.int32)
        )

    def decode(self, kv, tok, slot, pads, keys, ring, ring_idx, n, s):
        from cake_tpu.models.llama.batch import _paged_decode_fn

        self._kernel_note("decode")
        _note_fusion_kernels(self, s)
        self._check_write_bound("decode", int(slot) + n)
        # Position grids size to the epoch capacity, not the padded max_seq
        # — the decode twin of the bounded gather view (one compile per
        # capacity bucket; steady state within an epoch never retraces).
        fn = _paged_decode_fn(
            self.config, self.capacity_slots(), n,
            s.temperature, s.top_k, s.top_p, s.repeat_penalty,
            allow_pallas=self.allow_pallas,
        )
        return fn(
            self.params, kv, tok, jnp.int32(slot), pads, self._tables(),
            keys, ring, ring_idx,
        )

    def join(self, kv, row_tokens, pads1, ends1, lane):
        self._kernel_note("join")
        self._check_write_bound("join", int(np.asarray(ends1).max()))
        fn = _paged_join_fn(
            self.config, row_tokens.shape[1], self.allow_pallas
        )
        return fn(
            self.params, kv, jnp.asarray(row_tokens), pads1, ends1,
            self._lane_table(lane),
        )

    # Speculative verify through the paged cached-chunk arithmetic — the
    # presence of these two methods is the engine's capability gate, so
    # defining them is what turns speculation back ON under kv_mode="paged".

    def verify_greedy(self, kv, tokens, slot, pads):
        from cake_tpu.models.llama.batch import _paged_verify_greedy_fn

        self._kernel_note("verify")
        self._check_write_bound("verify", int(slot) + tokens.shape[1])
        fn = _paged_verify_greedy_fn(
            self.config, tokens.shape[1], self.allow_pallas
        )
        return fn(
            self.params, jnp.asarray(tokens), kv, jnp.asarray(pads),
            jnp.int32(slot), self._tables(),
        )

    def verify_sampled(self, kv, tokens, slot, pads, drafts, n_drafts, keys, s):
        from cake_tpu.models.llama.batch import _paged_verify_sampled_fn

        self._kernel_note("verify")
        self._check_write_bound("verify", int(slot) + tokens.shape[1])
        fn = _paged_verify_sampled_fn(
            self.config, tokens.shape[1], s.temperature, s.top_k, s.top_p,
            self.allow_pallas,
        )
        return fn(
            self.params, jnp.asarray(tokens), kv, jnp.asarray(pads),
            jnp.int32(slot), self._tables(), jnp.asarray(drafts),
            jnp.asarray(n_drafts, jnp.int32), keys,
        )


class TPBatchBackend:
    """Tensor-parallel batch ops: one shard_map per op over a 1-D tp mesh.

    Layer weights shard per parallel/tensor.layer_partition_specs (Megatron
    column/row + expert axis for MoE); KV heads shard with their
    projections; the head/embed replicate. The batched bodies themselves
    come from models/llama/batch.py with ``tp_axis`` threading the psums —
    numerics are the local path's, shard count only changes the reduction
    order.
    """

    def __init__(
        self,
        config: LlamaConfig,
        params: M.Params,
        *,
        tp: int | None = None,
        mesh: Mesh | None = None,
        max_seq_len: int,
        cache_dtype: jnp.dtype,
    ):
        if mesh is None:
            devs = jax.devices()
            tp = tp or len(devs)
            if len(devs) < tp:
                raise ValueError(f"tp={tp} needs {tp} devices, have {len(devs)}")
            mesh = Mesh(np.array(devs[:tp]), (TP_AXIS,))
        self.mesh = mesh
        self.tp = mesh.shape[TP_AXIS]
        validate_tp(config, self.tp)
        self.config = config
        self.max_seq_len = max_seq_len
        self.cache_dtype = cache_dtype

        self._layer_specs, self.layer_params, self.head_params = place_tp_model(
            config, params, mesh
        )
        self._kv_spec = P(None, None, TP_AXIS)
        self._rope = model_rope_tables(config, max_seq_len)
        self._finish_init()

    def _finish_init(self) -> None:
        self._prefill = self._build_prefill()
        self._join = self._build_join()
        self._decode_cache: OrderedDict = OrderedDict()

    @classmethod
    def from_runner(cls, runner, *, max_seq_len: int, cache_dtype):
        """Adopt a TensorParallelRunner's already-placed shards (no second
        device_put of the weights) — the --api-batch + --tp CLI path."""
        self = cls.__new__(cls)
        self.mesh = runner.mesh
        self.tp = runner.tp
        self.config = runner.config
        self.max_seq_len = max_seq_len
        self.cache_dtype = cache_dtype
        self._layer_specs = runner._layer_specs
        self.layer_params = runner.layer_params
        self.head_params = runner.head_params
        self._kv_spec = P(None, None, TP_AXIS)
        self._rope = model_rope_tables(self.config, max_seq_len)
        self._finish_init()
        return self

    def init_kv(self, b: int) -> KVCache:
        kv = init_cache(
            self.config.num_hidden_layers,
            b,
            self.max_seq_len,
            self.config.num_key_value_heads,
            self.config.head_dim,
            self.cache_dtype,
        )
        return jax.device_put(kv, NamedSharding(self.mesh, self._kv_spec))

    # -- shared shard_mapped bodies ---------------------------------------

    def _mapped_prefill_body(self):
        cfg = self.config
        cos, sin = self._rope

        def body(head, layers, tokens, kv, pads, ends, seq_len):
            b, l = tokens.shape
            x = M.embed_tokens(head, tokens, cfg)
            q_pos, k_pos = prefill_positions(l, pads, ends)
            x, kv = batched_blocks_forward(
                layers, x, kv, cos, sin, q_pos, k_pos, cfg,
                decode=False, pads=pads, lengths=ends,
                write_pos=jnp.int32(0), tp_axis=TP_AXIS,
            )
            return M.head_forward(head, x, seq_len, cfg), kv

        return checked_shard_map(
            body,
            mesh=self.mesh,
            in_specs=(
                P(), self._layer_specs, P(),
                KVCache(k=self._kv_spec, v=self._kv_spec), P(), P(), P(),
            ),
            out_specs=(P(), KVCache(k=self._kv_spec, v=self._kv_spec)),
        )

    def _build_prefill(self):
        mapped = self._mapped_prefill_body()

        def run(head, layers, tokens, kv, pads, ends, seq_len):
            return mapped(head, layers, tokens, kv, pads, ends, seq_len)

        return jax.jit(run, donate_argnums=(3,))

    def prefill(self, tokens, kv, pads, ends=None):
        tokens = jnp.asarray(tokens)
        b, l = tokens.shape
        ends = (
            jnp.full((b,), l, jnp.int32)
            if ends is None
            else jnp.asarray(ends, jnp.int32)
        )
        return self._prefill(
            self.head_params, self.layer_params, tokens, kv,
            jnp.asarray(pads), ends, jnp.int32(l),
        )

    def _build_join(self):
        mapped = self._mapped_prefill_body()

        def run(head, layers, kv, tokens, pads1, ends1, lane):
            kv_row = init_cache(
                self.config.num_hidden_layers,
                1,
                self.max_seq_len,
                self.config.num_key_value_heads,
                self.config.head_dim,
                self.cache_dtype,
            )
            kv_row = jax.lax.with_sharding_constraint(
                kv_row, NamedSharding(self.mesh, self._kv_spec)
            )
            logits, kv_row = mapped(
                head, layers, tokens, kv_row, pads1, ends1, ends1[0]
            )
            k = jax.lax.dynamic_update_slice(kv.k, kv_row.k, (0, lane, 0, 0, 0))
            v = jax.lax.dynamic_update_slice(kv.v, kv_row.v, (0, lane, 0, 0, 0))
            return logits, KVCache(k=k, v=v)

        return jax.jit(run, donate_argnums=(2,))

    def join(self, kv, row_tokens, pads1, ends1, lane):
        return self._join(
            self.head_params, self.layer_params, kv,
            jnp.asarray(row_tokens), pads1, ends1, jnp.int32(lane),
        )

    def _forward_one(self, pads):
        """Pad-closure one-token step: shard_mapped, for the decode scan."""
        cfg = self.config
        cos, sin = self._rope
        head, layers = self.head_params, self.layer_params

        def body(head, layers, tok, kv, pads, slot):
            # The cache's PADDED length (SEQ_MULTIPLE rounding), not the user
            # max_seq_len — the mask grid must cover every physical slot.
            x = M.embed_tokens(head, tok, cfg)
            q_pos, k_pos, lengths = decode_positions(slot, pads, kv.k.shape[-2])
            x, kv = batched_blocks_forward(
                layers, x, kv, cos, sin, q_pos, k_pos, cfg,
                decode=True, pads=pads, lengths=lengths, write_pos=slot,
                tp_axis=TP_AXIS,
            )
            return M.head_forward(head, x, jnp.int32(1), cfg), kv

        mapped = checked_shard_map(
            body,
            mesh=self.mesh,
            in_specs=(
                P(), self._layer_specs, P(),
                KVCache(k=self._kv_spec, v=self._kv_spec), P(), P(),
            ),
            out_specs=(P(), KVCache(k=self._kv_spec, v=self._kv_spec)),
        )

        def forward_one(tok, kv, slot):
            return mapped(head, layers, tok[:, 0][:, None], kv, pads, slot)

        return forward_one

    def decode(self, kv, tok, slot, pads, keys, ring, ring_idx, n, s):
        knobs = (n, s.temperature, s.top_k, s.top_p, s.repeat_penalty)

        def build():
            # The sampling tail runs OUTSIDE the shard_mapped forward, so
            # the tail fusion (ops/pallas/fused_sample_tail.py) applies to
            # the tp backend exactly as to the local one.
            from cake_tpu.ops.fuse import resolve_fusion

            fusions, fimpl = resolve_fusion(self.config)
            tail_impl = fimpl if "tail" in fusions else None

            def run(kv, tok, slot, pads, keys, ring, ring_idx):
                return sampled_decode_scan(
                    self._forward_one(pads),
                    kv, tok, slot, keys, ring, ring_idx,
                    n_steps=n,
                    temperature=s.temperature,
                    top_k=s.top_k,
                    top_p=s.top_p,
                    repeat_penalty=s.repeat_penalty,
                    tail_impl=tail_impl,
                )

            return jax.jit(run, donate_argnums=(0,))

        fn = _cache_get_or_build(self._decode_cache, knobs, build)
        return fn(kv, tok, jnp.int32(slot), pads, keys, ring, ring_idx)

    # Speculative verify over the tp mesh: one shard_mapped cached-chunk
    # forward scores every draft position (MoE forced drop-free dense under
    # tp — batched_verify_logits); acceptance runs replicated on-device.

    def _verify_mapped(self):
        from cake_tpu.models.llama.batch import batched_verify_logits

        cfg = self.config

        def body(head, layers, tokens, kv, pads, slot):
            params = dict(head)
            params["layers"] = layers
            return batched_verify_logits(
                params, tokens, kv, pads, slot, cfg, tp_axis=TP_AXIS
            )

        return checked_shard_map(
            body,
            mesh=self.mesh,
            in_specs=(
                P(), self._layer_specs, P(),
                KVCache(k=self._kv_spec, v=self._kv_spec), P(), P(),
            ),
            out_specs=(P(), KVCache(k=self._kv_spec, v=self._kv_spec)),
        )

    def verify_greedy(self, kv, tokens, slot, pads):
        key = ("verify_greedy", tokens.shape[1])

        def build():
            from cake_tpu.models.llama.batch import verify_greedy_ids

            mapped = self._verify_mapped()

            def run(head, layers, tokens, kv, pads, slot):
                logits, kv = mapped(head, layers, tokens, kv, pads, slot)
                return verify_greedy_ids(logits), kv

            return jax.jit(run, donate_argnums=(3,))

        fn = _cache_get_or_build(self._decode_cache, key, build)
        return fn(
            self.head_params, self.layer_params, jnp.asarray(tokens), kv,
            jnp.asarray(pads), jnp.int32(slot),
        )

    def verify_sampled(self, kv, tokens, slot, pads, drafts, n_drafts, keys, s):
        key = (
            "verify_sampled", tokens.shape[1],
            s.temperature, s.top_k, s.top_p,
        )

        def build():
            from cake_tpu.models.llama.batch import verify_sampled_accept

            mapped = self._verify_mapped()

            def run(head, layers, tokens, kv, pads, slot, drafts, n_drafts, keys):
                logits, kv = mapped(head, layers, tokens, kv, pads, slot)
                n_accs, nxts, keys = verify_sampled_accept(
                    logits, drafts, n_drafts, keys,
                    s.temperature, s.top_k, s.top_p,
                )
                return n_accs, nxts, kv, keys

            return jax.jit(run, donate_argnums=(3,))

        fn = _cache_get_or_build(self._decode_cache, key, build)
        return fn(
            self.head_params, self.layer_params, jnp.asarray(tokens), kv,
            jnp.asarray(pads), jnp.int32(slot), jnp.asarray(drafts),
            jnp.asarray(n_drafts, jnp.int32), keys,
        )


class PipelineBatchBackend:
    """Pipelined (stage [x tp]) batch ops over an in-mesh stage walk.

    The stage loop + ppermute rotation of parallel/pipeline.PipelineRunner,
    with the pad-aware batched bodies per stage (ragged stages padded with
    inert layers, gated by the valid mask). One jitted SPMD computation per
    op.

    Decode has TWO walks:

      * serialized (the single-stream discipline, llama.rs:81-117): the whole
        batch advances one stage per wall-step — S-1 stages idle. Correct for
        one stream; wasteful for a serving batch.
      * **1F1B interleaved** (default when the batch divides by S and per-row
        keys are used): the batch splits into S microbatch GROUPS in
        staggered flight — at every wall-step each stage serves a different
        group, sampling rides the LAST stage so the fresh embedding ppermutes
        straight into stage 0 for that group's next token. N tokens for all
        groups take N*S + S - 1 wall-steps of 1/S-batch stage work instead of
        N*S wall-steps of full-batch work: per-device work per wall-step
        drops S-fold at equal token output, which is the pipelined serving
        throughput the serialized walk forfeits. Token streams are
        bit-identical to the serialized walk (same per-row PRNG splits, same
        penalty-ring arithmetic, same slots — pinned in
        tests/test_interleaved_pipeline.py, along with the measured
        per-device compiled-FLOPs drop).
        KV stays the shared full-batch cache: groups read/write their row
        window in place (batch.batched_blocks_forward row_offset mode).
    """

    def __init__(
        self,
        config: LlamaConfig,
        params: M.Params,
        boundaries: list[tuple[int, int]],
        *,
        tp: int = 1,
        mesh: Mesh | None = None,
        max_seq_len: int,
        cache_dtype: jnp.dtype,
        interleave: bool = True,
    ):
        self.interleave = interleave
        self.config = config
        self.n_stages = len(boundaries)
        self.boundaries = boundaries
        if boundaries[0][0] != 0 or boundaries[-1][1] != config.num_hidden_layers:
            raise ValueError(f"stage boundaries {boundaries} do not cover the model")
        if tp > 1:
            validate_tp(config, tp)
        if mesh is None:
            need = self.n_stages * tp
            devs = jax.devices()
            if len(devs) < need:
                raise ValueError(
                    f"{self.n_stages} stages x tp={tp} need {need} devices, "
                    f"have {len(devs)}"
                )
            mesh = Mesh(
                np.array(devs[:need]).reshape(self.n_stages, tp),
                (STAGE_AXIS, TP_AXIS),
            )
        self.mesh = mesh
        self.tp = tp
        self.max_seq_len = max_seq_len
        self.cache_dtype = cache_dtype

        (
            self._layer_specs,
            self.stage_params,
            self.valid,
            self.head_params,
            self.l_pad,
        ) = place_stage_model(config, params, boundaries, mesh, tp)
        self._kv_spec = P(STAGE_AXIS, None, None, TP_AXIS if tp > 1 else None)
        self._rope = model_rope_tables(config, max_seq_len)
        self._finish_init()

    def _finish_init(self) -> None:
        self._prefill = jax.jit(self._prefill_impl, donate_argnums=(1,))
        self._join_jit = jax.jit(self._join_impl, donate_argnums=(1,))
        self._decode_cache: OrderedDict = OrderedDict()
        # The stage walks (prefill/decode/verify modes) live outside the
        # bounded knob cache: there are at most three, reused by every entry.
        self._walk_cache: dict = {}

    @classmethod
    def from_runner(cls, runner, *, max_seq_len: int, cache_dtype,
                    interleave: bool = True):
        """Adopt a PipelineRunner's already-placed stage shards (no second
        device_put of the weights) — the --api-batch + --backend mesh path."""
        self = cls.__new__(cls)
        self.interleave = interleave
        self.config = runner.config
        self.n_stages = runner.n_stages
        self.boundaries = runner.boundaries
        self.mesh = runner.mesh
        self.tp = runner.tp
        self.max_seq_len = max_seq_len
        self.cache_dtype = cache_dtype
        self.l_pad = runner.l_pad
        self._layer_specs = runner._layer_specs
        self.stage_params = runner.stage_params
        self.valid = runner.valid
        self.head_params = runner.head_params
        self._kv_spec = P(
            STAGE_AXIS, None, None, TP_AXIS if runner.tp > 1 else None
        )
        self._rope = model_rope_tables(self.config, max_seq_len)
        self._finish_init()
        return self

    def init_kv(self, b: int) -> KVCache:
        from cake_tpu.parallel.multihost import shard_put

        kv = init_cache(
            self.n_stages * self.l_pad,
            b,
            self.max_seq_len,
            self.config.num_key_value_heads,
            self.config.head_dim,
            self.cache_dtype,
        )
        return KVCache(
            k=shard_put(
                kv.k.reshape(self.n_stages, self.l_pad, *kv.k.shape[1:]),
                self.mesh, self._kv_spec,
            ),
            v=shard_put(
                kv.v.reshape(self.n_stages, self.l_pad, *kv.v.shape[1:]),
                self.mesh, self._kv_spec,
            ),
        )

    def _mapped_walk(self, mode: str):
        """The shard_mapped stage loop over pad-aware batched bodies.

        ``mode``: "prefill" (full-width chunk at slot 0), "decode" (one
        token at wpos), or "verify" (cached chunk at wpos — speculative
        verify; MoE forced drop-free dense under tp)."""
        cfg = self.config
        n = self.n_stages
        tp_axis = TP_AXIS if self.tp > 1 else None
        cos, sin = self._rope
        perm = [(j, (j + 1) % n) for j in range(n)]
        decode = mode == "decode"
        cached_chunk = mode == "verify"
        moe_dispatch = (
            "dense" if cached_chunk and tp_axis is not None else "auto"
        )

        def body(stage_params, valid, x, kv, q_pos, k_pos, pads, lengths, wpos):
            stage = jax.lax.axis_index(STAGE_AXIS)
            local_params = jax.tree.map(lambda a: a[0], stage_params)
            local_valid = valid[0]
            local_kv = KVCache(k=kv.k[0], v=kv.v[0])

            def run(x, kv_in):
                return batched_blocks_forward(
                    local_params, x, kv_in, cos, sin, q_pos, k_pos, cfg,
                    decode=decode, cached_chunk=cached_chunk, pads=pads,
                    lengths=lengths, write_pos=wpos,
                    valid=local_valid, tp_axis=tp_axis,
                    moe_dispatch=moe_dispatch,
                )

            def skip(x, kv_in):
                return x, kv_in

            def loop(i, carry):
                x, kv_c = carry
                x, kv_c = jax.lax.cond(i == stage, run, skip, x, kv_c)
                x = jax.lax.ppermute(x, STAGE_AXIS, perm)
                return x, kv_c

            x, local_kv = jax.lax.fori_loop(0, n, loop, (x, local_kv))
            return x, KVCache(k=local_kv.k[None], v=local_kv.v[None])

        return checked_shard_map(
            body,
            mesh=self.mesh,
            in_specs=(
                self._layer_specs, P(STAGE_AXIS), P(),
                KVCache(k=self._kv_spec, v=self._kv_spec),
                P(), P(), P(), P(), P(),
            ),
            out_specs=(P(STAGE_AXIS), KVCache(k=self._kv_spec, v=self._kv_spec)),
        )

    def _walks(self, mode: str):
        if mode not in self._walk_cache:
            self._walk_cache[mode] = self._mapped_walk(mode)
        return self._walk_cache[mode]

    def _prefill_impl(self, head, kv, tokens, pads, ends, seq_len):
        cfg = self.config
        b, l = tokens.shape
        x = M.embed_tokens(head, tokens, cfg)
        q_pos, k_pos = prefill_positions(l, pads, ends)
        x_stages, kv = self._walks("prefill")(
            self.stage_params, self.valid, x, kv, q_pos, k_pos,
            pads, ends, jnp.int32(0),
        )
        x = x_stages[:b]  # the true output cycles back to stage 0's shard
        return M.head_forward(head, x, seq_len, cfg), kv

    def prefill(self, tokens, kv, pads, ends=None):
        tokens = jnp.asarray(tokens)
        b, l = tokens.shape
        ends = (
            jnp.full((b,), l, jnp.int32)
            if ends is None
            else jnp.asarray(ends, jnp.int32)
        )
        return self._prefill(
            self.head_params, kv, tokens, jnp.asarray(pads), ends, jnp.int32(l)
        )

    def _join_impl(self, head, kv, tokens, pads1, ends1, lane):
        kv_row = init_cache(
            self.n_stages * self.l_pad,
            1,
            self.max_seq_len,
            self.config.num_key_value_heads,
            self.config.head_dim,
            self.cache_dtype,
        )
        kv_row = KVCache(
            k=kv_row.k.reshape(self.n_stages, self.l_pad, *kv_row.k.shape[1:]),
            v=kv_row.v.reshape(self.n_stages, self.l_pad, *kv_row.v.shape[1:]),
        )
        kv_row = jax.lax.with_sharding_constraint(
            kv_row, NamedSharding(self.mesh, self._kv_spec)
        )
        logits, kv_row = self._prefill_body_for_join(head, kv_row, tokens, pads1, ends1)
        k = jax.lax.dynamic_update_slice(
            kv.k, kv_row.k, (0, 0, lane, 0, 0, 0)
        )
        v = jax.lax.dynamic_update_slice(
            kv.v, kv_row.v, (0, 0, lane, 0, 0, 0)
        )
        return logits, KVCache(k=k, v=v)

    def _prefill_body_for_join(self, head, kv_row, tokens, pads1, ends1):
        return self._prefill_impl(head, kv_row, tokens, pads1, ends1, ends1[0])

    def join(self, kv, row_tokens, pads1, ends1, lane):
        return self._join_jit(
            self.head_params, kv, jnp.asarray(row_tokens), pads1, ends1,
            jnp.int32(lane),
        )

    # Speculative verify through the pipelined stage walk: one cached-chunk
    # SPMD computation scores every row's draft; acceptance runs replicated.

    def _verify_walk(self, kv, tokens, slot, pads):
        from cake_tpu.models.llama.batch import verify_positions

        cfg = self.config
        tokens = jnp.asarray(tokens)
        b, w = tokens.shape
        pads = jnp.asarray(pads, jnp.int32)
        x = M.embed_tokens(self.head_params, tokens, cfg)
        max_seq = kv.k.shape[-2]
        q_pos, k_pos, lengths = verify_positions(
            w, pads, jnp.int32(slot), max_seq
        )
        x_stages, kv = self._walks("verify")(
            self.stage_params, self.valid, x, kv, q_pos, k_pos,
            pads, lengths, jnp.int32(slot),
        )
        return x_stages[:b], kv

    def verify_greedy(self, kv, tokens, slot, pads):
        key = ("verify_greedy", tokens.shape[1])

        def build():
            from cake_tpu.models.llama.batch import verify_greedy_ids

            cfg = self.config

            def run(kv, tokens, slot, pads):
                x, kv = self._verify_walk(kv, tokens, slot, pads)
                logits = M.head_forward_all(self.head_params, x, cfg)
                return verify_greedy_ids(logits), kv

            return jax.jit(run, donate_argnums=(0,))

        fn = _cache_get_or_build(self._decode_cache, key, build)
        return fn(kv, jnp.asarray(tokens), jnp.int32(slot), jnp.asarray(pads))

    def verify_sampled(self, kv, tokens, slot, pads, drafts, n_drafts, keys, s):
        key = (
            "verify_sampled", tokens.shape[1],
            s.temperature, s.top_k, s.top_p,
        )

        def build():
            from cake_tpu.models.llama.batch import verify_sampled_accept

            cfg = self.config

            def run(kv, tokens, slot, pads, drafts, n_drafts, keys):
                x, kv = self._verify_walk(kv, tokens, slot, pads)
                logits = M.head_forward_all(self.head_params, x, cfg)
                n_accs, nxts, keys = verify_sampled_accept(
                    logits, drafts, n_drafts, keys,
                    s.temperature, s.top_k, s.top_p,
                )
                return n_accs, nxts, kv, keys

            return jax.jit(run, donate_argnums=(0,))

        fn = _cache_get_or_build(self._decode_cache, key, build)
        return fn(
            kv, jnp.asarray(tokens), jnp.int32(slot), jnp.asarray(pads),
            jnp.asarray(drafts), jnp.asarray(n_drafts, jnp.int32), keys,
        )

    def _forward_one(self, pads):
        cfg = self.config
        head = self.head_params
        walk = self._walks("decode")

        def forward_one(tok, kv, slot):
            b = tok.shape[0]
            # Padded physical cache length (SEQ_MULTIPLE rounding), as above.
            x = M.embed_tokens(head, tok, cfg)
            q_pos, k_pos, lengths = decode_positions(slot, pads, kv.k.shape[-2])
            x_stages, kv = walk(
                self.stage_params, self.valid, x, kv, q_pos, k_pos,
                pads, lengths, slot,
            )
            x = x_stages[:b]
            return M.head_forward(head, x, jnp.int32(1), cfg), kv

        return forward_one

    def decode(self, kv, tok, slot, pads, keys, ring, ring_idx, n, s):
        b = int(tok.shape[0])
        if (
            self.interleave
            and self.n_stages > 1
            and b % self.n_stages == 0
            and getattr(keys, "ndim", 1) == 2  # per-row streams required
        ):
            return self._decode_interleaved(
                kv, tok, slot, pads, keys, ring, ring_idx, n, s
            )
        knobs = (n, s.temperature, s.top_k, s.top_p, s.repeat_penalty)

        def build():
            # Serialized walk: sampling is outside the stage shard_map, so
            # the tail fusion applies. (The 1F1B interleaved walk below
            # samples INSIDE the stage loop and keeps the unfused tail —
            # bit-identical either way, fused.sample_step.)
            from cake_tpu.ops.fuse import resolve_fusion

            fusions, fimpl = resolve_fusion(self.config)
            tail_impl = fimpl if "tail" in fusions else None

            def run(kv, tok, slot, pads, keys, ring, ring_idx):
                return sampled_decode_scan(
                    self._forward_one(pads),
                    kv, tok, slot, keys, ring, ring_idx,
                    n_steps=n,
                    temperature=s.temperature,
                    top_k=s.top_k,
                    top_p=s.top_p,
                    repeat_penalty=s.repeat_penalty,
                    tail_impl=tail_impl,
                )

            return jax.jit(run, donate_argnums=(0,))

        fn = _cache_get_or_build(self._decode_cache, knobs, build)
        return fn(kv, tok, jnp.int32(slot), pads, keys, ring, ring_idx)

    # ---- 1F1B interleaved decode (S microbatch groups in flight) ----------

    def _interleaved_body(self, n: int, window: int, s):
        """The shard_mapped 1F1B wall-step scan (see class docstring).

        Group g's token k runs on stage s at wall-step t = k*S + g + s; the
        LAST stage samples (repeat penalty -> per-row key split -> sample,
        the exact serialized-walk arithmetic on this group's row slice) and
        embeds the next token, whose ppermute hop lands on stage 0 exactly
        when that group's next stage-0 step begins. Warmup injects the
        engine-provided last tokens (k == 0); total wall-steps
        T = n*S + S - 1 cover the drain.
        """
        cfg, S = self.config, self.n_stages
        tp_axis = TP_AXIS if self.tp > 1 else None
        cos, sin = self._rope
        perm = [(j, (j + 1) % S) for j in range(S)]
        T = n * S + S - 1

        def body(stage_params, valid, head, tok0, kv, slot0, pads,
                 keys, ring, ring_idx):
            s_idx = jax.lax.axis_index(STAGE_AXIS)
            local_params = jax.tree.map(lambda a: a[0], stage_params)
            local_valid = valid[0]
            k_loc, v_loc = kv.k[0], kv.v[0]
            b = tok0.shape[0]
            bg = b // S
            max_seq = k_loc.shape[-2]
            emb_dtype = head["embed"].dtype
            hidden = head["embed"].shape[1]

            def rows(a, row0):
                return jax.lax.dynamic_slice_in_dim(a, row0, bg, 0)

            def step(carry, t):
                x_res, k_c, v_c, out, keys_c, ring_c, ridx_c = carry
                rel = t - s_idx
                g = jnp.where(rel >= 0, rel % S, 0)
                ktok = jnp.where(rel >= 0, rel // S, 0)
                active = (rel >= 0) & (ktok < n)
                row0 = g * bg
                # Stage 0 warmup: inject the engine-provided last tokens.
                tok_g = rows(tok0, row0)
                x_inject = M.embed_tokens(head, tok_g[:, None], cfg).astype(
                    emb_dtype
                )
                x_in = jnp.where(
                    (s_idx == 0) & (ktok == 0), x_inject, x_res
                )

                wpos = slot0 + ktok
                pads_g = rows(pads, row0)
                q_pos, k_pos, lengths = decode_positions(wpos, pads_g, max_seq)

                def run(x, k_c, v_c):
                    x2, kvo = batched_blocks_forward(
                        local_params, x, KVCache(k=k_c, v=v_c), cos, sin,
                        q_pos, k_pos, cfg, decode=True, pads=pads_g,
                        lengths=lengths, write_pos=wpos, valid=local_valid,
                        tp_axis=tp_axis, row_offset=row0,
                    )
                    return x2, kvo.k, kvo.v

                def skip(x, k_c, v_c):
                    return x, k_c, v_c

                x_mid, k_c, v_c = jax.lax.cond(active, run, skip, x_in, k_c, v_c)

                # Last stage: head -> penalty -> per-row sample -> emit +
                # embed the group's next token. No collectives inside (tp
                # peers take the same branch and compute identically).
                def sample_branch(args):
                    x_mid, out, keys_c, ring_c, ridx_c = args
                    logits = M.head_forward(head, x_mid, jnp.int32(1), cfg)
                    # The group's row slice walks the ONE sampling arithmetic
                    # (fused.sample_step) — bit-identical to the serialized
                    # walk by construction.
                    nxt, keys_g, ring_g, ridx_g = sample_step(
                        logits, rows(keys_c, row0), rows(ring_c, row0),
                        rows(ridx_c, row0),
                        temperature=s.temperature, top_k=s.top_k,
                        top_p=s.top_p, repeat_penalty=s.repeat_penalty,
                    )
                    if window > 0:
                        ring_c = jax.lax.dynamic_update_slice_in_dim(
                            ring_c, ring_g, row0, 0
                        )
                        ridx_c = jax.lax.dynamic_update_slice_in_dim(
                            ridx_c, ridx_g, row0, 0
                        )
                    keys_c = jax.lax.dynamic_update_slice_in_dim(
                        keys_c, keys_g, row0, 0
                    )
                    out = jax.lax.dynamic_update_slice(
                        out, nxt[:, None], (row0, ktok)
                    )
                    x_new = M.embed_tokens(head, nxt[:, None], cfg).astype(
                        emb_dtype
                    )
                    return x_new, out, keys_c, ring_c, ridx_c

                def no_sample(args):
                    return args

                x_out, out, keys_c, ring_c, ridx_c = jax.lax.cond(
                    (s_idx == S - 1) & active,
                    sample_branch, no_sample,
                    (x_mid, out, keys_c, ring_c, ridx_c),
                )
                x_res = jax.lax.ppermute(x_out, STAGE_AXIS, perm)
                return (x_res, k_c, v_c, out, keys_c, ring_c, ridx_c), None

            carry0 = (
                jnp.zeros((bg, 1, hidden), emb_dtype),
                k_loc, v_loc,
                jnp.zeros((b, n), jnp.int32),
                keys, ring, ring_idx,
            )
            (x_f, k_loc, v_loc, out, keys_f, ring_f, ridx_f), _ = jax.lax.scan(
                step, carry0, jnp.arange(T)
            )
            # Sampling state lives on the LAST stage's copy; return everything
            # stage-stacked and let the caller slice index S-1.
            return (
                out[None],
                KVCache(k=k_loc[None], v=v_loc[None]),
                keys_f[None], ring_f[None], ridx_f[None],
            )

        stack = P(STAGE_AXIS)
        return checked_shard_map(
            body,
            mesh=self.mesh,
            in_specs=(
                self._layer_specs, P(STAGE_AXIS), P(), P(),
                KVCache(k=self._kv_spec, v=self._kv_spec),
                P(), P(), P(), P(), P(),
            ),
            out_specs=(
                stack,
                KVCache(k=self._kv_spec, v=self._kv_spec),
                stack, stack, stack,
            ),
        )

    def _decode_interleaved(self, kv, tok, slot, pads, keys, ring, ring_idx, n, s):
        window = int(ring.shape[1])
        knobs = (
            "1f1b", n, window,
            s.temperature, s.top_k, s.top_p, s.repeat_penalty,
        )

        def build():
            mapped = self._interleaved_body(n, window, s)
            head, stage_params, valid = (
                self.head_params, self.stage_params, self.valid
            )

            def run(kv, tok, slot, pads, keys, ring, ring_idx):
                out, kv, keys_f, ring_f, ridx_f = mapped(
                    stage_params, valid, head, tok, kv, slot, pads,
                    keys, ring, ring_idx,
                )
                last = self.n_stages - 1
                return out[last], kv, keys_f[last], ring_f[last], ridx_f[last]

            return jax.jit(run, donate_argnums=(0,))

        fn = _cache_get_or_build(self._decode_cache, knobs, build)
        b = int(tok.shape[0])
        # A scalar ring_idx (equal-length prompts) is valid on the serialized
        # walk; the group row-slicing here needs per-row rank — broadcast.
        ring_idx = jnp.broadcast_to(
            jnp.asarray(ring_idx, jnp.int32), (b,)
        )
        return fn(
            kv, jnp.asarray(tok, jnp.int32), jnp.int32(slot), pads,
            keys, jnp.asarray(ring, jnp.int32), ring_idx,
        )


class DistributedBatchBackend:
    """Continuous batching over the TCP topology (master <-> workers).

    The reference's defining deployment — heterogeneous hosts over TCP
    (README.md:89-121) — serves API requests ONE at a time behind a global
    lock (api/mod.rs:76). This backend runs the engine's init_kv/prefill/
    decode/join seam over the SAME StageClient spans the serialized master
    walks (runtime/master.py), with the left-padded lockstep layout riding
    a ``batch`` extension of the FORWARD header (runtime/proto.py): B
    concurrent rows share every wire round trip, so TCP serving throughput
    scales with the batch instead of the request count.

    State split: the master holds embed/ln_f/lm_head + its OWN local block
    ranges (kv here = a dict of those ranges' caches; may be empty); each
    worker keeps per-connection caches for its ranges, re-made at epoch
    prefill and lane-scattered on join (runtime/worker.py _forward_batch).
    Sampling runs master-side through fused.sample_step — the one
    arithmetic every backend walks, so engine streams are token-identical
    to the local backend (pinned in tests/test_distributed_batch.py).

    Failure semantics: every epoch runs under a replay session (one sid per
    init_kv, riding each FORWARD as sid/seq — runtime/proto.py), so a
    transient wire failure mid-op is absorbed by StageClient's deadline +
    idempotent resend: the worker re-executes the lost op or answers from
    its replay cache, and the epoch continues bit-identically. Only when the
    retry budget is exhausted or the worker truly lost the session (process
    death -> SessionLost) does ``_walk`` raise ``BackendWorkerError`` — and
    then the engine finishes just this epoch's LIVE streams with
    ``finish_reason="error"`` and keeps serving (runtime/serving.py); it no
    longer takes the whole engine down. The serialized generator path keeps
    its full-history replay on top of the same per-op machinery.
    """

    def __init__(self, step, *, max_seq_len: int | None = None,
                 cache_dtype: jnp.dtype = jnp.bfloat16):
        from cake_tpu.parallel.topology import MASTER_NODE

        self.step = step  # DistributedForwardStep: plan, clients, head, locals
        # Capability gate: an OLD worker ignores the FORWARD ``batch`` header
        # and would run padded rows as a plain chunk — silently wrong
        # activations. Its handshake omits batch_ops (defaults False), so
        # refuse loudly here instead.
        all_verify = True
        for node, client in step.clients.items():
            info = getattr(client, "info", None)
            if info is None or not getattr(info, "batch_ops", False):
                ver = getattr(info, "version", "unknown")
                raise RuntimeError(
                    f"worker {node!r} (version {ver}) does not support "
                    "lockstep batch ops; upgrade it or drop --api-batch"
                )
            all_verify &= bool(getattr(info, "verify_ops", False))
        if not all_verify:
            # A worker without the ``verify`` kind would reject speculative
            # frames MID-EPOCH; shadow the methods so the engine's
            # capability gate falls back to plain decode instead.
            self.verify_greedy = None
            self.verify_sampled = None
        self.config = step.config
        self.max_seq_len = int(max_seq_len or step.max_seq_len)
        self.cache_dtype = cache_dtype
        self._master_node = MASTER_NODE
        # Per-epoch trace attribution: the engine sets this to the epoch's
        # head request id (runtime/serving.py) and every remote round trip
        # below carries it in the FORWARD header (runtime/proto.py).
        self.trace_id: str | None = None
        cfg = self.config
        cos, sin = model_rope_tables(cfg, self.max_seq_len)

        from cake_tpu.obs.jitwatch import tracked_jit

        bprefill, bdecode, bjoin, bverify = make_lockstep_range_ops(
            cfg, cos, sin
        )
        self._local = {
            kind: tracked_jit(
                fn, name=f"master.batch_{kind}", donate_argnames=("kv",)
            )
            for kind, fn in (
                ("prefill", bprefill),
                ("decode", bdecode),
                ("join", bjoin),
                ("verify", bverify),
            )
        }

        def embed(head, tokens):
            return M.embed_tokens(head, tokens, cfg).astype(step.dtype)

        def head_at(head, x, seq_len):
            return M.head_forward(head, x, seq_len, cfg)

        def head_all_greedy(head, x):
            from cake_tpu.models.llama.batch import verify_greedy_ids

            return verify_greedy_ids(M.head_forward_all(head, x, cfg))

        self._embed = jax.jit(embed)
        self._head = jax.jit(head_at)
        self._head_all_greedy = jax.jit(head_all_greedy)
        self._sample_cache: OrderedDict = OrderedDict()
        self._accept_cache: OrderedDict = OrderedDict()

    def init_kv(self, b: int) -> dict:
        # New epoch = new route: the replica router advances each group to
        # its next healthy member (round-robin; ejected members sit out
        # until rejoin — runtime/router.py). The route is stable for the
        # whole epoch: its replay session lives on the routed workers.
        routed = set(self.step.router.refresh().values())
        # New epoch = new replay session on every ROUTED worker: the prefill
        # at seq 0 creates fresh worker-side caches under this sid, and
        # every subsequent op of the epoch is idempotently resendable after
        # a reconnect (runtime/client.py retry path). The PREVIOUS epoch's
        # session is retired explicitly (RESET sid) wherever one exists —
        # relying on the worker's LRU alone would pin up to MAX_SESSIONS
        # dead epochs' KV pools in its device memory.
        sid = f"ep-{uuid.uuid4().hex[:12]}"
        for name, client in self.step.clients.items():
            if client.sid is not None:
                try:
                    client.reset()
                except (ConnectionError, TimeoutError, OSError):
                    pass  # dead socket: nothing deliverable to retire; the
                    # old session ages out of the worker's LRU instead
                client.sid = None
            if name in routed:
                client.begin_session(sid)
        cfg = self.config
        return {
            (lo, hi): init_cache(
                hi - lo, b, self.max_seq_len, cfg.num_key_value_heads,
                cfg.head_dim, self.cache_dtype,
            )
            for (lo, hi) in self.step.local_params
        }

    # ------------------------------------------------------------ span walk

    def _walk(self, kind: str, x, pos: int, kv: dict, batch_hdr: dict,
              local_args: tuple):
        """Run ``x`` through the full stage plan: local ranges via the jitted
        pad-aware bodies, remote spans as ONE batched round trip each."""
        from cake_tpu.runtime.worker import jax_to_wire, wire_to_jax

        step = self.step
        i = 0
        plan = step.plan
        while i < len(plan):
            s = plan[i]
            if s.node == self._master_node:
                r = (s.lo, s.hi)
                x, kv[r] = self._local[kind](
                    step.local_params[r], x, kv[r], *local_args
                )
                i += 1
            else:
                ranges = []
                primary = s.node
                while i < len(plan) and plan[i].node == primary:
                    ranges.append((plan[i].lo, plan[i].hi))
                    i += 1
                # Replica routing: the plan names the primary; the epoch's
                # route (set at init_kv, possibly flipped by failover)
                # names the serving member.
                node = step.router.route(primary)
                try:
                    out = step.clients[node].forward(
                        jax_to_wire(x), ranges, pos, batch=batch_hdr,
                        trace=self.trace_id,
                    )
                except (ConnectionError, TimeoutError, OSError) as e:
                    # Deadline/retry/replay exhausted, or the worker lost
                    # the epoch's session (SessionLost): the epoch cannot
                    # continue. Structured failure (same counter/event as
                    # the serialized path), best-effort reconnect so the
                    # NEXT epoch has a live socket, then the typed error
                    # the engine isolates instead of dying on.
                    from cake_tpu.utils import metrics

                    metrics.registry.counter(
                        "cake_hop_failures_total",
                        "Worker hops abandoned after deadline/retry "
                        "exhaustion or session loss (each one either "
                        "triggers history replay or fails its streams "
                        "with finish_reason=error).",
                    ).inc(node=node)
                    metrics.flight.record(
                        "hop-failed", self.trace_id,
                        node=node, pos=int(pos), op=kind,
                        error=str(e)[:200],
                    )
                    try:
                        step.clients[node].reconnect()
                    except (ConnectionError, TimeoutError, OSError):
                        pass  # next epoch's init_kv / walk retries the dial
                    raise BackendWorkerError(node, kind, e) from e
                # A served hop clears any probation early — the node is
                # demonstrably back (standby rejoin without waiting out
                # the cooldown).
                step.router.report_success(node)
                x = wire_to_jax(out, step.dtype)
        return x, kv

    def failover(self, node: str) -> bool:
        """Eject ``node`` and re-route its replica group for the REST of
        this epoch (runtime/router.py). True iff a healthy replica took
        over — the engine then migrates live streams onto the new route
        (runtime/serving.py); False degrades to error isolation."""
        return self.step.router.failover(node) is not None

    # ------------------------------------------------------------ engine ops

    def prefill(self, tokens, kv, pads, ends=None):
        tokens = jnp.asarray(tokens)
        b, w = tokens.shape
        pads = jnp.asarray(pads, jnp.int32)
        ends = (
            jnp.full((b,), w, jnp.int32)
            if ends is None
            else jnp.asarray(ends, jnp.int32)
        )
        x = self._embed(self.step.head, tokens)
        hdr = {
            "kind": "prefill",
            "pads": [int(p) for p in np.asarray(pads)],
            "ends": [int(e) for e in np.asarray(ends)],
        }
        x, kv = self._walk("prefill", x, 0, kv, hdr, (pads, ends))
        return self._head(self.step.head, x, ends[0]), kv

    def decode(self, kv, tok, slot, pads, keys, ring, ring_idx, n, s):
        pads = jnp.asarray(pads, jnp.int32)
        hdr_pads = [int(p) for p in np.asarray(pads)]
        knobs = (s.temperature, s.top_k, s.top_p, s.repeat_penalty)

        def build():
            # Master-side sampling: the tail fusion applies here too — the
            # wire carries activations, the tail runs on the master.
            from cake_tpu.ops.fuse import resolve_fusion

            fusions, fimpl = resolve_fusion(self.config)
            tail_impl = fimpl if "tail" in fusions else None

            def one(logits, keys, ring, ring_idx):
                return sample_step(
                    logits, keys, ring, ring_idx,
                    temperature=s.temperature, top_k=s.top_k, top_p=s.top_p,
                    repeat_penalty=s.repeat_penalty, tail_impl=tail_impl,
                )

            return jax.jit(one)

        sampler = _cache_get_or_build(self._sample_cache, knobs, build)
        tok = jnp.asarray(tok, jnp.int32)
        out = []
        for i in range(n):
            pos = int(slot) + i
            x = self._embed(self.step.head, tok[:, None])
            hdr = {"kind": "decode", "pads": hdr_pads}
            x, kv = self._walk("decode", x, pos, kv, hdr, (pads, jnp.int32(pos)))
            logits = self._head(self.step.head, x, jnp.int32(1))
            tok, keys, ring, ring_idx = sampler(logits, keys, ring, ring_idx)
            out.append(tok)
        return jnp.stack(out, axis=1), kv, keys, ring, ring_idx

    def join(self, kv, row_tokens, pads1, ends1, lane):
        row_tokens = jnp.asarray(row_tokens)
        pads1 = jnp.asarray(pads1, jnp.int32)
        ends1 = jnp.asarray(ends1, jnp.int32)
        x = self._embed(self.step.head, row_tokens)
        hdr = {
            "kind": "join",
            "pads": [int(pads1[0])],
            "ends": [int(ends1[0])],
            "lane": int(lane),
        }
        x, kv = self._walk(
            "join", x, 0, kv, hdr, (pads1, ends1, jnp.int32(lane))
        )
        return self._head(self.step.head, x, ends1[0]), kv

    # Speculative verify over the wire: ONE batched cached-chunk round trip
    # per span verifies every row's draft; acceptance runs on the master.

    def _verify_walk(self, kv, tokens, slot, pads):
        tokens = jnp.asarray(tokens)
        pads = jnp.asarray(pads, jnp.int32)
        hdr = {
            "kind": "verify",
            "pads": [int(p) for p in np.asarray(pads)],
        }
        x = self._embed(self.step.head, tokens)
        return self._walk(
            "verify", x, int(slot), kv, hdr, (pads, jnp.int32(slot))
        )

    def verify_greedy(self, kv, tokens, slot, pads):
        x, kv = self._verify_walk(kv, tokens, slot, pads)
        return self._head_all_greedy(self.step.head, x), kv

    def verify_sampled(self, kv, tokens, slot, pads, drafts, n_drafts, keys, s):
        from cake_tpu.models.llama.batch import verify_sampled_accept

        x, kv = self._verify_walk(kv, tokens, slot, pads)
        knobs = (s.temperature, s.top_k, s.top_p)

        def build():
            cfg = self.config

            def run(head, x, drafts, n_drafts, keys):
                logits = M.head_forward_all(head, x, cfg)
                return verify_sampled_accept(
                    logits, drafts, n_drafts, keys, *knobs
                )

            return jax.jit(run)

        fn = _cache_get_or_build(self._accept_cache, knobs, build)
        n_accs, nxts, keys = fn(
            self.step.head, x, jnp.asarray(drafts),
            jnp.asarray(n_drafts, jnp.int32), keys,
        )
        return n_accs, nxts, kv, keys
