"""Master: owns the generator-side model and walks the stage plan per step.

Covers the reference master (cake-core/src/cake/master.rs and the block walk in
llama.rs:72-138): embedding, final norm and LM head run on the master; each
topology stage either executes locally (layers absent from the topology,
llama.rs:210-217) or is forwarded to a worker as ONE round trip per contiguous
span (llama.rs:95-114). Also provides the generation-loop wrapper with tokens/s
reporting that excludes the first (warmup/prefill) token (master.rs:54-97).

This is the HETEROGENEOUS deployment path (hosts over TCP/DCN). When all stages
live in one TPU slice, use parallel.pipeline.PipelineRunner instead — the whole
step compiles to one XLA computation with ICI hops and no host round trips.
"""

from __future__ import annotations

import logging
import time
import uuid
from pathlib import Path
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.cache import init_cache
from cake_tpu.utils import metrics, trace
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import (
    LlamaGenerator,
    SamplingConfig,
    StepConnectionError,
    Token,
)
from cake_tpu.models.llama.tokenizer import load_tokenizer
from cake_tpu.ops.rope import model_rope_tables
from cake_tpu.parallel.topology import MASTER_NODE, Stage, Topology
from cake_tpu.runtime import proto
from cake_tpu.runtime.client import StageClient
from cake_tpu.runtime.worker import jax_to_wire, wire_to_jax

log = logging.getLogger("cake_tpu.master")


class DistributedForwardStep:
    """ForwardStep that walks local stages and remote workers per token.

    Consecutive stages owned by the same worker are already merged by the stage
    plan; additionally, multiple non-adjacent ranges of the SAME worker separated
    only by other workers' ranges still reuse one connection (one socket per
    node, vs. the reference's one per layer, llama.rs:204-209).
    """

    def __init__(
        self,
        config: LlamaConfig,
        model_dir: str | Path,
        topology: Topology,
        *,
        dtype: jnp.dtype = jnp.bfloat16,
        max_seq_len: int | None = None,
        batch_size: int = 1,
        client_factory: Callable[[str, str], StageClient] | None = None,
        kv_dtype: jnp.dtype | None = None,
        op_deadline_s: float | None = None,
        op_retries: int = 2,
        reconnect_attempts: int = 3,
        reconnect_backoff_s: float = 0.5,
    ):
        from cake_tpu.io.safetensors_io import load_layer_params, open_checkpoint

        self.config = config
        self.dtype = dtype
        # KV storage dtype for the master's own local stages (--kv-dtype);
        # workers size their caches from their own flag.
        self.kv_dtype = dtype if kv_dtype is None else kv_dtype
        self._max_seq = int(max_seq_len or config.max_position_embeddings)
        self._batch = batch_size

        self.plan: list[Stage] = topology.stage_plan(config.num_hidden_layers)
        topology.validate(config.num_hidden_layers)
        # Request/trace id attribution: servers set this before a request's
        # steps (runtime/api.py) and the id rides every FORWARD frame header
        # (runtime/proto.py), so worker-side telemetry and logs attribute
        # each hop to the request that caused it. None = untraced.
        self.trace_id: str | None = None

        # Master loads embedding/norm/head + only ITS OWN local block ranges
        # (llama.rs:178-196 + 210-217).
        reader = open_checkpoint(model_dir)
        self.head = {
            "embed": reader.jax("model.embed_tokens.weight", dtype),
            "ln_f": reader.jax("model.norm.weight", dtype),
        }
        if not config.tie_word_embeddings:
            # read_weight understands quantized checkpoints (io/quantizer.py
            # stores lm_head as .q8/.q4 + .scale).
            from cake_tpu.io.safetensors_io import read_weight

            self.head["lm_head"] = read_weight(
                reader, "lm_head.weight", dtype, True
            )

        from cake_tpu.ops.fuse import fuse_layer_tree

        self.local_params: dict[tuple[int, int], M.Params] = {}
        for s in self.plan:
            if s.node == MASTER_NODE:
                # Fused QKV/gate-up like every other runner (ops/fuse.py).
                self.local_params[(s.lo, s.hi)] = fuse_layer_tree(
                    load_layer_params(reader, s.lo, s.hi, dtype, config)
                )

        # One client per distinct worker node, opened in plan order
        # (connect failure aborts startup, like client.rs:28-30). The
        # default factory threads the wire-resilience knobs (per-op
        # deadline, retry budget, reconnect attempts/backoff — ServeConfig/
        # CLI) into every StageClient.
        if client_factory is None:
            def client_factory(host: str, node: str) -> StageClient:
                return StageClient(
                    host, node,
                    op_deadline_s=op_deadline_s,
                    op_retries=op_retries,
                    reconnect_attempts=reconnect_attempts,
                    reconnect_backoff_s=reconnect_backoff_s,
                )

        # Replica routing (runtime/router.py): the stage plan names each
        # span's PRIMARY; the router resolves it to whichever group member
        # is healthy this sequence/epoch. Clients are opened for EVERY
        # member — standbys included — so failover is a route flip, not a
        # cold dial.
        self.replica_groups = topology.replica_groups()
        from cake_tpu.runtime.router import ReplicaRouter

        self.router = ReplicaRouter(
            {
                s.node: self.replica_groups.get(s.node, [s.node])
                for s in self.plan
                if s.node != MASTER_NODE
            }
        )
        self.clients: dict[str, StageClient] = {}
        for s in self.plan:
            if s.node == MASTER_NODE:
                continue
            for member in self.replica_groups.get(s.node, [s.node]):
                if member not in self.clients:
                    self.clients[member] = client_factory(
                        topology.nodes[member].host, member
                    )

        cfg = config
        cos, sin = model_rope_tables(cfg, self._max_seq)

        def run_blocks(layers, x, kv, pos, cached_prefill=False):
            return M.blocks_forward(
                layers, x, kv, cos, sin, pos, cfg, cached_prefill=cached_prefill
            )

        self._run_blocks = jax.jit(
            run_blocks,
            static_argnames=("cached_prefill",),
            donate_argnames=("kv",),
        )

        def embed(head, tokens):
            return M.embed_tokens(head, tokens, config).astype(dtype)

        def head_fn(head, x, seq_len):
            return M.head_forward(head, x, seq_len, cfg)

        def head_all_fn(head, x):
            # Greedy ids at every chunk position (speculative verify);
            # argmax on device, same rationale as speculative._verify_fn.
            return jnp.argmax(M.head_forward_all(head, x, cfg), -1).astype(
                jnp.int32
            )

        self._embed = jax.jit(embed)
        self._head = jax.jit(head_fn)
        self._head_all = jax.jit(head_all_fn)
        self.reset()

    @property
    def max_seq_len(self) -> int:
        return self._max_seq

    def reset(self) -> None:
        cfg = self.config
        self._local_kv = {
            (lo, hi): init_cache(
                hi - lo,
                self._batch,
                self._max_seq,
                cfg.num_key_value_heads,
                cfg.head_dim,
                self.kv_dtype,
            )
            for (lo, hi) in self.local_params
        }
        # New sequence = new route: the router advances each replica group
        # to its next healthy member (round-robin; ejected members sit out
        # until rejoin — runtime/router.py).
        routes = self.router.refresh()
        # Fresh replay session per sequence (runtime/proto.py sid/seq):
        # workers key their KV by this id, so the forwards below are
        # idempotently resendable after a reconnect, and stale state can
        # never leak across resets even on a surviving connection. Only
        # clients that HELD a session are retired (a never-routed standby
        # has nothing to drop), and only THIS route's clients begin one.
        sid = f"seq-{uuid.uuid4().hex[:12]}"
        routed = set(routes.values())
        for name, client in self.clients.items():
            if client.sid is not None:
                try:
                    client.reset()  # retire the previous sid's worker state
                except (ConnectionError, TimeoutError, OSError):
                    # A dead connection holds no deliverable state to
                    # retire; the old session ages out of the worker's LRU.
                    # Reconnect only nodes this route still uses.
                    if name in routed:
                        client.reconnect()
                client.sid = None
            if name in routed:
                client.begin_session(sid)

    def __call__(self, tokens: np.ndarray, pos: int, seq_len: int) -> np.ndarray:
        x = self._walk_plan(
            self._embed(self.head, jnp.asarray(tokens, jnp.int32)), pos
        )
        logits = self._head(self.head, x, jnp.int32(seq_len))
        return np.asarray(logits)

    def verify_chunk(self, tokens: np.ndarray, pos: int) -> np.ndarray:
        """Speculative-verify over the cluster: ONE chunked forward through
        the same stage plan (workers run the cached-prefill continuation for
        a width>1 chunk at pos>0), greedy ids at EVERY chunk position from
        the master-side head. This is what makes --speculative-k effective
        on the TCP deployment mode: K accepted drafts cost one worker round
        trip per span instead of K+1."""
        x = self._walk_plan(
            self._embed(self.head, jnp.asarray(tokens, jnp.int32)), pos
        )
        return np.asarray(self._head_all(self.head, x))

    def verify_chunk_sampled(
        self, tokens: np.ndarray, pos: int, draft: np.ndarray,
        n_draft: int, key, sampling,
    ) -> tuple[int, int, object]:
        """Sampled speculative verify over the cluster: the same one-chunk
        stage walk as verify_chunk, with rejection acceptance + residual/bonus
        sampling jitted on the master's head device
        (speculative._sampled_head_fn) — so --speculative-k stays effective
        for temperature > 0 streams on the TCP deployment mode."""
        from cake_tpu.models.llama.speculative import _sampled_head_fn

        x = self._walk_plan(
            self._embed(self.head, jnp.asarray(tokens, jnp.int32)), pos
        )
        fn = _sampled_head_fn(
            self.config, sampling.temperature, sampling.top_k, sampling.top_p
        )
        n_acc, nxt, key = fn(
            self.head, x, jnp.asarray(draft, jnp.int32), jnp.int32(n_draft), key
        )
        return int(n_acc), int(nxt), key

    def _walk_plan(self, x, pos: int):
        i = 0
        while i < len(self.plan):
            s = self.plan[i]
            if s.node == MASTER_NODE:
                r = (s.lo, s.hi)
                with trace.span("stage.local"):
                    x, self._local_kv[r] = self._run_blocks(
                        self.local_params[r],
                        x,
                        self._local_kv[r],
                        jnp.int32(pos),
                        cached_prefill=M.is_cached_prefill(pos, x.shape[1]),
                    )
                i += 1
            else:
                # One round trip even if the worker owns several consecutive
                # stages in the plan (shouldn't happen post-merge, but cheap).
                ranges = []
                primary = s.node
                while i < len(self.plan) and self.plan[i].node == primary:
                    ranges.append((self.plan[i].lo, self.plan[i].hi))
                    i += 1
                # Replica routing: the plan names the primary; this
                # sequence's route (advanced at reset()) names the member
                # that actually serves the span.
                node = self.router.route(primary)
                # Per-hop timing: the TCP analogue of the reference worker's
                # per-op stats (worker.rs:215-231), visible via trace.spans
                # and the API's /stats endpoint. timeline=False: the round
                # trip is already a structured `wire.<node>` span inside
                # client.forward — bridging this wrapper too would record
                # the same latency twice on the obs ring.
                with trace.span(f"hop.{node}", timeline=False):
                    try:
                        # client.forward already retried with idempotent
                        # session resends (runtime/client.py); reaching the
                        # except below means the budget is exhausted or the
                        # worker lost the session.
                        out = self.clients[node].forward(
                            jax_to_wire(x), ranges, pos, trace=self.trace_id
                        )
                    except (ConnectionError, TimeoutError, OSError) as e:
                        # The reference tears the whole run down here
                        # (SURVEY.md §5: no reconnect, no retry). Surface a
                        # STRUCTURED failure — counter + flight event, never
                        # a silent reconnect-and-continue — then reconnect
                        # the node and raise the typed error the generator
                        # recovers from by replaying its history.
                        log.warning("hop to %s failed: %s", node, e)
                        metrics.registry.counter(
                            "cake_hop_failures_total",
                            "Worker hops abandoned after deadline/retry "
                            "exhaustion or session loss (each one either "
                            "triggers history replay or fails its streams "
                            "with finish_reason=error).",
                        ).inc(node=node)
                        metrics.flight.record(
                            "hop-failed", self.trace_id,
                            node=node, pos=int(pos), error=str(e)[:200],
                        )
                        # Eject the member from rotation: the generator's
                        # history replay (reset() -> refresh) walks through
                        # a healthy replica instead of re-dialing the dead
                        # one — the serialized path's transparent failover.
                        self.router.report_failure(node)
                        try:
                            self.clients[node].reconnect()
                        except (ConnectionError, TimeoutError, OSError):
                            pass  # a replica can serve the replay; the
                            # ejected node redials on rejoin
                        raise StepConnectionError(node) from e
                    # A served hop is the strongest liveness signal there
                    # is: clear any probation early (standby rejoin without
                    # waiting out the cooldown).
                    self.router.report_success(node)
                    x = wire_to_jax(out, self.dtype)
        return x

    def pull_cluster_stats(self, observer=None) -> list[str]:
        """On-demand federation pull: one PING + STATS round trip per
        connected worker over a FRESH short-lived connection (the op
        sockets are strictly request-reply — interleaving a STATS
        mid-generation would desync them), feeding the cluster observer
        (obs/cluster.py). The heartbeat monitor does this continuously
        when probing is enabled; this is the pull path for masters running
        without probe threads (``cake-tpu stats`` / a /metrics scrape
        against a serialized ``--api-batch 1`` server). Returns the nodes
        that answered; unreachable or old (no ``stats_ops``) workers are
        skipped, never raised."""
        if observer is None:
            from cake_tpu.obs.cluster import cluster as observer
        import socket as _socket

        from cake_tpu.utils import parse_address

        pulled: list[str] = []
        for node, client in self.clients.items():
            host, port = parse_address(
                client.host, what=f"stats host for node {node!r}"
            )
            try:
                sock = _socket.create_connection((host, port), timeout=5.0)
            except OSError:
                continue
            try:
                sock.settimeout(5.0)
                proto.write_frame(sock, proto.hello_frame())
                info_reply = proto.read_frame(sock)
                if info_reply.type != proto.MsgType.WORKER_INFO:
                    continue
                info = proto.WorkerInfo.from_dict(info_reply.header["info"])
                if not info.stats_ops:
                    continue
                t0w = time.time()
                proto.write_frame(sock, proto.ping_frame())
                pong = proto.read_frame(sock)
                t1w = time.time()
                if pong.type == proto.MsgType.PING:
                    observer.observe_ping(
                        node, t0w, t1w, pong.header.get("t")
                    )
                proto.write_frame(sock, proto.stats_request_frame())
                stats = proto.read_frame(sock)
                if stats.type == proto.MsgType.STATS:
                    observer.update_report(node, stats.header.get("report"))
                    pulled.append(node)
            except (ConnectionError, TimeoutError, OSError, ValueError):
                continue  # a dead worker has no telemetry to contribute
            finally:
                try:
                    sock.close()
                except OSError:
                    pass
        return pulled

    def close(self) -> None:
        for c in self.clients.values():
            c.close()


class Master:
    """Generation orchestrator + throughput reporting (master.rs:22-97)."""

    def __init__(self, generator: LlamaGenerator, sample_len: int = 100):
        self.generator = generator
        self.sample_len = sample_len

    @classmethod
    def from_topology(
        cls,
        model_dir: str | Path,
        topology: Topology,
        *,
        dtype: jnp.dtype = jnp.bfloat16,
        max_seq_len: int | None = None,
        sampling: SamplingConfig = SamplingConfig(),
        sample_len: int = 100,
    ) -> "Master":
        config = LlamaConfig.from_model_dir(model_dir)
        step = DistributedForwardStep(
            config, model_dir, topology, dtype=dtype, max_seq_len=max_seq_len
        )
        gen = LlamaGenerator(config, step, load_tokenizer(model_dir), sampling)
        return cls(gen, sample_len=sample_len)

    def generate(
        self, on_token: Callable[[Token], None] | None = None
    ) -> str:
        """Decode loop with tokens/s that excludes the first token as warmup
        (master.rs:67-73, 86-94)."""
        first_token_at: float | None = None
        count = 0

        def hook(tok: Token) -> None:
            nonlocal first_token_at, count
            count += 1
            if count == 1:
                first_token_at = time.perf_counter()
            if on_token is not None:
                on_token(tok)

        start = time.perf_counter()
        text = self.generator.generate(self.sample_len, on_token=hook)
        elapsed = time.perf_counter() - start
        if count > 1 and first_token_at is not None:
            steady = count - 1
            dt = time.perf_counter() - first_token_at
            log.info(
                "%d tokens in %.2fs: %.2f tok/s (first token %.2fs, excluded)",
                count,
                elapsed,
                steady / dt if dt > 0 else float("inf"),
                first_token_at - start,
            )
        return text
