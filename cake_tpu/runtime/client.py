"""Master-side proxy for a remote worker (role of cake-core/src/cake/client.rs).

One connection per WORKER, not per layer — the reference opens a TCP connection
for every block even on the same host (llama.rs:204-209); here all of a node's
contiguous ranges ride one socket, and a multi-range request is still one round
trip (client.rs:117-126's batching, generalized).
"""

from __future__ import annotations

import itertools
import logging
import socket
import time

from cake_tpu.obs.timeline import timeline
from cake_tpu.runtime import proto
from cake_tpu.utils import metrics, parse_address

log = logging.getLogger("cake_tpu.client")

# Process-wide flow-id source: every FORWARD hop gets a fresh id, so the
# timeline's "s"/"f" arrow pairs never collide across clients or requests.
_flow_ids = itertools.count(1)


class StageClient:
    """Connects to one worker and forwards activations through its ranges."""

    def __init__(self, host: str, node_name: str, timeout: float = 30.0):
        self.node_name = node_name
        self.host = host
        self._timeout = timeout
        self._connect()

    def _connect(self) -> None:
        addr_host, addr_port = parse_address(
            self.host, what=f"topology host for node {self.node_name!r}"
        )
        t0 = time.perf_counter()
        self._sock = socket.create_connection(
            (addr_host, addr_port), timeout=self._timeout
        )
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        proto.write_frame(self._sock, proto.hello_frame())
        reply = proto.read_frame(self._sock)
        if reply.type != proto.MsgType.WORKER_INFO:
            raise ConnectionError(
                f"worker {self.node_name} handshake failed: got {reply.type.name}"
            )
        self.info = proto.WorkerInfo.from_dict(reply.header["info"])
        self.handshake_ms = (time.perf_counter() - t0) * 1e3
        log.info(
            "connected to %s (%s): device=%s dtype=%s ranges=%s in %.1fms",
            self.node_name,
            self.host,
            self.info.device,
            self.info.dtype,
            self.info.ranges,
            self.handshake_ms,
        )

    def reconnect(self, attempts: int = 3, backoff_s: float = 0.5) -> None:
        """Re-dial after a connection failure; fresh connection = fresh
        worker-side KV (worker.rs:52-61 semantics), so callers must replay
        sequence state afterwards (master.StepConnectionError recovery)."""
        self.close()
        metrics.registry.counter(
            "cake_worker_reconnects_total",
            "Connection re-dials after a worker hop failed.",
        ).inc(node=self.node_name)
        metrics.flight.record("worker-reconnect", node=self.node_name)
        last: Exception | None = None
        for i in range(attempts):
            try:
                self._connect()
                return
            except OSError as e:
                last = e
                log.warning(
                    "reconnect to %s failed (attempt %d/%d): %s",
                    self.node_name, i + 1, attempts, e,
                )
                if i + 1 < attempts:  # no pointless sleep before the raise
                    time.sleep(backoff_s * (2**i))
        raise ConnectionError(
            f"could not reconnect to worker {self.node_name}"
        ) from last

    def forward(
        self,
        x: proto.WireTensor,
        ranges: list[tuple[int, int]],
        pos: int,
        batch: dict | None = None,
        trace: str | None = None,
    ) -> proto.WireTensor:
        """One round trip: run ``x`` through the worker's owned ranges.

        Chunks may carry padded tails; no validity field travels (see
        proto.MsgType.FORWARD for why pad-tail KV is safe). ``batch``
        selects the lockstep layout (proto.forward_frame); ``trace`` rides
        the frame header for per-hop request attribution.

        Every round trip feeds the hop telemetry (utils/metrics.py): a
        ``cake_hop_seconds{node=...}`` latency histogram and tx/rx byte
        counters — the per-worker attribution the reference only logged as
        ad-hoc ops/s lines (worker.rs:253-264)."""
        # Timeline: the round trip is a span on this node's "wire" track and
        # a flow arrow into the worker's op span — linked by the flow id that
        # rides the frame header, so a merged export renders the cross-node
        # request as one connected timeline.
        flow_id = next(_flow_ids)
        t0 = time.perf_counter()
        with timeline.span(
            f"wire.{self.node_name}", rid=trace, track="wire",
            args={"pos": int(pos)},
        ):
            timeline.flow_start(flow_id, "hop", rid=trace, track="wire")
            proto.write_frame(
                self._sock, proto.forward_frame(x, ranges, pos, batch=batch,
                                                trace=trace, flow=flow_id)
            )
            reply = proto.read_frame(self._sock)
        metrics.registry.histogram(
            "cake_hop_seconds",
            "Wire round-trip latency per worker hop (send+compute+recv).",
        ).observe(time.perf_counter() - t0, node=self.node_name)
        # Payload bytes in BOTH directions (frame prefix+header excluded) so
        # tx and rx — and the worker's mirror counters — share one unit.
        bytes_c = metrics.registry.counter(
            "cake_wire_bytes_total",
            "Tensor payload bytes per worker hop and direction.",
        )
        bytes_c.inc(len(x.data), node=self.node_name, direction="tx")
        bytes_c.inc(len(reply.payload), node=self.node_name, direction="rx")
        if reply.type == proto.MsgType.ERROR:
            raise RuntimeError(
                f"worker {self.node_name}: {reply.header['error']}"
            )
        if reply.type != proto.MsgType.TENSOR:
            raise ConnectionError(f"unexpected reply {reply.type.name}")
        return reply.tensor()

    def reset(self) -> None:
        proto.write_frame(self._sock, proto.reset_frame())

    def ping(self) -> float:
        t0 = time.perf_counter()
        proto.write_frame(self._sock, proto.ping_frame())
        reply = proto.read_frame(self._sock)
        if reply.type != proto.MsgType.PING:
            raise ConnectionError(f"unexpected ping reply {reply.type.name}")
        return (time.perf_counter() - t0) * 1e3

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
