"""Master-side proxy for a remote worker (role of cake-core/src/cake/client.rs).

One connection per WORKER, not per layer — the reference opens a TCP connection
for every block even on the same host (llama.rs:204-209); here all of a node's
contiguous ranges ride one socket, and a multi-range request is still one round
trip (client.rs:117-126's batching, generalized).

Wire resilience (the reference has none — SURVEY §5): every round trip runs
under a per-op deadline (socket timeout), and when a session is active
(``begin_session``) a failed round trip is retried with bounded backoff by
re-dialing and RESENDING the same (sid, seq) frame — idempotent on the worker
side (runtime/worker.py sessions), so a dropped frame or lost reply costs a
retry, not the request. Retries are gated on the session: without sid/seq a
resend would double-apply KV writes, so the legacy path still fails fast.
``HeartbeatMonitor`` finally puts proto.PING to work: a dedicated probe
connection per worker feeding a liveness gauge and an unhealthy-transition
counter.
"""

from __future__ import annotations

import itertools
import logging
import socket
import threading
import time

from cake_tpu.obs.timeline import timeline
from cake_tpu.runtime import faults, proto
from cake_tpu.utils import metrics, parse_address

log = logging.getLogger("cake_tpu.client")

# Process-wide flow-id source: every FORWARD hop gets a fresh id, so the
# timeline's "s"/"f" arrow pairs never collide across clients or requests.
_flow_ids = itertools.count(1)


class SessionLost(ConnectionError):
    """The worker no longer holds this session's state (coded ERROR reply:
    restarted, evicted, or a sequence gap). Retrying the op cannot succeed;
    the caller must rebuild state (generator history replay / engine failure
    isolation). Subclasses ConnectionError so existing recovery paths fire."""

    def __init__(self, node: str, code: str, message: str):
        super().__init__(f"worker {node}: {code}: {message}")
        self.node = node
        self.code = code


class StageClient:
    """Connects to one worker and forwards activations through its ranges."""

    def __init__(
        self,
        host: str,
        node_name: str,
        timeout: float = 30.0,
        *,
        op_deadline_s: float | None = None,
        op_retries: int = 2,
        reconnect_attempts: int = 3,
        reconnect_backoff_s: float = 0.5,
    ):
        self.node_name = node_name
        self.host = host
        self._timeout = timeout
        # Per-op deadline: the socket timeout every round trip runs under
        # (default: the connect timeout). A worker that neither replies nor
        # closes within it surfaces as TimeoutError -> the retry path.
        self.op_deadline_s = (
            timeout if op_deadline_s is None else op_deadline_s
        )
        self.op_retries = max(0, op_retries)
        self.reconnect_attempts = max(1, reconnect_attempts)
        self.reconnect_backoff_s = reconnect_backoff_s
        # Replay session (begin_session): rides every FORWARD as sid/seq.
        self.sid: str | None = None
        self._seq = 0
        self._connect()

    def _connect(self) -> None:
        addr_host, addr_port = parse_address(
            self.host, what=f"topology host for node {self.node_name!r}"
        )
        t0 = time.perf_counter()
        self._sock = socket.create_connection(
            (addr_host, addr_port), timeout=self._timeout
        )
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock.settimeout(self.op_deadline_s)
        proto.write_frame(self._sock, proto.hello_frame())
        reply = proto.read_frame(self._sock)
        if reply.type != proto.MsgType.WORKER_INFO:
            raise ConnectionError(
                f"worker {self.node_name} handshake failed: got {reply.type.name}"
            )
        self.info = proto.WorkerInfo.from_dict(reply.header["info"])
        self.handshake_ms = (time.perf_counter() - t0) * 1e3
        log.info(
            "connected to %s (%s): device=%s dtype=%s ranges=%s in %.1fms",
            self.node_name,
            self.host,
            self.info.device,
            self.info.dtype,
            self.info.ranges,
            self.handshake_ms,
        )

    # ------------------------------------------------------------- sessions

    def begin_session(self, sid: str) -> None:
        """Start a fresh replay session (runtime/proto.py sid/seq contract):
        subsequent forwards carry monotonically increasing seq under ``sid``
        and become retry-safe. Call at epoch start / sequence reset."""
        self.sid = sid
        self._seq = 0

    def configure(
        self,
        *,
        op_deadline_s: float | None = None,
        op_retries: int | None = None,
        reconnect_attempts: int | None = None,
        reconnect_backoff_s: float | None = None,
    ) -> None:
        """Apply wire-resilience knobs to a LIVE client (the ServeConfig
        threading path: an engine adopting an already-connected step applies
        its config here). The deadline takes effect on the current socket
        immediately; the rest govern future failures."""
        if op_deadline_s is not None:
            self.op_deadline_s = op_deadline_s
            self._sock.settimeout(op_deadline_s)
        if op_retries is not None:
            self.op_retries = max(0, op_retries)
        if reconnect_attempts is not None:
            self.reconnect_attempts = max(1, reconnect_attempts)
        if reconnect_backoff_s is not None:
            self.reconnect_backoff_s = reconnect_backoff_s

    def reconnect(
        self, attempts: int | None = None, backoff_s: float | None = None
    ) -> None:
        """Re-dial after a connection failure with bounded exponential
        backoff (no sleep after the final failed attempt). Without an active
        session, a fresh connection means fresh worker-side KV
        (worker.rs:52-61 semantics) and callers must replay sequence state
        (master.StepConnectionError recovery); WITH a session, worker state
        survives by sid and the caller may simply resend the in-flight op."""
        attempts = self.reconnect_attempts if attempts is None else attempts
        backoff_s = (
            self.reconnect_backoff_s if backoff_s is None else backoff_s
        )
        self.close()
        metrics.registry.counter(
            "cake_worker_reconnects_total",
            "Connection re-dials after a worker hop failed.",
        ).inc(node=self.node_name)
        metrics.flight.record("worker-reconnect", node=self.node_name)
        last: Exception | None = None
        for i in range(attempts):
            try:
                self._connect()
                return
            except OSError as e:
                last = e
                log.warning(
                    "reconnect to %s failed (attempt %d/%d): %s",
                    self.node_name, i + 1, attempts, e,
                )
                if i + 1 < attempts:  # no pointless sleep before the raise
                    time.sleep(backoff_s * (2**i))
        raise ConnectionError(
            f"could not reconnect to worker {self.node_name}"
        ) from last

    def forward(
        self,
        x: proto.WireTensor,
        ranges: list[tuple[int, int]],
        pos: int,
        batch: dict | None = None,
        trace: str | None = None,
    ) -> proto.WireTensor:
        """One round trip: run ``x`` through the worker's owned ranges.

        Chunks may carry padded tails; no validity field travels (see
        proto.MsgType.FORWARD for why pad-tail KV is safe). ``batch``
        selects the lockstep layout (proto.forward_frame); ``trace`` rides
        the frame header for per-hop request attribution.

        Every round trip feeds the hop telemetry (utils/metrics.py): a
        ``cake_hop_seconds{node=...}`` latency histogram and tx/rx byte
        counters — the per-worker attribution the reference only logged as
        ad-hoc ops/s lines (worker.rs:253-264).

        Failure handling: with a session active, a deadline/connection
        failure re-dials and RESENDS the same (sid, seq) frame up to
        ``op_retries`` times — the worker either executes it (never arrived)
        or answers from its replay cache (reply was lost), so the retry is
        exact. Without a session the first failure raises (a blind resend
        would double-apply KV writes)."""
        seq: int | None = None
        if self.sid is not None:
            seq = self._seq
            self._seq += 1
        retries = self.op_retries if seq is not None else 0
        for attempt in range(retries + 1):
            try:
                return self._round_trip(x, ranges, pos, batch, trace, seq)
            except SessionLost:
                raise  # a resend cannot succeed; caller rebuilds state
            except (ConnectionError, TimeoutError, OSError) as e:
                if attempt >= retries:
                    raise
                log.warning(
                    "op to %s failed (attempt %d/%d, seq=%s): %s — "
                    "reconnecting for an idempotent resend",
                    self.node_name, attempt + 1, retries + 1, seq, e,
                )
                metrics.registry.counter(
                    "cake_op_retries_total",
                    "FORWARD round trips resent after a deadline or "
                    "connection failure (session replay path).",
                ).inc(node=self.node_name)
                metrics.flight.record(
                    "op-retry", trace, node=self.node_name,
                    seq=seq, error=str(e)[:200],
                )
                # Never reuse the broken socket: a late reply from the timed-
                # out op would desync the request/reply stream.
                self.reconnect()
        raise AssertionError("unreachable")  # loop always returns or raises

    def _round_trip(self, x, ranges, pos, batch, trace, seq):
        """One send+recv on the current socket (the retried unit)."""
        # Timeline: the round trip is a span on this node's "wire" track and
        # a flow arrow into the worker's op span — linked by the flow id that
        # rides the frame header, so a merged export renders the cross-node
        # request as one connected timeline.
        flow_id = next(_flow_ids)
        t0 = time.perf_counter()
        with timeline.span(
            f"wire.{self.node_name}", rid=trace, track="wire",
            args={"pos": int(pos)},
        ):
            timeline.flow_start(flow_id, "hop", rid=trace, track="wire")
            frame = proto.forward_frame(
                x, ranges, pos, batch=batch, trace=trace, flow=flow_id,
                sid=self.sid if seq is not None else None, seq=seq,
            )
            spec = faults.check("client.send", node=self.node_name)
            if spec is not None and spec.kind == "drop":
                pass  # frame "lost on the wire": the reply read times out
            elif spec is not None and spec.kind == "kill":
                # The worker is unreachable from this client: the op never
                # leaves, the socket is torn down. With ``count=0`` the
                # node stays dead through every retry/reconnect — the
                # deterministic stand-in for a vanished host that drives
                # the failover path (runtime/router.py).
                self.close()
                raise ConnectionError("fault: connection killed pre-send")
            elif spec is not None and spec.kind == "truncate":
                data = proto.encode_frame(frame)
                self._sock.sendall(
                    data[: max(1, int(len(data) * spec.frac))]
                )
                raise ConnectionError("fault: frame truncated mid-send")
            else:
                if spec is not None and spec.kind == "delay":
                    faults.sleep(spec)
                proto.write_frame(self._sock, frame)
            spec = faults.check("client.recv", node=self.node_name)
            if spec is not None and spec.kind == "delay":
                faults.sleep(spec)
            reply = proto.read_frame(self._sock)
        metrics.registry.histogram(
            "cake_hop_seconds",
            "Wire round-trip latency per worker hop (send+compute+recv).",
        ).observe(time.perf_counter() - t0, node=self.node_name)
        # Payload bytes in BOTH directions (frame prefix+header excluded) so
        # tx and rx — and the worker's mirror counters — share one unit.
        bytes_c = metrics.registry.counter(
            "cake_wire_bytes_total",
            "Tensor payload bytes per worker hop and direction.",
        )
        bytes_c.inc(len(x.data), node=self.node_name, direction="tx")
        bytes_c.inc(len(reply.payload), node=self.node_name, direction="rx")
        if reply.type == proto.MsgType.ERROR:
            code = reply.header.get("code")
            if code in (proto.ERR_UNKNOWN_SESSION, proto.ERR_BAD_SEQ):
                raise SessionLost(
                    self.node_name, code, reply.header["error"]
                )
            raise RuntimeError(
                f"worker {self.node_name}: {reply.header['error']}"
            )
        if reply.type != proto.MsgType.TENSOR:
            raise ConnectionError(f"unexpected reply {reply.type.name}")
        return reply.tensor()

    def reset(self) -> None:
        """Drop worker-side sequence state. With a session active this
        retires the CURRENT sid (the worker frees its replay state); callers
        then begin_session a fresh one for the next sequence."""
        proto.write_frame(self._sock, proto.reset_frame(sid=self.sid))

    def ping(self) -> float:
        t0 = time.perf_counter()
        proto.write_frame(self._sock, proto.ping_frame())
        reply = proto.read_frame(self._sock)
        if reply.type != proto.MsgType.PING:
            raise ConnectionError(f"unexpected ping reply {reply.type.name}")
        return (time.perf_counter() - t0) * 1e3

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class HeartbeatMonitor:
    """Per-worker liveness probing over dedicated PING connections.

    One daemon thread per worker dials its OWN connection (the op socket is
    strictly request-reply — a concurrent PING would interleave frames) and
    pings every ``interval_s`` under a ``deadline_s`` socket timeout. A probe
    that fails or times out marks the node unhealthy within
    ``interval_s + deadline_s`` of the stall starting:

      * gauge ``cake_worker_healthy{node}`` — 1/0 liveness
      * counter ``cake_worker_unhealthy_total{node}`` — transitions to down
      * histogram ``cake_worker_ping_seconds{node}`` — probe RTT
      * flight events ``worker-unhealthy`` / ``worker-healthy`` + a timeline
        instant per transition, so chaos runs show exactly when the monitor
        noticed.

    The probe connection also carries the CLUSTER OBSERVABILITY plane
    (obs/cluster.py), so federation allocates nothing new: every PING
    reply's worker clock stamp feeds the node's clock-offset estimate
    (``cake_clock_offset_seconds{node}``), and every ``stats_every``-th
    probe round-trips a STATS frame pulling the worker's metric dump,
    flight-event tail, and timeline slice into the observer — what the
    master's merged /metrics, /events, and /trace?cluster=1 render. Both
    are gated on the worker's ``stats_ops`` handshake capability, so old
    workers are probed exactly as before.

    The monitor only OBSERVES: routing/failover decisions belong to the
    caller (``healthy()``/``snapshot()``).
    """

    def __init__(
        self,
        hosts: dict[str, str],
        *,
        interval_s: float = 2.0,
        deadline_s: float = 2.0,
        stats_every: int = 5,
        observer=None,
    ):
        self.hosts = dict(hosts)
        self.interval_s = interval_s
        self.deadline_s = deadline_s
        # Telemetry pull cadence: one STATS round trip every N probes
        # (0 = liveness-only probing). The observer defaults to the
        # process-global cluster plane.
        self.stats_every = max(0, int(stats_every))
        if observer is None:
            from cake_tpu.obs.cluster import cluster as observer
        self.observer = observer
        self._lock = threading.Lock()
        self._healthy: dict[str, bool | None] = {n: None for n in self.hosts}
        self._stats_capable: dict[str, bool] = {}
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    def start(self) -> "HeartbeatMonitor":
        for node, host in self.hosts.items():
            t = threading.Thread(
                target=self._probe_loop, args=(node, host),
                name=f"heartbeat-{node}", daemon=True,
            )
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=self.deadline_s + 1.0)
        self._threads = []

    # ------------------------------------------------------------- queries

    def healthy(self, node: str) -> bool:
        """True until a probe has FAILED: an unprobed worker is presumed
        live (the monitor exists to notice deaths, not to gate startup)."""
        with self._lock:
            return self._healthy.get(node) is not False

    def snapshot(self) -> dict[str, bool | None]:
        with self._lock:
            return dict(self._healthy)

    # ------------------------------------------------------------- probing

    def _dial(self, host: str, node: str) -> socket.socket:
        addr_host, addr_port = parse_address(
            host, what=f"heartbeat host for node {node!r}"
        )
        sock = socket.create_connection(
            (addr_host, addr_port), timeout=self.deadline_s
        )
        try:
            proto.write_frame(sock, proto.hello_frame())
            reply = proto.read_frame(sock)
            if reply.type != proto.MsgType.WORKER_INFO:
                raise ConnectionError(
                    f"heartbeat handshake to {node} got {reply.type.name}"
                )
            info = proto.WorkerInfo.from_dict(reply.header["info"])
            with self._lock:
                # Old workers (stats_ops False) are probed liveness-only:
                # a STATS frame would only earn an ERROR reply.
                self._stats_capable[node] = bool(info.stats_ops)
        except BaseException:
            sock.close()
            raise
        return sock

    def _probe_loop(self, node: str, host: str) -> None:
        sock: socket.socket | None = None
        probes = 0
        while not self._stop.is_set():
            try:
                if sock is None:
                    sock = self._dial(host, node)
                t0w = time.time()
                t0 = time.perf_counter()
                proto.write_frame(sock, proto.ping_frame())
                reply = proto.read_frame(sock)
                t1w = time.time()
                if reply.type != proto.MsgType.PING:
                    raise ConnectionError(
                        f"heartbeat reply {reply.type.name}"
                    )
                metrics.registry.histogram(
                    "cake_worker_ping_seconds",
                    "Heartbeat PING round-trip time per worker.",
                ).observe(time.perf_counter() - t0, node=node)
                with self._lock:
                    capable = self._stats_capable.get(node, False)
                if self.observer is not None and capable:
                    # Clock-offset sample from the reply's worker stamp
                    # (NTP midpoint — obs/cluster.ClockOffsetEstimator).
                    self.observer.observe_ping(
                        node, t0w, t1w, reply.header.get("t")
                    )
                probes += 1
                if (
                    self.observer is not None
                    and capable
                    and self.stats_every
                    and (probes - 1) % self.stats_every == 0
                ):
                    # Federation pull, piggybacked on the live probe
                    # connection (strictly request-reply, so a STATS here
                    # can never interleave with a PING). Its OWN failure
                    # handling: the PING above already proved liveness, so
                    # a slow/failed telemetry reply (a large report built
                    # under a busy GIL can outrun deadline_s) costs this
                    # connection — redialed next probe — never the node's
                    # health (a telemetry-volume false positive would
                    # trigger real failover).
                    try:
                        proto.write_frame(sock, proto.stats_request_frame())
                        stats = proto.read_frame(sock)
                        if stats.type == proto.MsgType.STATS:
                            self.observer.update_report(
                                node, stats.header.get("report")
                            )
                    except (
                        ConnectionError, TimeoutError, OSError, ValueError
                    ):
                        try:
                            sock.close()  # mid-frame state: stream torn
                        except OSError:
                            pass
                        sock = None
                self._mark(node, True)
            except (ConnectionError, TimeoutError, OSError, ValueError):
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    sock = None
                self._mark(node, False)
            self._stop.wait(self.interval_s)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _mark(self, node: str, ok: bool) -> None:
        with self._lock:
            prev = self._healthy.get(node)
            self._healthy[node] = ok
        metrics.registry.gauge(
            "cake_worker_healthy",
            "Heartbeat liveness per worker (1 = answering PING in time).",
        ).set(1 if ok else 0, node=node)
        # Only TRANSITIONS get counters/events (the gauge tracks level), so
        # the flight ring isn't flooded at probe cadence.
        if not ok and prev is not False:
            metrics.registry.counter(
                "cake_worker_unhealthy_total",
                "Heartbeat transitions to unhealthy per worker.",
            ).inc(node=node)
            metrics.flight.record("worker-unhealthy", node=node)
            timeline.instant(
                "worker-unhealthy", track="health", args={"node": node}
            )
            log.warning("worker %s marked UNHEALTHY (heartbeat)", node)
        elif ok and prev is False:
            metrics.flight.record("worker-healthy", node=node)
            timeline.instant(
                "worker-healthy", track="health", args={"node": node}
            )
            log.info("worker %s healthy again (heartbeat)", node)
