"""Worker: serves its topology-assigned block ranges over the wire protocol.

Covers the reference worker (cake-core/src/cake/worker.rs): resolve own topology
entry by name with first-entry fallback (worker.rs:73-93), load ONLY the assigned
blocks (worker.rs:95-108), accept master connections, per-connection handshake then
an op loop, per-connection KV-cache isolation (worker.rs:52-61), and periodic
throughput stats (worker.rs:19, 253-264).

TPU-first differences:
  * Each owned contiguous range is ONE jitted lax.scan over stacked params — the
    whole span executes as a single XLA computation per request, instead of the
    reference's per-block kernel walk (worker.rs:218-229).
  * KV caches are preallocated fixed-shape buffers donated through the jit, not
    concat-grown tensors.
  * RESET lets a master start a new sequence on a live connection; errors return
    a structured ERROR frame instead of dropping the connection.

Failure semantics (the recovery half of runtime/faults.py): a FORWARD frame
carrying ``sid``/``seq`` headers is served from an EPOCH-SCOPED SESSION that
survives the connection — KV caches keyed by sid in a bounded LRU, each
remembering the last applied seq and its encoded reply. A master that lost a
reply (socket died mid-round-trip) reconnects and RESENDS the same (sid, seq):
if the op was applied, the cached reply returns without re-execution; if it
never arrived, it executes now. Either way the outcome is idempotent. A seq
gap or an evicted/unknown session returns a coded ERROR
(proto.ERR_BAD_SEQ / ERR_UNKNOWN_SESSION) so the client escalates to
full-history replay (serialized path) or failure isolation (engine path)
instead of burning retries.
"""

from __future__ import annotations

import logging
import select
import socket
import threading
import time
from collections import OrderedDict
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu import __version__
from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.cache import KVCache, init_cache
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.ops.rope import model_rope_tables
from cake_tpu.obs.timeline import timeline
from cake_tpu.parallel.topology import Topology
from cake_tpu.runtime import faults, proto
from cake_tpu.utils import metrics, trace

log = logging.getLogger("cake_tpu.worker")

NUM_OPS_TO_STATS = 5  # parity with worker.rs:19

# Replay sessions kept per worker: enough for a few masters' live epochs plus
# stragglers; LRU-evicted beyond this (an evicted session answers
# ERR_UNKNOWN_SESSION, which clients recover from — correctness never depends
# on retention, only fast-path replay does).
MAX_SESSIONS = 8


class _ConnectionTorn(Exception):
    """Internal: a fault spec asked for this connection to die mid-op."""


class _Session:
    """One epoch's replayable state: KV caches + the last applied op.

    ``lock`` serializes op execution per session: a retried (sid, seq) can
    arrive on a NEW connection while the original connection's thread is
    still executing that seq — the second thread must wait, then observe
    ``seq == last_seq`` and replay the cached reply instead of re-executing.
    """

    __slots__ = ("caches", "last_seq", "last_reply", "lock")

    def __init__(self, caches):
        self.caches = caches
        self.last_seq = -1
        self.last_reply: bytes | None = None
        self.lock = threading.Lock()


def wire_to_jax(t: proto.WireTensor, compute_dtype: jnp.dtype) -> jnp.ndarray:
    arr = t.to_numpy()
    if t.dtype == "bf16":
        return jnp.asarray(arr).view(jnp.bfloat16).astype(compute_dtype)
    if t.dtype == "f32" and np.dtype(compute_dtype).name == "bfloat16":
        # Narrow on host (native RTNE codec or ml_dtypes — bit-identical to the
        # on-device convert): halves the host->device upload for f32 senders.
        from cake_tpu import native

        return jnp.asarray(native.f32_to_bf16(arr)).view(jnp.bfloat16)
    return jnp.asarray(arr).astype(compute_dtype)


def jax_to_wire(x: jnp.ndarray) -> proto.WireTensor:
    if x.dtype == jnp.bfloat16:
        arr = np.asarray(x.view(jnp.uint16))
        return proto.WireTensor.from_numpy(arr, dtype_tag="bf16")
    return proto.WireTensor.from_numpy(np.asarray(x))


class Worker:
    """Block-range server bound to one topology node."""

    def __init__(
        self,
        name: str,
        model_dir: str | Path,
        topology: Topology,
        address: tuple[str, int],
        *,
        dtype: jnp.dtype = jnp.bfloat16,
        max_seq_len: int | None = None,
        batch_size: int = 1,
        attention_impl: str | None = None,
        fusion_impl: str | None = None,
        quantize: str | None = None,
        kv_dtype: jnp.dtype | None = None,
        io_timeout_s: float = 120.0,
    ):
        from cake_tpu.io.safetensors_io import load_params

        self.config = LlamaConfig.from_model_dir(
            model_dir, attention_impl=attention_impl
        )
        if fusion_impl not in (None, "none"):
            # Decode op fusion (--fusion) rides the worker's config exactly
            # like attention_impl: the norm/ingest fusion sites live in the
            # block forward THIS process runs.
            import dataclasses

            from cake_tpu.ops.fuse import parse_fusion_spec

            parse_fusion_spec(fusion_impl)  # raises on a malformed spec
            self.config = dataclasses.replace(
                self.config, fusion_impl=fusion_impl
            )
        if name not in topology.nodes and topology.nodes:
            # First-entry fallback, mirroring worker.rs:81-88.
            fallback = next(iter(topology.nodes))
            log.warning("worker name %r not in topology, using %r", name, fallback)
            name = fallback
        self.name = name
        self.dtype = dtype
        # KV storage dtype (--kv-dtype): f8 halves this worker's cache
        # memory and per-token cache bandwidth; activations stay ``dtype``.
        self.kv_dtype = dtype if kv_dtype is None else kv_dtype
        self._max_seq = int(max_seq_len or self.config.max_position_embeddings)
        self._batch = batch_size

        plan = topology.stage_plan(self.config.num_hidden_layers)
        # A replica member serves its group PRIMARY's plan ranges: the
        # stage plan names only the first-declared node of each replica
        # group (parallel/topology.py), but every member must load and
        # serve the identical spans so the master's router can swap them
        # freely (runtime/router.py).
        groups = topology.replica_groups()
        primary = next(
            (p for p, members in groups.items() if name in members), name
        )
        self.ranges = [(s.lo, s.hi) for s in plan if s.node == primary]
        if not self.ranges:
            raise ValueError(f"topology assigns no layers to worker {name!r}")

        if quantize not in (None, "int8", "int4"):
            raise ValueError(f"unknown quantize mode {quantize!r}")
        t0 = time.perf_counter()
        self.range_params = {
            (lo, hi): load_params(
                model_dir, self.config, dtype, layer_range=(lo, hi)
            )["layers"]
            for lo, hi in self.ranges
        }
        if quantize:
            # Weight-only int8/int4 on the worker's own block ranges: halves/
            # quarters this worker's weight HBM traffic; wire activations stay
            # full dtype.
            from cake_tpu.ops.quant import quantize_layer_tree

            self.range_params = {
                r: quantize_layer_tree(p, quantize)
                for r, p in self.range_params.items()
            }
        # Fuse QKV / gate|up per range (ops/fuse.py): fewer ops per scanned
        # layer, column-identical numerics (commutes with the quantize above).
        from cake_tpu.ops.fuse import fuse_layer_tree

        self.range_params = {
            r: fuse_layer_tree(p) for r, p in self.range_params.items()
        }
        log.info(
            "worker %s loaded layers %s in %.2fs",
            name,
            self.ranges,
            time.perf_counter() - t0,
        )
        trace.log_memory(f"worker.{name}.loaded")

        cfg = self.config
        cos, sin = model_rope_tables(cfg, self._max_seq)

        def run_blocks(layers, x, kv, pos, cached_prefill=False):
            return M.blocks_forward(
                layers, x, kv, cos, sin, pos, cfg, cached_prefill=cached_prefill
            )

        self._run = jax.jit(
            run_blocks,
            static_argnames=("cached_prefill",),
            donate_argnames=("kv",),
        )

        # Left-padded LOCKSTEP batch ops (continuous batching over the wire,
        # runtime/batch_backend.DistributedBatchBackend): the same pad-aware
        # batched bodies every in-process backend runs, so the TCP deployment
        # serves B concurrent rows per round trip instead of one request at a
        # time behind the API lock (the reference quirk, api/mod.rs:76).
        from cake_tpu.models.llama.batch import make_lockstep_range_ops

        from cake_tpu.obs.jitwatch import tracked_jit

        run_bprefill, run_bdecode, run_bjoin, run_bverify = (
            make_lockstep_range_ops(cfg, cos, sin)
        )
        self._run_bprefill = tracked_jit(
            run_bprefill, name="worker.batch_prefill", donate_argnames=("kv",)
        )
        self._run_bdecode = tracked_jit(
            run_bdecode, name="worker.batch_decode", donate_argnames=("kv",)
        )
        self._run_bjoin = tracked_jit(
            run_bjoin, name="worker.batch_join", donate_argnames=("kv",)
        )
        self._run_bverify = tracked_jit(
            run_bverify, name="worker.batch_verify", donate_argnames=("kv",)
        )

        self._sock = socket.create_server(address, reuse_port=False)
        self.address = self._sock.getsockname()
        # Per-connection IO deadline: a peer that stalls MID-FRAME (or never
        # finishes the handshake) releases this thread after io_timeout_s;
        # idle waits between frames are exempt (the loop treats a clean
        # zero-byte timeout as a poll tick — proto._recv_exact distinguishes).
        self.io_timeout_s = io_timeout_s
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()
        # Epoch-scoped replay sessions (module docstring), sid -> _Session.
        self._sessions: OrderedDict[str, _Session] = OrderedDict()
        self._sessions_lock = threading.Lock()

    # ------------------------------------------------------------- caches

    def _fresh_caches(self, batch: int | None = None) -> dict[tuple[int, int], KVCache]:
        """Per-connection KV state (the reference's per-client cache clone,
        worker.rs:52-61). ``batch`` sizes the cache rows; a connection's caches
        are re-made at the incoming batch whenever a new sequence (pos == 0)
        arrives with a different batch dim. Rows share one position stream
        (blocks_forward has no per-row pads), so this serves EQUAL-LENGTH
        (pad-free) batches; left-padded lockstep layouts (models/llama/batch.py)
        need the local backend, which passes per-row positions directly."""
        cfg = self.config
        return {
            (lo, hi): init_cache(
                hi - lo,
                batch or self._batch,
                self._max_seq,
                cfg.num_key_value_heads,
                cfg.head_dim,
                self.kv_dtype,
            )
            for lo, hi in self.ranges
        }

    # ------------------------------------------------------------ sessions

    def _session(self, sid: str, seq: int) -> _Session | None:
        """Resolve (creating at seq 0) the replay session for ``sid``.

        None = unknown session at seq > 0: the state this op depends on is
        gone (worker restarted, or LRU-evicted) — the caller answers with a
        coded ERROR and the client escalates to its own replay/recovery.
        """
        with self._sessions_lock:
            sess = self._sessions.get(sid)
            if sess is not None:
                self._sessions.move_to_end(sid)
                return sess
            if seq != 0:
                return None
            sess = self._sessions[sid] = _Session(self._fresh_caches())
            while len(self._sessions) > MAX_SESSIONS:
                evicted, _ = self._sessions.popitem(last=False)
                log.info("session %s evicted (LRU, cap %d)", evicted,
                         MAX_SESSIONS)
            return sess

    def _drop_session(self, sid: str) -> None:
        with self._sessions_lock:
            self._sessions.pop(sid, None)

    def _drop_all_sessions(self) -> None:
        """The 'crash' fault: what a process restart does to replay state."""
        with self._sessions_lock:
            self._sessions.clear()

    # ------------------------------------------------------------- serving

    def serve_forever(self) -> None:
        log.info("worker %s listening on %s", self.name, self.address)
        self._sock.settimeout(0.5)
        while not self._stop.is_set():
            try:
                conn, peer = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            # Register BEFORE spawning the thread: stop() must see every
            # accepted socket, or a just-accepted connection could leak a
            # thread parked in recv.
            with self._conns_lock:
                self._conns.add(conn)
            if self._stop.is_set():
                # stop() may have snapshotted _conns between accept() and the
                # registration above; registration-then-check closes that race
                # (either stop() sees the socket, or we see the flag).
                try:
                    conn.close()
                except OSError:
                    pass
                break
            t = threading.Thread(
                target=self._serve_connection, args=(conn, peer), daemon=True
            )
            t.start()
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def start(self) -> threading.Thread:
        t = threading.Thread(target=self.serve_forever, daemon=True)
        self._serve_thread = t
        t.start()
        return t

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        # Accepted sockets are blocking; threads parked in recv() would never
        # observe _stop. Closing the connections unblocks and ends them, which
        # also releases their per-connection KV caches.
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        # Join the accept loop and connection threads (bounded): a daemon
        # thread still inside a jitted op while the interpreter tears down
        # can abort the process from XLA's C++ teardown — stop() returning
        # means the worker's threads are actually gone.
        serve_t = getattr(self, "_serve_thread", None)
        if serve_t is not None and serve_t is not threading.current_thread():
            serve_t.join(timeout=5.0)
        for t in self._threads:
            if t.is_alive() and t is not threading.current_thread():
                t.join(timeout=5.0)

    def _worker_info(self, latency_ms: float) -> proto.WorkerInfo:
        dev = jax.devices()[0]
        return proto.WorkerInfo(
            dtype={"bfloat16": "bf16", "float16": "f16", "float32": "f32"}[
                jnp.dtype(self.dtype).name
            ],
            device=dev.platform,
            device_count=jax.device_count(),
            latency_ms=latency_ms,
            ranges=[list(r) for r in self.ranges],
            batch_ops=True,   # understands the FORWARD ``batch`` header
            verify_ops=True,  # understands the ``verify`` batch kind
            stats_ops=True,   # answers STATS pulls + clock-stamped PINGs
        )

    def _stats_report(self, frame: proto.Frame) -> dict:
        """One node's telemetry snapshot for a STATS pull (runtime/proto.py).

        The report is the NODE-ATTRIBUTED slice of this process's telemetry:
        metric series carrying ``node=<this worker>``, flight events and
        timeline events stamped with it. In a real deployment that is
        everything the worker records (worker-side series/spans all label
        themselves — the ``unbounded-metric-label`` rule's bounded ``node``
        convention); in a single-process test cluster it also keeps a pulled
        report from echoing the master's own events back at it.
        """
        header = frame.header
        ev_cap = max(0, int(header.get("events", 256)))
        tl_cap = max(0, int(header.get("timeline", 4096)))
        dump = metrics.registry.dump()
        mine = []
        for m in dump["metrics"]:
            series = [
                s for s in m["series"]
                if s["labels"].get("node") == self.name
            ]
            if series:
                mine.append({**m, "series": series})
        events = [
            e for e in metrics.flight.snapshot()
            if e.get("node") == self.name
        ]
        tl = [
            e for e in timeline.snapshot()
            if e.get("node") == self.name
        ]
        return {
            "node": self.name,
            "wall": round(time.time(), 6),
            "metrics": {"metrics": mine},
            "events": events[-ev_cap:] if ev_cap else [],
            "timeline": tl[-tl_cap:] if tl_cap else [],
        }

    def _serve_connection(self, conn: socket.socket, peer) -> None:
        log.info("connection from %s", peer)
        # IO deadline (see __init__). The select-gated loop below only lets
        # this cover MID-frame stalls; idle waits are unbounded.
        conn.settimeout(self.io_timeout_s)
        # Legacy per-connection KV, allocated LAZILY on the first sid-less
        # FORWARD: heartbeat probes (PING-only connections) and session-
        # carrying masters (KV lives in self._sessions) never pay for a
        # full per-connection cache set.
        caches = None
        ops = 0
        read_bytes = 0
        write_bytes = 0
        window_start = time.perf_counter()
        try:
            with conn:
                # Handshake: Hello -> WorkerInfo with measured read latency
                # (worker.rs:165-182).
                t0 = time.perf_counter()
                first = proto.read_frame(conn)
                latency_ms = (time.perf_counter() - t0) * 1e3
                if first.type != proto.MsgType.HELLO:
                    proto.write_frame(
                        conn, proto.error_frame("expected HELLO")
                    )
                    return
                # The HELLO carries the master's package version; a skew is
                # legal (capability flags gate features) but worth a line in
                # the log when a wire bug is being chased.
                peer_version = first.header.get("version", "?")
                if peer_version != __version__:
                    log.warning(
                        "master version %s != worker version %s "
                        "(capability flags negotiate features; mind wire "
                        "changes)",
                        peer_version,
                        __version__,
                    )
                proto.write_frame(
                    conn, proto.worker_info_frame(self._worker_info(latency_ms))
                )

                while not self._stop.is_set():
                    # Idle wait OUTSIDE the frame read: select until bytes
                    # arrive (re-checking _stop), so io_timeout_s only ever
                    # measures MID-frame progress. Once readable, any
                    # timeout from the read means a peer stalled mid-frame
                    # (both the Python and native codecs raise TimeoutError
                    # there) — the stream is torn, drop the connection.
                    ready, _, _ = select.select([conn], [], [], 0.5)
                    if not ready:
                        continue
                    try:
                        frame = proto.read_frame(conn)
                    except (ConnectionError, TimeoutError, OSError):
                        break
                    if frame.type == proto.MsgType.RESET:
                        sid = frame.header.get("sid")
                        if sid is None:
                            caches = None  # dropped; re-made on next use
                        else:
                            self._drop_session(sid)
                        continue
                    if frame.type == proto.MsgType.PING:
                        spec = faults.check("worker.ping", node=self.name)
                        if spec is not None and spec.kind == "stall":
                            faults.sleep(spec)  # a wedged worker, as the
                            # heartbeat monitor sees one
                        # The reply carries this worker's wall clock: the
                        # prober estimates the clock offset from the RTT
                        # midpoint (obs/cluster.py), which is what lets a
                        # merged Perfetto export align this node's spans.
                        proto.write_frame(
                            conn, proto.ping_frame(t=time.time())
                        )
                        continue
                    if frame.type == proto.MsgType.STATS:
                        # Federated telemetry pull: a read-only snapshot —
                        # it touches no caches or replay sessions, so a
                        # STATS mid-session is replay-safe by construction
                        # (pinned by tests/test_cluster_obs.py).
                        proto.write_frame(
                            conn,
                            proto.stats_reply_frame(
                                self._stats_report(frame)
                            ),
                        )
                        continue
                    if frame.type != proto.MsgType.FORWARD:
                        proto.write_frame(
                            conn,
                            proto.error_frame(f"unexpected {frame.type.name}"),
                        )
                        continue

                    read_bytes += len(frame.payload)
                    t_op = time.perf_counter()
                    try:
                        # Timeline: the op is a span on this worker's node
                        # (pid) with the wire hop's flow arrow landing inside
                        # it ("f" under the frame's flow id) — the receiving
                        # half of the master's connected cross-node view.
                        kind = frame.header.get("batch", {}).get(
                            "kind", "chunk"
                        )
                        with timeline.span(
                            f"worker.{kind}",
                            rid=frame.header.get("trace"),
                            node=self.name,
                            track="ops",
                            args={"pos": frame.header.get("pos")},
                        ):
                            flow_id = frame.header.get("flow")
                            if flow_id is not None:
                                timeline.flow_end(
                                    flow_id, "hop", node=self.name,
                                    track="ops",
                                )
                            spec = faults.check("worker.op", node=self.name)
                            if spec is not None:
                                if spec.kind == "stall":
                                    faults.sleep(spec)
                                elif spec.kind in ("kill", "crash"):
                                    if spec.kind == "crash":
                                        # Process death: replay state is gone
                                        # too, not just the transport.
                                        self._drop_all_sessions()
                                    raise _ConnectionTorn()
                            caches, out_bytes, served = self._serve_forward(
                                frame, caches, conn
                            )
                        if not served:
                            continue  # replay / coded error: not a fresh op
                    except _ConnectionTorn:
                        break  # fault plan: die mid-op, no reply
                    except (ConnectionError, OSError):
                        break  # peer went away while we replied
                    except Exception as e:  # structured error, keep connection
                        log.exception("forward failed")
                        proto.write_frame(conn, proto.error_frame(str(e)))
                        continue
                    # Per-op telemetry, attributable to the master's request
                    # via the propagated trace id (the structured successor of
                    # the reference's ops/s log lines, worker.rs:253-264).
                    metrics.registry.histogram(
                        "cake_worker_op_seconds",
                        "Seconds per served FORWARD op (decode+compute+reply).",
                    ).observe(
                        time.perf_counter() - t_op,
                        node=self.name,
                        kind=kind,
                    )
                    write_bytes += out_bytes
                    ops += 1
                    if ops % NUM_OPS_TO_STATS == 0:
                        dt = time.perf_counter() - window_start
                        log.info(
                            "%s: %.1f ops/s, read %.1f KiB/s, write %.1f KiB/s",
                            peer,
                            NUM_OPS_TO_STATS / dt,
                            read_bytes / dt / 1024,
                            write_bytes / dt / 1024,
                        )
                        read_bytes = write_bytes = 0
                        window_start = time.perf_counter()
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            log.info("connection from %s closed", peer)

    def _record_op_bytes(self, rx: int, tx: int) -> None:
        """Payload bytes per direction — same unit as the master's
        cake_wire_bytes_total (frame prefix+header excluded), so the two
        ends of a hop reconcile."""
        wb = metrics.registry.counter(
            "cake_worker_bytes_total",
            "Tensor payload bytes served, by direction.",
        )
        wb.inc(rx, node=self.name, direction="rx")
        wb.inc(tx, node=self.name, direction="tx")

    def _serve_forward(self, frame, caches, conn):
        """Route one FORWARD through session replay or the legacy per-
        connection caches; execute, reply, and update replay state.

        Returns (caches, bytes_written, served): served False = the frame
        was answered from replay state or with a coded error — no fresh op
        ran, so the caller skips the per-op telemetry for it.
        """
        sid = frame.header.get("sid")
        if sid is None:
            # Legacy contract: per-connection caches, no replay.
            if caches is None:
                caches = self._fresh_caches()
            out, caches = self._execute(frame, caches)
            written = self._send_reply(
                conn, proto.encode_frame(
                    proto.tensor_frame(out, trace=frame.header.get("trace"))
                ),
            )
            self._record_op_bytes(len(frame.payload), len(out.data))
            return caches, written, True

        seq = int(frame.header.get("seq", 0))
        sess = self._session(sid, seq)
        if sess is None:
            proto.write_frame(conn, proto.error_frame(
                f"session {sid!r} unknown at seq {seq} (restarted or "
                "evicted); state must be rebuilt",
                code=proto.ERR_UNKNOWN_SESSION,
            ))
            return caches, 0, False
        with sess.lock:
            if seq == sess.last_seq and sess.last_reply is not None:
                # Idempotent replay: the op already applied, only its reply
                # was lost on the wire — answer from the cache, do NOT
                # re-execute (the KV writes must not happen twice).
                metrics.registry.counter(
                    "cake_worker_replays_total",
                    "FORWARD ops answered from the session replay cache "
                    "(duplicate sid/seq after a reconnect).",
                ).inc(node=self.name)
                metrics.flight.record(
                    "op-replayed", frame.header.get("trace"),
                    node=self.name, seq=seq,
                )
                conn.sendall(sess.last_reply)
                return caches, len(sess.last_reply), False
            if seq != sess.last_seq + 1:
                proto.write_frame(conn, proto.error_frame(
                    f"seq {seq} does not follow applied seq "
                    f"{sess.last_seq} for session {sid!r}",
                    code=proto.ERR_BAD_SEQ,
                ))
                return caches, 0, False
            out, sess.caches = self._execute(frame, sess.caches)
            data = proto.encode_frame(
                proto.tensor_frame(out, trace=frame.header.get("trace"))
            )
            # Commit replay state BEFORE the send: if the reply is lost on
            # the wire, the retried (sid, seq) must find it here.
            sess.last_seq, sess.last_reply = seq, data
        written = self._send_reply(conn, data)
        self._record_op_bytes(len(frame.payload), len(out.data))
        return caches, written, True

    def _send_reply(self, conn: socket.socket, data: bytes) -> int:
        """Send an encoded reply frame, honoring worker.reply fault specs
        (drop = never send — the op applied, the reply is lost; truncate =
        partial frame then tear the connection down)."""
        spec = faults.check("worker.reply", node=self.name)
        if spec is not None:
            if spec.kind == "drop":
                return 0
            if spec.kind == "truncate":
                conn.sendall(data[: max(1, int(len(data) * spec.frac))])
                raise _ConnectionTorn()
            if spec.kind == "delay":
                faults.sleep(spec)
        conn.sendall(data)
        return len(data)

    def _execute(self, frame, caches):
        """Run one FORWARD op; returns (out WireTensor, caches)."""
        ranges = [tuple(r) for r in frame.header["ranges"]]
        pos = frame.header["pos"]
        trace_id = frame.header.get("trace")
        if trace_id is not None:
            log.debug("op trace=%s pos=%s ranges=%s", trace_id, pos, ranges)
        x = wire_to_jax(frame.tensor(), self.dtype)
        if "batch" in frame.header:
            return self._forward_batch(frame, ranges, pos, x, caches)
        cache_batch = next(iter(caches.values())).k.shape[1]
        if x.shape[0] != cache_batch:
            if pos == 0:
                # New sequence at a new batch size: re-make this connection's
                # caches to match (batch>1 lockstep masters share the worker
                # protocol with single-stream ones).
                caches = self._fresh_caches(batch=int(x.shape[0]))
            else:
                raise ValueError(
                    f"batch changed mid-sequence: cache has {cache_batch} "
                    f"rows, activation has {x.shape[0]} (pos={pos}); "
                    "RESET or restart at pos 0 first"
                )
        for r in ranges:
            if r not in self.range_params:
                raise ValueError(f"range {r} not owned (have {self.ranges})")
            x, caches[r] = self._run(
                self.range_params[r],
                x,
                caches[r],
                jnp.int32(pos),
                # Chunked-prefill continuation: a multi-token chunk at pos > 0
                # must attend over the cache prefix, not just within itself.
                cached_prefill=M.is_cached_prefill(pos, x.shape[1]),
            )
        return jax_to_wire(x), caches

    def _forward_batch(self, frame, ranges, pos, x, caches):
        """Lockstep batch op over this connection's caches (see run_b* jits).

        Kinds: "prefill" (pos 0, fresh B-row caches), "decode" (one token at
        slot == pos), "join" (single row scattered into ``lane``).
        """
        b = frame.header["batch"]
        kind = b["kind"]
        pads = jnp.asarray(b["pads"], jnp.int32)
        if kind == "prefill":
            # Every epoch starts here: re-make this connection's caches at
            # the incoming batch (stale prior-epoch state must never leak).
            caches = self._fresh_caches(batch=int(x.shape[0]))
        else:
            cache_batch = next(iter(caches.values())).k.shape[1]
            if kind == "join":
                if int(x.shape[0]) != 1:
                    raise ValueError(
                        f"join expects a single row, got {int(x.shape[0])}"
                    )
                if int(b["lane"]) >= cache_batch:
                    raise ValueError(
                        f"join lane {b['lane']} out of range for batch "
                        f"{cache_batch}"
                    )
            elif kind in ("decode", "verify") and int(x.shape[0]) != cache_batch:
                raise ValueError(
                    f"batch {kind} with {int(x.shape[0])} rows against "
                    f"{cache_batch}-row caches; prefill the epoch first"
                )
        for r in ranges:
            if r not in self.range_params:
                raise ValueError(f"range {r} not owned (have {self.ranges})")
            if kind == "prefill":
                x, caches[r] = self._run_bprefill(
                    self.range_params[r], x, caches[r], pads,
                    jnp.asarray(b["ends"], jnp.int32),
                )
            elif kind == "decode":
                x, caches[r] = self._run_bdecode(
                    self.range_params[r], x, caches[r], pads, jnp.int32(pos)
                )
            elif kind == "join":
                x, caches[r] = self._run_bjoin(
                    self.range_params[r], x, caches[r], pads,
                    jnp.asarray(b["ends"], jnp.int32), jnp.int32(b["lane"]),
                )
            elif kind == "verify":
                # Speculative verify: a cached chunk written at slot == pos.
                x, caches[r] = self._run_bverify(
                    self.range_params[r], x, caches[r], pads, jnp.int32(pos)
                )
            else:
                raise ValueError(f"unknown batch kind {kind!r}")
        return jax_to_wire(x), caches
