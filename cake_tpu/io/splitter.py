"""Model splitter: carve a checkpoint into per-worker bundles.

Covers the reference's ``cake-split-model`` tool (cake-split-model/src/main.rs):
for each topology worker, filter the safetensors weight_map by layer ownership
(main.rs:80-106), copy only the owned tensors into a reduced checkpoint
(main.rs:108-142), and emit ``{worker}-node/model/`` with a rewritten index, the
reduced safetensors, a single-entry topology.yml, and the model config
(main.rs:161-224), then validate the bundle round-trips (main.rs:202-208).

Design notes vs the reference:
  * Output is written as ONE ``reduced.safetensors`` per worker with a fresh
    contiguous layout (the reference also rewrites data, main.rs:120-137).
  * ``config.json`` and (if present) ``tokenizer.json`` are copied into each
    bundle so a worker dir is self-sufficient.
  * Pure-Python safetensors writer (io.safetensors_io) — no framework dep.
"""

from __future__ import annotations

import json
import logging
import shutil
import struct
from pathlib import Path

import numpy as np

from cake_tpu.io.safetensors_io import (
    INDEX_FILE,
    SafetensorsReader,
    open_checkpoint,
)
from cake_tpu.parallel.topology import Topology

log = logging.getLogger("cake_tpu.splitter")

REDUCED_FILE = "reduced.safetensors"


def _write_safetensors(path: Path, tensors: dict[str, tuple[np.ndarray, str]]) -> int:
    """Write {name: (raw_array, safetensors_dtype)} preserving raw dtypes."""
    header: dict[str, dict] = {}
    offset = 0
    for name, (arr, st_dtype) in tensors.items():
        nbytes = arr.nbytes
        header[name] = {
            "dtype": st_dtype,
            "shape": list(arr.shape),
            "data_offsets": [offset, offset + nbytes],
        }
        offset += nbytes
    header_bytes = json.dumps(header).encode()
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(header_bytes)))
        f.write(header_bytes)
        for arr, _ in tensors.values():
            f.write(arr.tobytes())
    return offset


def split_model(
    model_dir: str | Path,
    topology_path: str | Path,
    output_dir: str | Path,
) -> list[Path]:
    """Produce ``{worker}-node/model`` bundles; returns the bundle paths."""
    model_dir = Path(model_dir)
    output_dir = Path(output_dir)
    topology = Topology.from_path(topology_path)
    reader = open_checkpoint(model_dir)

    bundles: list[Path] = []
    for name, node in topology.nodes.items():
        owned = sorted(t for t in reader.names() if node.is_layer_owner(t))
        if not owned:
            log.warning("worker %s owns no tensors, skipping", name)
            continue
        bundle = output_dir / f"{name}-node"
        bundle_model = bundle / "model"
        bundle_model.mkdir(parents=True, exist_ok=True)

        tensors: dict[str, tuple[np.ndarray, str]] = {
            t: (reader.numpy(t), reader.st_dtype(t)) for t in owned
        }
        total = _write_safetensors(bundle_model / REDUCED_FILE, tensors)

        with open(bundle_model / INDEX_FILE, "w") as f:
            json.dump(
                {
                    "metadata": {"total_size": total},
                    "weight_map": {t: REDUCED_FILE for t in tensors},
                },
                f,
                indent=2,
            )
        # Self-sufficient bundle: config + tokenizer + single-node topology
        # (split-model main.rs:176-223 writes the reduced topology the same way).
        shutil.copy(model_dir / "config.json", bundle_model / "config.json")
        tok = model_dir / "tokenizer.json"
        if tok.exists():
            shutil.copy(tok, bundle_model / "tokenizer.json")
        Topology({name: node}).save(bundle / "topology.yml")

        _validate_bundle(bundle_model, list(tensors))
        log.info(
            "wrote %s: %d tensors, %.1f MiB", bundle, len(tensors), total / 2**20
        )
        bundles.append(bundle)
    return bundles


def _validate_bundle(bundle_model: Path, expected: list[str]) -> None:
    """Round-trip validation (split-model main.rs:202-208)."""
    r = SafetensorsReader([bundle_model / REDUCED_FILE])
    names = set(r.names())
    missing = set(expected) - names
    if missing:
        raise RuntimeError(f"bundle {bundle_model} missing tensors: {missing}")
    for t in expected:
        r.numpy(t)  # decodable


def main(argv: list[str] | None = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="cake-tpu-split-model",
        description="split a checkpoint into per-worker bundles by topology",
    )
    p.add_argument("--model", required=True, help="source checkpoint directory")
    p.add_argument("--topology", required=True, help="topology YAML")
    p.add_argument("--output", required=True, help="output directory")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    bundles = split_model(args.model, args.topology, args.output)
    print(f"wrote {len(bundles)} worker bundles under {args.output}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
