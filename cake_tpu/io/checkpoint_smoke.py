"""Full-size checkpoint IO smoke: resolve -> mmap -> split -> serve, for real.

The test suite exercises the multi-file/fused/bf16 layouts at reduced scale
(tests/test_checkpoint_smoke.py); THIS tool runs the whole documented
deployment flow against a checkpoint with real-model geometry and multi-GB
footprint — the scale where mmap behavior, index resolution over many
shards, splitter IO, and worker range loads actually get stressed:

    python -m cake_tpu.io.checkpoint_smoke --dir /tmp/ckpt_smoke

  1. writes a full-width Llama-3-8B-geometry checkpoint (hidden 4096,
     inter 14336, 32q/8kv heads, vocab 128256; depth --layers, default 8 =
     ~4.5 GB) as bf16 HF-style shards of --shard-gb each;
  2. resolves the index, mmaps, and loads it like any user checkpoint;
  3. splits it with the real splitter into two worker bundles;
  4. starts two live TCP workers on localhost, serves a greedy generation
     through the distributed master, and compares token-for-token against
     the single-process load of the same files.

Prints one PASS/FAIL line plus stage timings. Mirrors the reference's
documented workflow (README.md:54-121: split-model then serve) at the
reference's real scale. Zero-egress environments cannot download true
checkpoints, so the weights are random — every IO property that matters
(multi-file index, bf16 storage, file boundaries inside layer ranges,
range-selective worker loads) is real.
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--dir", required=True, help="working directory (multi-GB)")
    p.add_argument("--layers", type=int, default=8)
    p.add_argument("--shard-gb", type=float, default=1.0)
    p.add_argument("--tokens", type=int, default=4)
    p.add_argument(
        "--skip-write", action="store_true",
        help="reuse an existing checkpoint in --dir",
    )
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import yaml

    from cake_tpu.io.safetensors_io import (
        load_params,
        resolve_checkpoint_files,
        save_sharded_checkpoint,
    )
    from cake_tpu.io.splitter import split_model
    from cake_tpu.models.llama import model as M
    from cake_tpu.models.llama.chat import Message
    from cake_tpu.models.llama.config import LlamaConfig
    from cake_tpu.models.llama.generator import (
        LlamaGenerator,
        LocalForwardStep,
        SamplingConfig,
    )
    from cake_tpu.models.llama.tokenizer import ByteTokenizer
    from cake_tpu.parallel.topology import Topology
    from cake_tpu.runtime.master import DistributedForwardStep
    from cake_tpu.runtime.worker import Worker

    base = Path(args.dir)
    model_dir = base / "model"
    config = LlamaConfig(
        hidden_size=4096,
        intermediate_size=14336,
        vocab_size=128256,
        num_hidden_layers=args.layers,
        num_attention_heads=32,
        num_key_value_heads=8,
        rope_theta=500000.0,
        max_position_embeddings=256,
        bos_token_id=256,
        eos_token_ids=(128001,),
    )
    times: dict[str, float] = {}

    if not args.skip_write:
        t0 = time.perf_counter()
        params = M.init_params(config, jax.random.PRNGKey(0), jnp.bfloat16)
        save_sharded_checkpoint(
            model_dir, params, config,
            max_shard_bytes=int(args.shard_gb * (1 << 30)), dtype=jnp.bfloat16,
        )
        del params
        times["write_s"] = time.perf_counter() - t0

    files = resolve_checkpoint_files(model_dir)
    total_gb = sum(f.stat().st_size for f in files) / 1e9
    print(f"checkpoint: {len(files)} shard files, {total_gb:.2f} GB", flush=True)
    if len(files) < 2:
        print("FAIL: expected a multi-file index")
        return 1

    half = args.layers // 2
    topo_dict = {
        "w1": {"host": "placeholder", "layers": [f"model.layers.0-{half - 1}"]},
        "w2": {
            "host": "placeholder",
            "layers": [f"model.layers.{half}-{args.layers - 1}"],
        },
    }
    topo_path = base / "topology.yml"
    topo_path.write_text(yaml.safe_dump(topo_dict))

    t0 = time.perf_counter()
    split_model(model_dir, topo_path, base / "split")
    times["split_s"] = time.perf_counter() - t0
    bundles = {n: base / "split" / f"{n}-node" / "model" for n in ("w1", "w2")}

    t0 = time.perf_counter()
    local_params = load_params(model_dir, config, jnp.float32)
    times["load_s"] = time.perf_counter() - t0

    sampling = SamplingConfig(temperature=0.0, repeat_penalty=1.0)

    def run(step):
        gen = LlamaGenerator(config, step, ByteTokenizer(), sampling)
        gen.add_message(Message.user("full size smoke"))
        gen.generate(args.tokens)
        return list(gen.generated_token_ids)

    t0 = time.perf_counter()
    oracle = run(
        LocalForwardStep(
            config, local_params, max_seq_len=128, cache_dtype=jnp.float32
        )
    )
    times["local_generate_s"] = time.perf_counter() - t0
    del local_params

    topo = Topology.from_dict(topo_dict)
    workers = []
    try:
        t0 = time.perf_counter()
        for name in ("w1", "w2"):
            w = Worker(
                name, bundles[name], topo, ("127.0.0.1", 0),
                dtype=jnp.float32, max_seq_len=128,
            )
            w.start()
            topo.nodes[name].host = f"127.0.0.1:{w.address[1]}"
            workers.append(w)
        times["workers_up_s"] = time.perf_counter() - t0
        step = DistributedForwardStep(
            config, model_dir, topo, dtype=jnp.float32, max_seq_len=128
        )
        try:
            t0 = time.perf_counter()
            served = run(step)
            times["tcp_generate_s"] = time.perf_counter() - t0
        finally:
            step.close()
    finally:
        for w in workers:
            w.stop()

    timing = " ".join(f"{k}={v:.1f}" for k, v in times.items())
    if served == oracle and len(oracle) == args.tokens:
        print(f"PASS tokens={oracle} {timing}")
        return 0
    print(f"FAIL local={oracle} tcp={served} {timing}")
    return 1


if __name__ == "__main__":
    sys.exit(main())
