"""Safetensors weight loading: checkpoint directory -> stacked param pytree.

Covers the reference's loading path (cake-core/src/utils/mod.rs:32-104): resolve the
file list from ``model.safetensors.index.json``'s weight_map, fall back to a single
``model.safetensors``, and mmap — only tensors actually requested are materialized.

TPU-first differences:
  * Per-layer weights land STACKED [n_layers, ...] (see models/llama/model.py), and a
    worker loading a block range [lo, hi) stacks only its own layers — the equivalent
    of the reference worker loading only its topology-assigned blocks
    (worker.rs:95-108).
  * Linear weights are transposed from HF's [out, in] to [in, out] once at load.
  * Loading is zero-copy up to the dtype cast: numpy mmap views feed jnp.asarray.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Iterator

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.model import Params

INDEX_FILE = "model.safetensors.index.json"
SINGLE_FILE = "model.safetensors"

# HF tensor-name templates for one decoder layer, keyed by our stacked-param name.
# transpose=True for linear weights stored [out, in] in the checkpoint.
_LAYER_TEMPLATES: dict[str, tuple[str, bool]] = {
    "wq": ("model.layers.{i}.self_attn.q_proj.weight", True),
    "wk": ("model.layers.{i}.self_attn.k_proj.weight", True),
    "wv": ("model.layers.{i}.self_attn.v_proj.weight", True),
    "wo": ("model.layers.{i}.self_attn.o_proj.weight", True),
    "w_gate": ("model.layers.{i}.mlp.gate_proj.weight", True),
    "w_up": ("model.layers.{i}.mlp.up_proj.weight", True),
    "w_down": ("model.layers.{i}.mlp.down_proj.weight", True),
    "ln_attn": ("model.layers.{i}.input_layernorm.weight", False),
    "ln_mlp": ("model.layers.{i}.post_attention_layernorm.weight", False),
}

# Optional per-layer tensors: QKV biases (Qwen2 family, config.attention_bias).
# Loaded only when present in the checkpoint; [out]-shaped, no transpose.
_LAYER_BIAS_TEMPLATES: dict[str, tuple[str, bool]] = {
    "bq": ("model.layers.{i}.self_attn.q_proj.bias", False),
    "bk": ("model.layers.{i}.self_attn.k_proj.bias", False),
    "bv": ("model.layers.{i}.self_attn.v_proj.bias", False),
}

# Qwen3 family: per-head q/k RMSNorm weights ([head_dim], no transpose),
# loaded only when present in the checkpoint.
_QK_NORM_TEMPLATES: dict[str, tuple[str, bool]] = {
    "q_norm": ("model.layers.{i}.self_attn.q_norm.weight", False),
    "k_norm": ("model.layers.{i}.self_attn.k_norm.weight", False),
}

# Gemma-2 layers carry four norms; these override/extend the two-norm
# templates when present in the checkpoint.
_GEMMA2_NORM_TEMPLATES: dict[str, tuple[str, bool]] = {
    "ln_mlp": ("model.layers.{i}.pre_feedforward_layernorm.weight", False),
    "ln_post_attn": ("model.layers.{i}.post_attention_layernorm.weight", False),
    "ln_post_mlp": ("model.layers.{i}.post_feedforward_layernorm.weight", False),
}

# MoE layers: the dense-MLP templates are replaced by a router plus
# per-expert SwiGLU weights, stacked [n_experts, in, out] at load. Mixtral
# and Qwen2-MoE use different tensor names (and the latter adds an always-on
# shared expert); the layout is detected from the checkpoint itself.
_MOE_LAYOUTS: dict[str, dict] = {
    "mixtral": {
        "router": "model.layers.{i}.block_sparse_moe.gate.weight",
        "experts": {
            "w_gate": "model.layers.{i}.block_sparse_moe.experts.{e}.w1.weight",
            "w_up": "model.layers.{i}.block_sparse_moe.experts.{e}.w3.weight",
            "w_down": "model.layers.{i}.block_sparse_moe.experts.{e}.w2.weight",
        },
        "shared": {},
    },
    "qwen2_moe": {
        "router": "model.layers.{i}.mlp.gate.weight",
        "experts": {
            "w_gate": "model.layers.{i}.mlp.experts.{e}.gate_proj.weight",
            "w_up": "model.layers.{i}.mlp.experts.{e}.up_proj.weight",
            "w_down": "model.layers.{i}.mlp.experts.{e}.down_proj.weight",
        },
        "shared": {
            "sh_gate": "model.layers.{i}.mlp.shared_expert.gate_proj.weight",
            "sh_up": "model.layers.{i}.mlp.shared_expert.up_proj.weight",
            "sh_down": "model.layers.{i}.mlp.shared_expert.down_proj.weight",
            "se_gate": "model.layers.{i}.mlp.shared_expert_gate.weight",
        },
    },
}

_DTYPES = {
    "F64": np.float64,
    "F32": np.float32,
    "F16": np.float16,
    "BF16": None,  # no numpy bf16; handled as uint16 view -> jnp
    "I64": np.int64,
    "I32": np.int32,
    "I16": np.int16,
    "I8": np.int8,
    "U8": np.uint8,
    "BOOL": np.bool_,
}


class SafetensorsReader:
    """Lazy mmap'd reader over one or more safetensors files.

    The file format is simple enough (8-byte LE header length, JSON header, raw
    little-endian tensor data) that reading it directly beats pulling in a
    framework dependency; this also lets bf16 tensors pass through to JAX without
    a float32 detour.
    """

    def __init__(self, paths: list[Path]):
        self._entries: dict[str, tuple[np.memmap, dict]] = {}
        self._mmaps: list[np.memmap] = []
        for path in paths:
            with open(path, "rb") as f:
                header_len = int.from_bytes(f.read(8), "little")
                header = json.loads(f.read(header_len))
            data_offset = 8 + header_len
            mm = np.memmap(path, dtype=np.uint8, mode="r", offset=data_offset)
            self._mmaps.append(mm)
            for name, meta in header.items():
                if name == "__metadata__":
                    continue
                self._entries[name] = (mm, meta)

    def names(self) -> Iterator[str]:
        return iter(self._entries)

    def __contains__(self, name: str) -> bool:
        return name in self._entries

    def shape(self, name: str) -> tuple[int, ...]:
        return tuple(self._entries[name][1]["shape"])

    def st_dtype(self, name: str) -> str:
        """The tensor's safetensors dtype tag (e.g. "F32", "BF16")."""
        return self._entries[name][1]["dtype"]

    def numpy(self, name: str) -> np.ndarray:
        """Raw view of a tensor (bf16 comes back as a uint16 view)."""
        mm, meta = self._entries[name]
        lo, hi = meta["data_offsets"]
        buf = mm[lo:hi]
        shape = tuple(meta["shape"])
        st_dtype = meta["dtype"]
        if st_dtype == "BF16":
            return buf.view(np.uint16).reshape(shape)
        np_dtype = _DTYPES.get(st_dtype)
        if np_dtype is None:
            raise ValueError(f"unsupported safetensors dtype {st_dtype!r}")
        return buf.view(np_dtype).reshape(shape)

    def jax(self, name: str, dtype: jnp.dtype, transpose: bool = False) -> jnp.ndarray:
        mm, meta = self._entries[name]
        arr = self.numpy(name)
        if meta["dtype"] == "BF16":
            x = jnp.asarray(arr).view(jnp.bfloat16)
        else:
            x = jnp.asarray(arr)
        if transpose:
            x = x.T
        return x.astype(dtype)


def resolve_checkpoint_files(model_dir: str | Path) -> list[Path]:
    """File list from the index's weight_map, else the single-file fallback
    (utils/mod.rs:32-82)."""
    model_dir = Path(model_dir)
    index = model_dir / INDEX_FILE
    if index.exists():
        with open(index) as f:
            weight_map: dict[str, str] = json.load(f)["weight_map"]
        return [model_dir / fname for fname in sorted(set(weight_map.values()))]
    single = model_dir / SINGLE_FILE
    if single.exists():
        return [single]
    raise FileNotFoundError(f"no {INDEX_FILE} or {SINGLE_FILE} in {model_dir}")


def open_checkpoint(model_dir: str | Path) -> SafetensorsReader:
    return SafetensorsReader(resolve_checkpoint_files(model_dir))


_PHI3_QKV_TEMPLATE = "model.layers.{i}.self_attn.qkv_proj.weight"
_PHI3_GATE_UP_TEMPLATE = "model.layers.{i}.mlp.gate_up_proj.weight"


def _has_tensor(reader: SafetensorsReader, name: str) -> bool:
    """Present as plain OR quantized storage (hf_tensor_dict suffixes)."""
    return name in reader or name + ".q8" in reader or name + ".q4" in reader


def _read_stacked(
    reader: SafetensorsReader,
    names: list[str],
    dtype: jnp.dtype,
    transpose: bool,
):
    """Stack one weight across layers; reconstructs quantized leaves.

    Quantized tensors (``.q8``/``.q4`` + ``.scale``, written by
    hf_tensor_dict from a quantize_params tree) are stored in compute
    orientation and round-trip bit-identically — no dequantize, no re-cast.
    A tree-level stack over read_weight, so the suffix dispatch lives once.
    """
    return jax.tree.map(
        lambda *xs: jnp.stack(xs),
        *[read_weight(reader, n, dtype, transpose) for n in names],
    )


def _read_stacked2(
    reader: SafetensorsReader,
    names2d: list[list[str]],
    dtype: jnp.dtype,
):
    """[n_layers, n_experts, ...] stacking of MoE expert weights, quantized
    or plain (expert stacks are int8 under the mixed int4 mode) — a
    per-layer _read_stacked plus one tree-level stack, so the suffix logic
    exists once."""
    rows = [_read_stacked(reader, row, dtype, True) for row in names2d]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *rows)


def read_weight(
    reader: SafetensorsReader,
    name: str,
    dtype: jnp.dtype,
    transpose: bool = False,
):
    """One weight by HF name — plain array or reconstructed quantized leaf
    (callers that read head tensors directly, e.g. runtime/master.py).
    Reads the single tensor directly: no stack/unstack transient."""
    from cake_tpu.ops.quant import Quant4Weight, QuantWeight

    for suf, cls in ((".q4", Quant4Weight), (".q8", QuantWeight)):
        if name + suf in reader:
            return cls(
                w=jnp.asarray(reader.numpy(name + suf)),
                scale=jnp.asarray(reader.numpy(name + ".scale")),
            )
    return reader.jax(name, dtype, transpose=transpose)


def load_layer_params(
    reader: SafetensorsReader,
    lo: int,
    hi: int,
    dtype: jnp.dtype = jnp.bfloat16,
    config: LlamaConfig | None = None,
) -> Params:
    """Load block range [lo, hi) as stacked [hi-lo, ...] per-weight arrays.

    Quantized checkpoints (io/quantizer.py) reconstruct their
    QuantWeight/Quant4Weight leaves directly — the full-precision weights
    never materialize (an int4 8B loads ~4 GB of packed bytes, not 15)."""
    out: Params = {}
    templates = dict(_LAYER_TEMPLATES)
    for key, entry in (
        *_LAYER_BIAS_TEMPLATES.items(),
        *_QK_NORM_TEMPLATES.items(),
    ):
        if entry[0].format(i=lo) in reader:
            templates[key] = entry
    if _GEMMA2_NORM_TEMPLATES["ln_mlp"][0].format(i=lo) in reader:
        # Gemma-2/3 four-norm layout: HF's post_attention_layernorm is a real
        # POST-attention norm there (in Llama it is the pre-MLP norm), and
        # the pre-MLP norm is pre_feedforward_layernorm.
        templates.update(_GEMMA2_NORM_TEMPLATES)
        if _QK_NORM_TEMPLATES["q_norm"][0].format(i=lo) in reader:
            # Gemma-3 (four norms + qk-norm): the 5:1 window pattern and the
            # per-layer rope plane come from the config (layer_types is not
            # a tensor), sliced to this block range so stages/workers keep
            # absolute layer parity.
            if config is None or config.sliding_pattern is None:
                raise ValueError(
                    "gemma3 checkpoint needs the model config (layer_types "
                    "drives per-layer windows and rope selection)"
                )
            flags = config.sliding_pattern[lo:hi]
            out["win_flag"] = jnp.asarray(flags)
            out["rope_sel"] = jnp.asarray(flags, jnp.int32)
        else:
            # Gemma-2: the alternating local/global pattern is positional.
            out["win_flag"] = (jnp.arange(lo, hi) % 2) == 0
    layout = next(
        (
            lay
            for lay in _MOE_LAYOUTS.values()
            if lay["router"].format(i=lo) in reader
        ),
        None,
    )
    if layout is not None:
        for key in layout["experts"]:
            del templates[key]  # dense-MLP names are absent in MoE checkpoints
        n_experts = 0
        while _has_tensor(
            reader, layout["experts"]["w_gate"].format(i=lo, e=n_experts)
        ):
            n_experts += 1
        out["router"] = jnp.stack(
            [
                reader.jax(layout["router"].format(i=i), dtype, transpose=True)
                for i in range(lo, hi)
            ]
        )
        for key, tmpl in layout["experts"].items():
            out[key] = _read_stacked2(
                reader,
                [
                    [tmpl.format(i=i, e=e) for e in range(n_experts)]
                    for i in range(lo, hi)
                ],
                dtype,
            )
        # Shared-expert tensors: the config is the authority. An explicit
        # shared_expert_intermediate_size=0 skips them; a nonzero size with
        # absent tensors is an incomplete checkpoint and must fail loudly
        # (the read raises on the missing name). With no config, trust the
        # checkpoint's own layout.
        se = None if config is None else config.shared_expert_intermediate_size
        for key, tmpl in layout["shared"].items():
            if se == 0 or (
                se is None and not _has_tensor(reader, tmpl.format(i=lo))
            ):
                continue
            out[key] = _read_stacked(
                reader,
                [tmpl.format(i=i) for i in range(lo, hi)],
                dtype,
                True,
            )
    fused_qkv = _PHI3_QKV_TEMPLATE.format(i=lo) in reader
    if fused_qkv:
        # Phi-3 fuses q|k|v rows into one tensor (and gate|up likewise);
        # split at load so the model core sees the standard layout. The
        # split points need the head geometry, so the config is required.
        if config is None:
            raise ValueError(
                "fused qkv_proj checkpoint (phi3) needs the model config "
                "to split projections"
            )
        for key in ("wq", "wk", "wv", "w_gate", "w_up"):
            del templates[key]
        hd = config.head_dim
        n_q = config.num_attention_heads * hd
        n_kv = config.num_key_value_heads * hd
        qs, ks, vs, gs, us = [], [], [], [], []
        for i in range(lo, hi):
            qkv = reader.jax(_PHI3_QKV_TEMPLATE.format(i=i), dtype, transpose=True)
            if qkv.shape[1] != n_q + 2 * n_kv:
                raise ValueError(
                    f"layer {i}: fused qkv width {qkv.shape[1]} does not "
                    f"match config geometry q={n_q} + 2*kv={2 * n_kv} — "
                    "config.json and checkpoint disagree"
                )
            qs.append(qkv[:, :n_q])
            ks.append(qkv[:, n_q : n_q + n_kv])
            vs.append(qkv[:, n_q + n_kv :])
            gu = reader.jax(
                _PHI3_GATE_UP_TEMPLATE.format(i=i), dtype, transpose=True
            )
            if gu.shape[1] % 2:
                raise ValueError(
                    f"layer {i}: fused gate_up width {gu.shape[1]} is odd"
                )
            inter = gu.shape[1] // 2
            gs.append(gu[:, :inter])
            us.append(gu[:, inter:])
        out["wq"] = jnp.stack(qs)
        out["wk"] = jnp.stack(ks)
        out["wv"] = jnp.stack(vs)
        out["w_gate"] = jnp.stack(gs)
        out["w_up"] = jnp.stack(us)
    for key, (tmpl, transpose) in templates.items():
        out[key] = _read_stacked(
            reader,
            [tmpl.format(i=i) for i in range(lo, hi)],
            dtype,
            transpose,
        )
    return out


def load_params(
    model_dir: str | Path,
    config: LlamaConfig,
    dtype: jnp.dtype = jnp.bfloat16,
    layer_range: tuple[int, int] | None = None,
) -> Params:
    """Load a full param pytree (or, for a worker, just a block range's layers).

    With ``layer_range`` set, only the stacked layer shard is returned — embedding,
    final norm, and lm_head stay on the master (llama.rs:178-196 vs worker.rs:95-108).
    """
    reader = open_checkpoint(model_dir)
    if layer_range is not None:
        lo, hi = layer_range
        return {"layers": load_layer_params(reader, lo, hi, dtype, config)}
    params: Params = {
        "embed": reader.jax("model.embed_tokens.weight", dtype),
        "layers": load_layer_params(
            reader, 0, config.num_hidden_layers, dtype, config
        ),
        "ln_f": reader.jax("model.norm.weight", dtype),
    }
    if not config.tie_word_embeddings:
        params["lm_head"] = read_weight(reader, "lm_head.weight", dtype, True)
    return params


def hf_tensor_dict(
    params: Params, config: LlamaConfig, dtype: jnp.dtype = jnp.float32
) -> dict[str, np.ndarray]:
    """Flatten a param tree into HF-named checkpoint tensors ([out, in] rows).

    THE inverse of load_layer_params' name mapping, shared by both fixture
    writers (single-file and sharded) so writer and reader naming cannot
    drift. (The splitter never rebuilds names — it filters the reader's raw
    tensors by ownership, io/splitter.py.) ``dtype`` is the STORAGE dtype
    (bf16 for realistic full-size checkpoints; the reader handles
    BF16/F16/F32).

    QUANTIZED leaves (ops/quant.py, e.g. a tree from quantize_params — the
    io/quantizer.py tool's path) store under suffixed names in COMPUTE
    orientation (no [out, in] transpose: the packed int4 in-axis and the
    scale layouts are meaningful as stored):

        {hf name}.q8     int8 [..., in, out]        (int8 weights)
        {hf name}.q4     int8 [..., in//2, out]     (packed int4 nibbles)
        {hf name}.scale  f32  [..., 1|G, out]

    load_layer_params reconstructs the exact QuantWeight/Quant4Weight leaves
    (bit-identical round trip, tests/test_quantized_checkpoint.py)."""
    tensors = head_tensor_dict(params, config, dtype)
    tensors.update(
        layer_tensor_dict(
            params["layers"], config, dtype, 0, config.num_hidden_layers
        )
    )
    return tensors


def head_tensor_dict(
    params: Params, config: LlamaConfig, dtype: jnp.dtype = jnp.float32
) -> dict[str, np.ndarray]:
    """HF-named tensors for the non-layer leaves (embed, final norm, and —
    when untied — lm_head, plain or quantized). The head half of
    hf_tensor_dict, shared with the streaming quantizer so the name/transpose
    contract lives in one place."""
    tensors: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(params["embed"].astype(dtype)),
        "model.norm.weight": np.asarray(params["ln_f"].astype(dtype)),
    }
    if not config.tie_word_embeddings:
        _emit_tensor(tensors, "lm_head.weight", params["lm_head"], True, dtype)
    return tensors


def _emit_tensor(
    tensors: dict, name: str, leaf, transpose: bool, dtype
) -> None:
    from cake_tpu.ops.quant import Quant4Weight, QuantWeight

    if isinstance(leaf, QuantWeight):
        tensors[name + ".q8"] = np.asarray(leaf.w)
        tensors[name + ".scale"] = np.asarray(leaf.scale, np.float32)
    elif isinstance(leaf, Quant4Weight):
        tensors[name + ".q4"] = np.asarray(leaf.w)
        tensors[name + ".scale"] = np.asarray(leaf.scale, np.float32)
    else:
        a = np.asarray(leaf.astype(dtype))
        tensors[name] = a.T.copy() if transpose else a


def layer_tensor_dict(
    layers: Params,
    config: LlamaConfig,
    dtype: jnp.dtype,
    lo: int,
    hi: int,
) -> dict[str, np.ndarray]:
    """HF-named tensors for a stacked layer tree covering ABSOLUTE layers
    [lo, hi) — names carry lo..hi-1, the stack axis indexes 0..hi-lo-1.

    The per-range half of hf_tensor_dict, split out so the offline quantizer
    can stream one block range at a time instead of materializing the whole
    tree (io/quantizer.py)."""
    from cake_tpu.ops.quant import Quant4Weight, QuantWeight

    tensors: dict[str, np.ndarray] = {}

    def emit(name: str, leaf, transpose: bool) -> None:
        _emit_tensor(tensors, name, leaf, transpose, dtype)

    def leaf_slice(leaf, *idx):
        if isinstance(leaf, (QuantWeight, Quant4Weight)):
            w, s = leaf.w, leaf.scale
            for i in idx:
                w, s = w[i], s[i]
            return type(leaf)(w=w, scale=s)
        a = leaf
        for i in idx:
            a = a[i]
        return a

    moe = "router" in layers
    all_templates = {**_LAYER_TEMPLATES, **_LAYER_BIAS_TEMPLATES}
    if "q_norm" in layers:
        all_templates.update(_QK_NORM_TEMPLATES)
    if "ln_post_attn" in layers:
        all_templates.update(_GEMMA2_NORM_TEMPLATES)
    n_range = hi - lo
    # win_flag is positional metadata synthesized at load, never a tensor.
    if moe:
        # Layout by declared family, not params-key sniffing: a qwen2_moe
        # model with the shared expert disabled has no sh_gate but must still
        # write qwen2_moe tensor names to match its own config.json.
        layout = _MOE_LAYOUTS[
            "qwen2_moe"
            if config.model_type in ("qwen2_moe", "qwen3_moe")
            else "mixtral"
        ]
        for key in layout["experts"]:
            del all_templates[key]
        routers = np.asarray(layers["router"].astype(dtype))
        for i in range(routers.shape[0]):
            tensors[layout["router"].format(i=lo + i)] = routers[i].T.copy()
        for key, tmpl in layout["experts"].items():
            leaf = layers[key]
            n_experts = (
                leaf.w.shape[1]
                if isinstance(leaf, (QuantWeight, Quant4Weight))
                else leaf.shape[1]
            )
            for i in range(n_range):
                for e in range(n_experts):
                    emit(tmpl.format(i=lo + i, e=e), leaf_slice(leaf, i, e), True)
        for key, tmpl in layout["shared"].items():
            if key not in layers:
                continue  # shared expert disabled
            leaf = layers[key]
            for i in range(n_range):
                emit(tmpl.format(i=lo + i), leaf_slice(leaf, i), True)
    for key, (tmpl, transpose) in all_templates.items():
        if key not in layers:
            continue
        leaf = layers[key]
        for i in range(n_range):
            emit(tmpl.format(i=lo + i), leaf_slice(leaf, i), transpose)
    return tensors


_NP_TO_ST = {
    np.dtype(np.float32): "F32",
    np.dtype(np.float16): "F16",
    np.dtype(np.int8): "I8",  # quantized weights (plain or nibble-packed)
}


def _st_dtype(arr: np.ndarray) -> str:
    if arr.dtype in _NP_TO_ST:
        return _NP_TO_ST[arr.dtype]
    if "bfloat16" in str(arr.dtype):
        return "BF16"
    raise ValueError(f"unsupported checkpoint dtype {arr.dtype}")


def write_safetensors(path: Path, tensors: dict[str, np.ndarray]) -> int:
    """Write one .safetensors file; returns its payload byte count."""
    import struct

    header: dict[str, dict] = {}
    offset = 0
    blobs: list[bytes] = []
    for name, arr in tensors.items():
        blob = arr.tobytes()
        header[name] = {
            "dtype": _st_dtype(arr),
            "shape": list(arr.shape),
            "data_offsets": [offset, offset + len(blob)],
        }
        offset += len(blob)
        blobs.append(blob)
    header_bytes = json.dumps(header).encode()
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(header_bytes)))
        f.write(header_bytes)
        for blob in blobs:
            f.write(blob)
    return offset


class ShardedCheckpointWriter:
    """Incremental HF-style multi-file checkpoint writer.

    ``add()`` tensors in any order, in as many calls as you like; shards are
    greedily packed to ``max_shard_bytes`` and FLUSHED TO DISK as they fill,
    so peak memory is one shard regardless of checkpoint size — the seam the
    offline quantizer streams 70B-scale checkpoints through (io/quantizer.py).
    Shards are written under temporary names (the final ``i-of-N`` names need
    the total count) and renamed at ``finish()``, which also writes the
    weight_map index and returns the shard paths. On failure mid-stream call
    ``abort()`` (or use the writer as a context manager, which aborts on
    exception) — it deletes the flushed .tmp shards so a died run doesn't
    strand gigabytes of hidden partial output."""

    def __init__(self, model_dir: str | Path, max_shard_bytes: int = 1 << 30):
        self.dir = Path(model_dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.max_shard_bytes = max_shard_bytes
        self._cur: dict[str, np.ndarray] = {}
        self._cur_bytes = 0
        self._tmp_paths: list[Path] = []
        self._shard_names: list[list[str]] = []
        self._total = 0
        # Stale tmp shards from a previously-died run would otherwise survive
        # next to a smaller successful retry.
        for stale in self.dir.glob(".model-part-*.tmp"):
            stale.unlink()

    def __enter__(self) -> "ShardedCheckpointWriter":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            self.abort()

    def abort(self) -> None:
        """Delete all flushed tmp shards and drop the buffered one."""
        for tmp in self._tmp_paths:
            tmp.unlink(missing_ok=True)
        self._tmp_paths = []
        self._shard_names = []
        self._cur = {}
        self._cur_bytes = 0

    def add(self, tensors: dict[str, np.ndarray]) -> None:
        for name, arr in tensors.items():
            nbytes = arr.size * arr.dtype.itemsize
            if self._cur_bytes and self._cur_bytes + nbytes > self.max_shard_bytes:
                self._flush()
            self._cur[name] = arr
            self._cur_bytes += nbytes

    def _flush(self) -> None:
        if not self._cur:
            return
        path = self.dir / f".model-part-{len(self._tmp_paths):05d}.tmp"
        self._total += write_safetensors(path, self._cur)
        self._tmp_paths.append(path)
        self._shard_names.append(list(self._cur))
        self._cur = {}
        self._cur_bytes = 0

    def finish(self) -> list[Path]:
        self._flush()
        n = len(self._tmp_paths)
        weight_map: dict[str, str] = {}
        paths = []
        for i, (tmp, names) in enumerate(
            zip(self._tmp_paths, self._shard_names), start=1
        ):
            fname = f"model-{i:05d}-of-{n:05d}.safetensors"
            tmp.rename(self.dir / fname)
            for name in names:
                weight_map[name] = fname
            paths.append(self.dir / fname)
        with open(self.dir / INDEX_FILE, "w") as f:
            json.dump(
                {
                    "metadata": {"total_size": self._total},
                    "weight_map": weight_map,
                },
                f,
                indent=2,
            )
        return paths


def save_tiny_checkpoint(
    model_dir: str | Path, params: Params, config: LlamaConfig
) -> None:
    """Write a random-init model as a real safetensors checkpoint (test fixture)."""
    model_dir = Path(model_dir)
    model_dir.mkdir(parents=True, exist_ok=True)
    with open(model_dir / "config.json", "w") as f:
        json.dump(config.to_hf_dict(), f, indent=2)

    tensors = hf_tensor_dict(params, config)
    total = write_safetensors(model_dir / SINGLE_FILE, tensors)

    # An index file too, so the weight_map path (splitter, workers) is exercised.
    with open(model_dir / INDEX_FILE, "w") as f:
        json.dump(
            {
                "metadata": {"total_size": total},
                "weight_map": {name: SINGLE_FILE for name in tensors},
            },
            f,
            indent=2,
        )


def save_sharded_checkpoint(
    model_dir: str | Path,
    params: Params,
    config: LlamaConfig,
    *,
    max_shard_bytes: int = 1 << 30,
    dtype: jnp.dtype = jnp.float32,
) -> list[Path]:
    """Write an HF-style MULTI-FILE checkpoint: model-0000i-of-0000N shards
    packed greedily to ``max_shard_bytes``, plus the weight_map index.

    This is the layout real multi-GB checkpoints ship in (file boundaries
    cut across layers, a worker's block range spans several files) — the
    full-size IO smoke (tests/test_checkpoint_smoke.py, the
    checkpoint_smoke CLI) runs resolve -> mmap -> split -> serve against it.
    Returns the shard paths."""
    model_dir = Path(model_dir)
    model_dir.mkdir(parents=True, exist_ok=True)
    with open(model_dir / "config.json", "w") as f:
        json.dump(config.to_hf_dict(), f, indent=2)

    writer = ShardedCheckpointWriter(model_dir, max_shard_bytes)
    writer.add(hf_tensor_dict(params, config, dtype=dtype))
    return writer.finish()
