"""Offline checkpoint quantizer: ``python -m cake_tpu.io.quantizer``.

Sits beside the splitter in the reference's offline-tooling family
(cake-split-model, split-model/src/main.rs:55-223 — carve a checkpoint into
what each process actually loads): this tool quantizes a full-precision HF
checkpoint ONCE and writes a checkpoint whose linear weights are stored
int8 (per-output-channel scales) or packed int4 (group-128 scales), under
the suffixed names documented in io/safetensors_io.hf_tensor_dict.

Why offline: runtime ``--quantize`` must stream the full bf16 weights from
disk before rounding them — an int4-quantized 8B checkpoint is ~4 GB on
disk instead of 15, loads in one pass with no full-precision materialization
(safetensors_io reconstructs the Quant leaves directly), and composes with
the splitter (quantized tensor names keep their ``model.layers.N.`` prefixes,
so per-worker bundles carve exactly the same way).

The written tree round-trips bit-identically: loading the quantized
checkpoint yields the same leaves as calling quantize_params in memory, so
every numerics test pinning runtime quantization covers the offline path too
(tests/test_quantized_checkpoint.py asserts this equivalence).

Family quirks are canonicalized at quantize time — a Phi-3 source (fused
qkv/gate_up storage) writes standard per-projection names, which the loader
prefers; MoE expert stacks stay int8 under ``--mode int4`` (the documented
mixed mode, ops/quant.py).
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
from pathlib import Path

import jax.numpy as jnp

from cake_tpu.models.llama.config import LlamaConfig


def quantize_checkpoint(
    model_dir: str | Path,
    output_dir: str | Path,
    mode: str = "int8",
    *,
    dtype: jnp.dtype = jnp.bfloat16,
    max_shard_bytes: int = 1 << 30,
) -> Path:
    """Quantize ``model_dir`` into ``output_dir``; returns the output path.

    ``dtype`` is the storage dtype for the UNQUANTIZED leaves (embedding,
    norms, routers, biases). Non-tensor files (tokenizer, generation config)
    are copied through so the output is a drop-in checkpoint directory.
    """
    from cake_tpu.io.safetensors_io import load_params, save_sharded_checkpoint
    from cake_tpu.ops.quant import quantize_params, tree_quantization

    model_dir, output_dir = Path(model_dir), Path(output_dir)
    config = LlamaConfig.from_model_dir(model_dir)
    params = load_params(model_dir, config, dtype)
    if tree_quantization(params):
        raise ValueError(
            f"{model_dir} is already quantized ({tree_quantization(params)})"
        )
    qparams = quantize_params(params, mode)
    save_sharded_checkpoint(
        output_dir, qparams, config,
        max_shard_bytes=max_shard_bytes, dtype=dtype,
    )

    # Stamp the mode into config.json (informational — the loader detects
    # quantization from tensor names) and carry the non-tensor files over.
    cfg_path = output_dir / "config.json"
    with open(cfg_path) as f:
        cfg = json.load(f)
    cfg["cake_quantization"] = {"mode": mode}
    with open(cfg_path, "w") as f:
        json.dump(cfg, f, indent=2)
    # Weight files in ANY format stay behind (HF dirs often ship torch .bin
    # alongside safetensors — copying those would silently undo the size win).
    skip_suffixes = (".safetensors", ".bin", ".pth", ".pt", ".gguf")
    for p in model_dir.iterdir():
        if (
            p.is_file()
            and p.suffix not in skip_suffixes
            and not p.name.endswith(".index.json")
            and p.name != "config.json"
        ):
            shutil.copy2(p, output_dir / p.name)
    return output_dir


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="cake-tpu-quantize",
        description="quantize a checkpoint's linear weights offline",
    )
    ap.add_argument("--model", required=True, help="source checkpoint dir")
    ap.add_argument("--output", required=True, help="output checkpoint dir")
    ap.add_argument("--mode", choices=("int8", "int4"), default="int8")
    ap.add_argument(
        "--dtype", choices=("bf16", "f32"), default="bf16",
        help="storage dtype for the unquantized leaves (embed/norms/routers)",
    )
    args = ap.parse_args(argv)
    out = quantize_checkpoint(
        args.model, args.output, args.mode,
        dtype=jnp.bfloat16 if args.dtype == "bf16" else jnp.float32,
    )
    print(f"quantized ({args.mode}) checkpoint written to {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
