"""Offline checkpoint quantizer: ``python -m cake_tpu.io.quantizer``.

Sits beside the splitter in the reference's offline-tooling family
(cake-split-model, split-model/src/main.rs:55-223 — carve a checkpoint into
what each process actually loads): this tool quantizes a full-precision HF
checkpoint ONCE and writes a checkpoint whose linear weights are stored
int8 (per-output-channel scales) or packed int4 (group-128 scales), under
the suffixed names documented in io/safetensors_io.hf_tensor_dict.

Why offline: runtime ``--quantize`` must stream the full bf16 weights from
disk before rounding them — an int4-quantized 8B checkpoint is ~4 GB on
disk instead of 15, loads in one pass with no full-precision materialization
(safetensors_io reconstructs the Quant leaves directly), and composes with
the splitter (quantized tensor names keep their ``model.layers.N.`` prefixes,
so per-worker bundles carve exactly the same way).

The written tree round-trips bit-identically: loading the quantized
checkpoint yields the same leaves as calling quantize_params in memory, so
every numerics test pinning runtime quantization covers the offline path too
(tests/test_quantized_checkpoint.py asserts this equivalence).

Family quirks are canonicalized at quantize time — a Phi-3 source (fused
qkv/gate_up storage) writes standard per-projection names, which the loader
prefers; MoE expert stacks stay int8 under ``--mode int4`` (the documented
mixed mode, ops/quant.py).
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
from pathlib import Path

import jax.numpy as jnp

from cake_tpu.models.llama.config import LlamaConfig


def quantize_checkpoint(
    model_dir: str | Path,
    output_dir: str | Path,
    mode: str = "int8",
    *,
    dtype: jnp.dtype = jnp.bfloat16,
    max_shard_bytes: int = 1 << 30,
    layers_per_chunk: int = 4,
) -> Path:
    """Quantize ``model_dir`` into ``output_dir``; returns the output path.

    STREAMING: layers are loaded, quantized, and appended to the shard
    writer ``layers_per_chunk`` at a time, so peak host memory is one layer
    chunk plus one unflushed shard — a 70B checkpoint quantizes in a few GB
    of RAM, not the ~140 GB a whole-tree load would need. ``dtype`` is the
    storage dtype for the UNQUANTIZED leaves (embedding, norms, routers,
    biases). Non-tensor files (tokenizer, generation config) are copied
    through so the output is a drop-in checkpoint directory.
    """
    from cake_tpu.io.safetensors_io import (
        ShardedCheckpointWriter,
        head_tensor_dict,
        layer_tensor_dict,
        load_layer_params,
        open_checkpoint,
        read_weight,
    )
    from cake_tpu.ops.quant import quantize_layer_tree, quantize_params

    if mode not in ("int8", "int4"):
        raise ValueError(f"unknown quantize mode {mode!r}")
    model_dir, output_dir = Path(model_dir), Path(output_dir)
    config = LlamaConfig.from_model_dir(model_dir)
    reader = open_checkpoint(model_dir)
    quantized_names = [n for n in reader.names() if n.endswith((".q8", ".q4"))]
    if quantized_names:
        # int4 wins the label: the mixed int4 mode stores MoE expert stacks
        # as .q8 by design (ops/quant.py), so any .q4 means int4.
        kind = (
            "int4"
            if any(n.endswith(".q4") for n in quantized_names)
            else "int8"
        )
        raise ValueError(
            f"{model_dir} is already quantized ({kind}); re-quantizing "
            "would corrupt it"
        )

    output_dir.mkdir(parents=True, exist_ok=True)
    with open(output_dir / "config.json", "w") as f:
        cfg = config.to_hf_dict()
        # Stamp the mode (informational — the loader detects quantization
        # from tensor names).
        cfg["cake_quantization"] = {"mode": mode}
        json.dump(cfg, f, indent=2)

    with ShardedCheckpointWriter(output_dir, max_shard_bytes) as writer:
        head = {
            "embed": reader.jax("model.embed_tokens.weight", dtype),
            "ln_f": reader.jax("model.norm.weight", dtype),
        }
        if not config.tie_word_embeddings:
            # lm_head quantizes like the linear it is (quantize_params parity).
            head["lm_head"] = read_weight(reader, "lm_head.weight", dtype, True)
        qhead = quantize_params(head | {"layers": {}}, mode)
        writer.add(head_tensor_dict(qhead, config, dtype))

        n_layers = config.num_hidden_layers
        for lo in range(0, n_layers, layers_per_chunk):
            hi = min(lo + layers_per_chunk, n_layers)
            layers = load_layer_params(reader, lo, hi, dtype, config)
            qlayers = quantize_layer_tree(layers, mode)
            writer.add(layer_tensor_dict(qlayers, config, dtype, lo, hi))
            del layers, qlayers
        writer.finish()
    # Weight files in ANY format stay behind (HF dirs often ship torch .bin
    # alongside safetensors — copying those would silently undo the size win).
    skip_suffixes = (".safetensors", ".bin", ".pth", ".pt", ".gguf")
    for p in model_dir.iterdir():
        if (
            p.is_file()
            and p.suffix not in skip_suffixes
            and not p.name.endswith(".index.json")
            and p.name != "config.json"
        ):
            shutil.copy2(p, output_dir / p.name)
    return output_dir


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="cake-tpu-quantize",
        description="quantize a checkpoint's linear weights offline",
    )
    ap.add_argument("--model", required=True, help="source checkpoint dir")
    ap.add_argument("--output", required=True, help="output checkpoint dir")
    ap.add_argument("--mode", choices=("int8", "int4"), default="int8")
    ap.add_argument(
        "--dtype", choices=("bf16", "f32"), default="bf16",
        help="storage dtype for the unquantized leaves (embed/norms/routers)",
    )
    args = ap.parse_args(argv)
    out = quantize_checkpoint(
        args.model, args.output, args.mode,
        dtype=jnp.bfloat16 if args.dtype == "bf16" else jnp.float32,
    )
    print(f"quantized ({args.mode}) checkpoint written to {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
