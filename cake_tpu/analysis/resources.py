"""Interprocedural resource-lifecycle analysis: protocols, owned sets, proofs.

The serving path rests on manually-paired ownership protocols — KV pages
alloc/release, prefix-cache lease pin/unpin, tenant-quota grant/close
through one choke point, lane register/recycle, retained-KV attach/drop —
and the recurring bug class (PRs 8/10/15) is always a resource acquired on
one path and not released on some exception/shed/cancel path. This module
is the review-time counterpart to the chaos tests' "pool drains"
assertions: a declarative protocol table keyed on the real APIs, plus an
owned-set dataflow walk over the PR 17 shared walk core
(``walk.entry_points`` roots, ``callgraph`` attr-type resolution),
consumed by the ``rules/lifecycle.py`` pack and the ``cake-tpu resources``
CLI.

Three pieces:

  * **Protocol model** (``ResourceModel``) — each protocol declares its
    acquire/release ops, the owning class(es), receiver-name tails for the
    ``getattr``-seam receivers the callgraph cannot type
    (``self._alloc = getattr(backend, "allocator", None)``), transfer
    sinks (registry attrs a known release site drains — leases parked in
    ``_lane_leases``, the quota close parked on ``_on_close``), the
    refund spelling, and shed exception classes. A call site resolves to
    (protocol, acquire|release|refund) through the receiver's class when
    the callgraph can type it, else through the tails; calls inside the
    owning class itself are implementation, not consumption, and produce
    no events (``PageAllocator.release_lanes`` calling ``self.release``
    is the protocol, not a use of it).

  * **Owned-set walk** (``_Walker``) — from every shared entry point,
    track which acquired resources are live at each program point of the
    frame that acquired them, through try/except/finally, early returns,
    and ownership transfers. A ``raise`` whose class escapes the frame
    (no matching handler, no finally that releases) with owned,
    untransferred resources is a leak edge; a second release of the same
    subject on one path — or a release after the subject was transferred
    into a sink — is a double release. Exceptions crossing a call
    boundary are assumed handled by the caller (the caller's own frame is
    checked against the caller's own acquires), and a callee's releases
    propagate to the caller through transitive may-release summaries.

  * **Site census + choke points** — independent of walk reachability,
    every classified call site is tallied per protocol (the engagement
    surface the CLI table and the CI pin test render), and protocols that
    declare a funnel (``TenantMeter.close`` must flow through the
    ``_on_close`` choke point unless it is a ``refund=True`` admission
    rollback) get every release site checked against it lexically.

Conservatism contract (same as the callgraph's and the lock walk's): a
receiver that resolves to neither an owning class nor a declared tail
produces no events; an exception class that cannot be named is assumed
caught; a release clears every owned instance of its protocol when the
subject is ambiguous. The pass stays false-positive-shy; coverage grows
as resolution does.
"""

from __future__ import annotations

import ast
import dataclasses
import weakref

from cake_tpu.analysis import _util as u
from cake_tpu.analysis import callgraph as cg
from cake_tpu.analysis import walk as wk

Site = wk.Site
modname = wk.modname


@dataclasses.dataclass(frozen=True)
class Protocol:
    """One acquire/release pairing, keyed on the real APIs."""

    name: str
    noun: str
    owner_classes: tuple[str, ...]
    acquire_ops: tuple[str, ...]
    release_ops: tuple[str, ...]
    # Receiver-name tails for receivers the callgraph cannot type (getattr
    # seams, parameters). A tail must appear in the LAST dotted component.
    receiver_tails: tuple[str, ...] = ()
    # Attr names whose stores transfer ownership: a subscript/attr store
    # into `self.<sink>[...]` parks the resource in a registry a known
    # release site drains.
    sink_tails: tuple[str, ...] = ()
    # Release ops must flow through a closure assigned to one of these
    # attrs (the choke point); empty = unrestricted.
    funnel_attrs: tuple[str, ...] = ()
    # A truthy keyword by this name turns a release into a refund (the
    # admission-rollback spelling, exempt from the funnel).
    refund_kwarg: str | None = None
    # Exception classes whose escape with this protocol owned is the
    # shed-without-refund bug, not a generic leak.
    shed_exceptions: tuple[str, ...] = ()
    # Record events for calls made from inside the owning class too (for
    # engine-internal protocols whose consumers ARE the owner's methods).
    intra_owner: bool = False


PROTOCOLS: tuple[Protocol, ...] = (
    Protocol(
        name="kv-pages",
        noun="KV page mapping",
        owner_classes=("PageAllocator",),
        acquire_ops=(
            "alloc", "extend", "map_range", "fork", "fork_chain",
            "retain_pages", "make_private",
        ),
        release_ops=(
            "release", "release_pages", "unmap_page", "release_lanes",
            "reset",
        ),
        receiver_tails=("alloc", "allocator"),
    ),
    Protocol(
        name="prefix-lease",
        noun="prefix-cache chain lease",
        owner_classes=("PrefixCache",),
        acquire_ops=("fork",),
        release_ops=("release",),
        receiver_tails=("prefix",),
        sink_tails=("_lane_leases",),
    ),
    Protocol(
        name="quota",
        noun="tenant quota grant",
        owner_classes=("TenantMeter",),
        acquire_ops=("admit",),
        release_ops=("close",),
        receiver_tails=("meter", "quota"),
        funnel_attrs=("_on_close",),
        refund_kwarg="refund",
        shed_exceptions=("EngineOverloaded", "QuotaExceeded"),
    ),
    Protocol(
        name="lanes",
        noun="batch lane registration",
        owner_classes=("BatchEngine",),
        acquire_ops=("_fork_lane",),
        release_ops=("_lane_recycle",),
        intra_owner=True,
    ),
    Protocol(
        name="retained-kv",
        noun="retained KV buffer",
        owner_classes=("PagedLocalBackend", "LocalBatchBackend"),
        acquire_ops=("retain_kv",),
        release_ops=("drop_retained_kv",),
        receiver_tails=("backend",),
    ),
)

# Minimal builtin exception hierarchy for handler matching; in-tree classes
# chain into it via their (resolved) base names.
_BUILTIN_BASES = {
    "RuntimeError": "Exception",
    "NotImplementedError": "RuntimeError",
    "RecursionError": "RuntimeError",
    "ValueError": "Exception",
    "TypeError": "Exception",
    "KeyError": "LookupError",
    "IndexError": "LookupError",
    "LookupError": "Exception",
    "ArithmeticError": "Exception",
    "ZeroDivisionError": "ArithmeticError",
    "OverflowError": "ArithmeticError",
    "AttributeError": "Exception",
    "AssertionError": "Exception",
    "StopIteration": "Exception",
    "OSError": "Exception",
    "IOError": "OSError",
    "TimeoutError": "OSError",
    "ConnectionError": "OSError",
    "BrokenPipeError": "ConnectionError",
    "ConnectionResetError": "ConnectionError",
    "ConnectionRefusedError": "ConnectionError",
    "Exception": "BaseException",
}

_CATCH_ALL = ("Exception", "BaseException")


# --------------------------------------------------------------------- events


@dataclasses.dataclass
class AcquireEv:
    """One tracked acquire site, with how the walk saw it resolved."""

    proto: str
    subject: str | None
    site: Site
    stack: tuple[str, ...]
    func: str  # qualname of the acquiring frame
    outcomes: set = dataclasses.field(default_factory=set)


@dataclasses.dataclass(frozen=True)
class ReleaseEv:
    proto: str
    kind: str  # "release" | "refund"
    subject: tuple | None
    site: Site
    stack: tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class TransferEv:
    """Ownership parked in a registry a known release site drains."""

    proto: str
    sink: str
    subject: str | None
    site: Site
    stack: tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class LeakEv:
    """A raise escaped the acquiring frame with the resource still owned."""

    proto: str
    noun: str
    exc: str
    acquire_site: Site
    raise_site: Site
    func: str
    stack: tuple[str, ...]
    shed: bool  # True -> the refund-missing-on-shed flavor


@dataclasses.dataclass(frozen=True)
class DoubleReleaseEv:
    proto: str
    subject: str
    first: Site
    second: Site
    stack: tuple[str, ...]
    after_transfer: bool


@dataclasses.dataclass(frozen=True)
class ChokeEv:
    """A funneled release spelled outside its declared choke point."""

    proto: str
    desc: str
    funnel: tuple[str, ...]
    site: Site


# ---------------------------------------------------------------------- model


class ResourceModel:
    """Call-site classification against the protocol table."""

    def __init__(self, index: cg.ProjectIndex):
        self.index = index
        self.protocols = PROTOCOLS
        self._by_op: dict[str, list[tuple[Protocol, str]]] = {}
        for p in self.protocols:
            for op in p.acquire_ops:
                self._by_op.setdefault(op, []).append((p, "acquire"))
            for op in p.release_ops:
                self._by_op.setdefault(op, []).append((p, "release"))
        # In-tree exception class -> base name (last component).
        self.exc_bases: dict[str, str] = {}
        for mod in index.modules:
            for cls in mod.classes.values():
                for base in cls.bases:
                    b = u.last_component(base)
                    if b and (b in _BUILTIN_BASES or b in _CATCH_ALL):
                        self.exc_bases.setdefault(cls.name, b)

    # ------------------------------------------------------------ exceptions

    def catches(self, handler_names: tuple[str, ...], raised: str | None) -> bool:
        """Would an ``except (<handler_names>)`` clause catch ``raised``?

        Unknown on either side defaults to "caught" — false-positive-shy."""
        if not handler_names:
            return False
        if raised is None:
            return True  # cannot name the exception: assume handled
        for h in handler_names:
            if h in _CATCH_ALL:
                return True
            cur: str | None = raised
            seen: set[str] = set()
            while cur is not None and cur not in seen:
                if cur == h:
                    return True
                seen.add(cur)
                cur = self.exc_bases.get(cur) or _BUILTIN_BASES.get(cur)
        known = (
            raised in self.exc_bases
            or raised in _BUILTIN_BASES
            or raised in _CATCH_ALL
        )
        # A raised class we know nothing about could subclass anything the
        # handlers name: assume caught.
        return not known

    def is_shed(self, proto: Protocol, raised: str | None) -> bool:
        """Is ``raised`` one of the protocol's shed/overload classes (or a
        known subclass)? Exact chain walk — an unknown class is a generic
        leak, not a shed."""
        cur = raised
        seen: set[str] = set()
        while cur is not None and cur not in seen:
            if cur in proto.shed_exceptions:
                return True
            seen.add(cur)
            cur = self.exc_bases.get(cur) or _BUILTIN_BASES.get(cur)
        return False

    # ------------------------------------------------------- classification

    def _receiver_class(
        self,
        module: cg.Module,
        caller: ast.AST | None,
        cls: ast.ClassDef | None,
        recv: ast.AST,
    ) -> str | None:
        parts = cg._dotted_parts(recv)
        if parts is None:
            return None
        if parts[0] == "self":
            if cls is None:
                return None
            if len(parts) == 1:
                return cls.name
            cur: tuple[cg.Module, ast.ClassDef] | None = (module, cls)
            for attr in parts[1:]:
                if cur is None:
                    return None
                cur = self.index.attr_class(cur[0], cur[1], attr)
            return cur[1].name if cur is not None else None
        if len(parts) == 1 and caller is not None:
            found = self.index._local_ctor_class(module, caller, parts[0])
            if found is not None:
                return found[1].name
        return None

    def classify(
        self,
        module: cg.Module,
        caller: ast.AST | None,
        cls: ast.ClassDef | None,
        call: ast.Call,
    ) -> tuple[Protocol, str] | None:
        """A call -> (protocol, "acquire"|"release"|"refund"), or None."""
        func = call.func
        if not isinstance(func, ast.Attribute):
            return None
        cands = self._by_op.get(func.attr)
        if not cands:
            return None
        encl = cls.name if cls is not None else None
        recv_cls = self._receiver_class(module, caller, cls, func.value)
        if recv_cls is not None:
            for proto, kind in cands:
                if recv_cls in proto.owner_classes:
                    if encl in proto.owner_classes and not proto.intra_owner:
                        return None  # implementation, not consumption
                    return proto, self._refine(proto, kind, call)
            return None  # typed receiver that is not an owner: not an event
        parts = cg._dotted_parts(func.value)
        tail = parts[-1].lower() if parts else ""
        if not tail:
            return None
        for proto, kind in cands:
            if any(t in tail for t in proto.receiver_tails):
                if encl in proto.owner_classes and not proto.intra_owner:
                    return None
                return proto, self._refine(proto, kind, call)
        return None

    @staticmethod
    def _refine(proto: Protocol, kind: str, call: ast.Call) -> str:
        if kind == "release" and proto.refund_kwarg:
            for kw in call.keywords:
                if kw.arg == proto.refund_kwarg and not (
                    isinstance(kw.value, ast.Constant) and not kw.value.value
                ):
                    return "refund"
        return kind


# ------------------------------------------------------------------ analysis


class ResourceAnalysis:
    """The computed events plus the per-protocol site census."""

    def __init__(self, model: ResourceModel):
        self.model = model
        self.acquires: list[AcquireEv] = []
        self.releases: list[ReleaseEv] = []
        self.transfers: list[TransferEv] = []
        self.leaks: list[LeakEv] = []
        self.doubles: list[DoubleReleaseEv] = []
        self.chokes: list[ChokeEv] = []
        self.funnel_sites: list[tuple[str, Site]] = []
        # protocol -> kind -> sorted unique sites (walk-independent census).
        self.census: dict[str, dict[str, list[Site]]] = {
            p.name: {"acquire": [], "release": [], "refund": []}
            for p in model.protocols
        }

    def leak_edges(self) -> list:
        return [*self.leaks, *self.doubles, *self.chokes]


def _exc_name(stmt: ast.Raise) -> str | None:
    exc = stmt.exc
    if isinstance(exc, ast.Call):
        exc = exc.func
    return u.last_component(exc) if exc is not None else None


class _Summaries:
    """Transitive may-release sets: the protocols a function releases (or
    refunds) on SOME path, through in-tree calls. Used to credit a callee's
    cleanup to the caller's owned set and to recognize protective
    ``finally`` blocks — the false-positive-shy direction."""

    def __init__(self, index: cg.ProjectIndex, model: ResourceModel):
        self.index = index
        self.model = model
        self.memo: dict[int, frozenset[str]] = {}
        self.active: set[int] = set()

    def may_release(self, info: cg.FuncInfo, depth: int = 0) -> frozenset[str]:
        key = id(info.node)
        if key in self.memo:
            return self.memo[key]
        if key in self.active or depth > wk.MAX_DEPTH:
            return frozenset()
        self.active.add(key)
        out: set[str] = set()
        module = info.module
        cls = self.index.enclosing_class(module, info.node)
        for node in cg._own_scope_nodes(info.node):
            if not isinstance(node, ast.Call):
                continue
            c = self.model.classify(module, info.node, cls, node)
            if c is not None and c[1] in ("release", "refund"):
                out.add(c[0].name)
            callee = self.index.resolve_call_ext(module, info.node, node)
            if callee is not None:
                out |= self.may_release(callee, depth + 1)
        self.active.discard(key)
        self.memo[key] = frozenset(out)
        return frozenset(out)

    def stmts_release(
        self, info: cg.FuncInfo, cls, stmts: list[ast.stmt]
    ) -> frozenset[str]:
        """Protocols released somewhere in ``stmts`` (a finally/handler
        body), directly or through a resolvable callee."""
        out: set[str] = set()
        for stmt in stmts:
            for node in wk.walk_exprs(stmt):
                if not isinstance(node, ast.Call):
                    continue
                c = self.model.classify(info.module, info.node, cls, node)
                if c is not None and c[1] in ("release", "refund"):
                    out.add(c[0].name)
                callee = self.index.resolve_call_ext(
                    info.module, info.node, node
                )
                if callee is not None:
                    out |= self.may_release(callee)
        return frozenset(out)


@dataclasses.dataclass
class _Owned:
    proto: Protocol
    subject: str | None
    ev: AcquireEv
    transferred: bool = False


@dataclasses.dataclass
class _TryFrame:
    handlers: tuple[tuple[str, ...], ...]  # per except clause
    final_rel: frozenset[str]

    def catches(self, model: ResourceModel, raised: str | None) -> bool:
        return any(model.catches(h, raised) for h in self.handlers)


class _State:
    """Per-frame walk state. ``owned`` is the live set of this frame's own
    acquires; ``ledger``/``tledger`` are the path-local release and
    transfer subjects for the double-release check; ``protect`` is the
    stack of enclosing try frames; ``caught`` names the innermost except
    clause's classes (what a bare ``raise`` re-raises)."""

    def __init__(self):
        self.owned: list[_Owned] = []
        self.ledger: dict[tuple, Site] = {}
        self.tledger: dict[str, Site] = {}
        self.protect: list[_TryFrame] = []
        self.caught: tuple[str, ...] = ()

    def branch(self) -> "_State":
        s = _State()
        s.owned = list(self.owned)
        s.ledger = dict(self.ledger)
        s.tledger = dict(self.tledger)
        s.protect = self.protect  # lexical: push/pop balanced per body
        s.caught = self.caught
        return s

    def drop_name(self, name: str) -> None:
        """A rebound name invalidates path subjects that mention it."""
        self.ledger = {
            k: v
            for k, v in self.ledger.items()
            if name not in k[1].split(".") and name not in k[3].split(".")
        }
        self.tledger = {
            k: v
            for k, v in self.tledger.items()
            if name not in k.split(".")
        }


class _Walker:
    """Owned-set propagation from every shared entry point; each function
    is walked once (ownership facts are frame-local, so unlike the lock
    walk there is no caller-context to re-walk under)."""

    def __init__(
        self,
        index: cg.ProjectIndex,
        analysis: ResourceAnalysis,
        summaries: _Summaries,
    ):
        self.index = index
        self.model = analysis.model
        self.analysis = analysis
        self.summaries = summaries
        self.visited: set[int] = set()
        # Call node -> its AcquireEv, so an enclosing assignment can name
        # the owned subject (`plan = self._prefix.fork(...)` -> "plan").
        self._acq_by_node: dict[int, AcquireEv] = {}

    def run(self) -> None:
        for root in wk.entry_points(self.index):
            self._walk_fn(root, ())

    def _qual(self, info: cg.FuncInfo) -> str:
        return f"{modname(info.module)}.{info.qualname}"

    def _walk_fn(self, info: cg.FuncInfo, stack: tuple[str, ...]) -> None:
        if id(info.node) in self.visited or len(stack) > wk.MAX_DEPTH:
            return
        self.visited.add(id(info.node))
        frame = (
            f"{self._qual(info)} ({info.ctx.path}:{info.node.lineno})"
            if not stack
            else stack[-1]
        )
        base = stack if stack else (frame,)
        cls = self.index.enclosing_class(info.module, info.node)
        self._body(info, cls, info.node.body, _State(), base)

    # ------------------------------------------------------------ statements

    def _body(
        self,
        info: cg.FuncInfo,
        cls: ast.ClassDef | None,
        stmts: list[ast.stmt],
        S: _State,
        stack: tuple[str, ...],
    ) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, ast.Raise):
                self._exprs(info, cls, stmt, S, stack)
                self._raise(info, stmt, S, stack)
                break  # nothing after a raise on this path
            if isinstance(stmt, ast.Return):
                if stmt.value is not None:
                    self._exprs(info, cls, stmt.value, S, stack)
                break
            if isinstance(stmt, (ast.Break, ast.Continue)):
                break
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._exprs(info, cls, item.context_expr, S, stack)
                self._body(info, cls, stmt.body, S, stack)
            elif isinstance(stmt, ast.If):
                self._exprs(info, cls, stmt.test, S, stack)
                self._body(info, cls, stmt.body, S.branch(), stack)
                self._body(info, cls, stmt.orelse, S.branch(), stack)
            elif isinstance(stmt, ast.While):
                self._exprs(info, cls, stmt.test, S, stack)
                self._body(info, cls, stmt.body, S.branch(), stack)
                self._body(info, cls, stmt.orelse, S.branch(), stack)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._exprs(info, cls, stmt.iter, S, stack)
                body = S.branch()
                if isinstance(stmt.target, ast.Name):
                    body.drop_name(stmt.target.id)
                self._body(info, cls, stmt.body, body, stack)
                self._body(info, cls, stmt.orelse, S.branch(), stack)
            elif isinstance(stmt, ast.Try):
                self._try(info, cls, stmt, S, stack)
            elif isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                self._assign(info, cls, stmt, S, stack)
            else:
                for child in ast.iter_child_nodes(stmt):
                    self._exprs(info, cls, child, S, stack)

    def _try(self, info, cls, stmt: ast.Try, S: _State, stack) -> None:
        final_rel = self.summaries.stmts_release(info, cls, stmt.finalbody)
        handlers = tuple(
            tuple(u.last_component(t) or "BaseException"
                  for t in (
                      h.type.elts
                      if isinstance(h.type, ast.Tuple)
                      else (h.type,) if h.type is not None else ()
                  ))
            or ("BaseException",)
            for h in stmt.handlers
        )
        entry = S.branch()  # what an except clause observes
        S.protect.append(_TryFrame(handlers, final_rel))
        self._body(info, cls, stmt.body, S, stack)
        S.protect.pop()
        for h, names in zip(stmt.handlers, handlers):
            hs = entry.branch()
            hs.caught = names
            # The handler's own raises skip this try's clauses but still
            # unwind through its finally.
            hs.protect = S.protect + [_TryFrame((), final_rel)]
            self._body(info, cls, h.body, hs, stack)
        self._body(info, cls, stmt.orelse, S, stack)
        self._body(info, cls, stmt.finalbody, S, stack)

    def _assign(self, info, cls, stmt, S: _State, stack) -> None:
        value = getattr(stmt, "value", None)
        if value is not None:
            self._exprs(info, cls, value, S, stack)
        targets = (
            stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        )
        # Direct acquire assignment names the owned subject.
        if isinstance(value, ast.Call):
            ev = self._acq_by_node.get(id(value))
            if ev is not None and ev.subject is None and targets:
                parts = cg._dotted_parts(targets[0])
                if parts:
                    ev.subject = ".".join(parts)
                    for o in S.owned:
                        if o.ev is ev:
                            o.subject = ev.subject
        vparts = cg._dotted_parts(value) if value is not None else None
        vtext = ".".join(vparts) if vparts else None
        for t in targets:
            self._transfer(info, t, value, vtext, S, stack)
            if isinstance(t, ast.Name):
                S.drop_name(t.id)

    def _transfer(self, info, target, value, vtext, S: _State, stack) -> None:
        """A store into a declared sink (or a funnel closure) parks
        ownership: ``self._lane_leases[lane] = plan.lease``,
        ``handle._on_close = lambda: meter.close(rid)``."""
        tnode = target
        if isinstance(tnode, ast.Subscript):
            tnode = tnode.value
        tparts = cg._dotted_parts(tnode)
        if not tparts:
            return
        attr = tparts[-1]
        site = wk.site_of(info.ctx, target)
        # Closure stored on a funnel attr: the closure's releases transfer
        # their protocols (the registered drain will run them).
        closure_rel: set[str] = set()
        if isinstance(value, ast.Lambda):
            cls = self.index.enclosing_class(info.module, info.node)
            for node in ast.walk(value.body):
                if isinstance(node, ast.Call):
                    c = self.model.classify(info.module, info.node, cls, node)
                    if c is not None and c[1] in ("release", "refund"):
                        closure_rel.add(c[0].name)
        for o in S.owned:
            if o.transferred:
                continue
            proto = o.proto
            sinkish = any(s in attr for s in proto.sink_tails)
            funnelish = attr in proto.funnel_attrs and proto.name in closure_rel
            if not (sinkish or funnelish):
                continue
            if sinkish and vtext is not None and o.subject is not None:
                if not (vtext == o.subject
                        or vtext.startswith(o.subject + ".")):
                    continue
            o.transferred = True
            o.ev.outcomes.add(f"transferred -> {attr}")
            self.analysis.transfers.append(
                TransferEv(proto.name, attr, vtext, site, stack)
            )
            if vtext is not None:
                S.tledger.setdefault(vtext, site)

    # ----------------------------------------------------------- expressions

    def _exprs(self, info, cls, expr, S: _State, stack) -> None:
        for node in wk.walk_exprs(expr):
            if isinstance(node, ast.Call):
                self._call(info, cls, node, S, stack)

    def _call(self, info, cls, call: ast.Call, S: _State, stack) -> None:
        site = wk.site_of(info.ctx, call)
        c = self.model.classify(info.module, info.node, cls, call)
        if c is not None:
            proto, kind = c
            if kind == "acquire":
                ev = AcquireEv(
                    proto.name, None, site, stack, self._qual(info)
                )
                self.analysis.acquires.append(ev)
                self._acq_by_node[id(call)] = ev
                S.owned.append(_Owned(proto, None, ev))
            else:
                self._release(proto, kind, call, site, S, stack)
        # Interprocedural: the callee's events get walked once, and its
        # may-release summary credits the caller's owned set.
        callee = self.index.resolve_call_ext(info.module, info.node, call)
        if callee is not None:
            released = self.summaries.may_release(callee)
            if released:
                for o in S.owned:
                    if o.proto.name in released and not o.transferred:
                        o.ev.outcomes.add(
                            f"released via {callee.qualname}"
                        )
                S.owned = [
                    o for o in S.owned if o.proto.name not in released
                ]
            entry = f"{self._qual(callee)} ({info.ctx.path}:{call.lineno})"
            self._walk_fn(callee, stack + (entry,))

    def _release(self, proto, kind, call, site, S: _State, stack) -> None:
        recv = cg._dotted_parts(call.func.value)
        arg0 = cg._dotted_parts(call.args[0]) if call.args else None
        rtext = ".".join(recv) if recv else ""
        atext = ".".join(arg0) if arg0 else ""
        subject = (proto.name, rtext, atext) if rtext else None
        self.analysis.releases.append(
            ReleaseEv(proto.name, kind, subject, site, stack)
        )
        if kind == "release":
            # Release after the subject was parked in a sink: the drain
            # site owns it now, a direct release double-frees.
            if atext and atext in S.tledger:
                self.analysis.doubles.append(
                    DoubleReleaseEv(
                        proto.name, atext, S.tledger[atext], site, stack,
                        after_transfer=True,
                    )
                )
            # Path-local double release: same receiver, same argument
            # spelling, no rebind between. A complex first argument
            # (`...pop(lane, None)` drains) is untracked — conservative.
            trackable = rtext and (arg0 is not None or not call.args)
            key = (proto.name, rtext, call.func.attr, atext)
            if trackable and key in S.ledger:
                self.analysis.doubles.append(
                    DoubleReleaseEv(
                        proto.name,
                        f"{rtext}.{call.func.attr}({atext})",
                        S.ledger[key], site, stack,
                        after_transfer=False,
                    )
                )
            elif trackable:
                S.ledger[key] = site
        # Clear owned: by subject when it matches, else every owned
        # instance of the protocol (a release on the path means the
        # resource is no longer this frame's liability). Refunds clear
        # regardless of subject: a compensation edge is keyed by the
        # admission id (`close(rid, refund=True)`), not by whatever name
        # the grant happened to be bound to.
        kept: list[_Owned] = []
        for o in S.owned:
            if o.proto is not proto:
                kept.append(o)
                continue
            if kind != "refund" and atext and o.subject is not None and not (
                atext == o.subject or atext.startswith(o.subject + ".")
            ):
                kept.append(o)
                continue
            o.ev.outcomes.add("refunded" if kind == "refund" else "released")
        S.owned = kept

    # ---------------------------------------------------------------- raises

    def _raise(self, info, stmt: ast.Raise, S: _State, stack) -> None:
        if stmt.exc is None:
            raised_names: tuple[str | None, ...] = S.caught or (None,)
        else:
            raised_names = (_exc_name(stmt),)
        live = [o for o in S.owned if not o.transferred]
        if not live:
            return
        site = wk.site_of(info.ctx, stmt)
        for raised in raised_names:
            surviving = list(live)
            for tf in reversed(S.protect):
                surviving = [
                    o for o in surviving if o.proto.name not in tf.final_rel
                ]
                if tf.catches(self.model, raised):
                    surviving = []
                    break
            for o in surviving:
                shed = self.model.is_shed(o.proto, raised) and bool(
                    o.proto.refund_kwarg
                )
                o.ev.outcomes.add("leaked")
                self.analysis.leaks.append(
                    LeakEv(
                        o.proto.name, o.proto.noun, raised or "?",
                        o.ev.site, site, self._qual(info), stack, shed,
                    )
                )
            if surviving:
                live = [o for o in live if o not in surviving]


# -------------------------------------------------------- census/choke scan


def _lexical_scan(
    index: cg.ProjectIndex, model: ResourceModel, analysis: ResourceAnalysis
) -> None:
    """Walk-independent pass over every call in every module: the
    per-protocol site census (what the CLI table and the engagement pin
    count) and the choke-point check for funneled protocols."""
    seen: dict[tuple[str, str], set[tuple[str, int]]] = {}
    for mod in index.modules:
        ctx = mod.ctx
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not isinstance(
                node.func, ast.Attribute
            ):
                continue
            caller, cls = _enclosing(ctx, node)
            c = model.classify(mod, caller, cls, node)
            if c is None:
                continue
            proto, kind = c
            site = wk.site_of(ctx, node)
            key = (proto.name, kind)
            if (site.path, site.line) not in seen.setdefault(key, set()):
                seen[key].add((site.path, site.line))
                analysis.census[proto.name][kind].append(site)
            if kind == "release" and proto.funnel_attrs:
                recv = cg._dotted_parts(node.func.value)
                desc = ".".join(recv or ()) + f".{node.func.attr}"
                if _in_funnel(ctx, node, proto):
                    analysis.funnel_sites.append((proto.name, site))
                else:
                    analysis.chokes.append(
                        ChokeEv(proto.name, desc, proto.funnel_attrs, site)
                    )
    for table in analysis.census.values():
        for sites in table.values():
            sites.sort(key=lambda s: (s.path, s.line))


def _enclosing(ctx, node) -> tuple[ast.AST | None, ast.ClassDef | None]:
    caller = None
    cls = None
    for anc in ctx.ancestors(node):
        if caller is None and isinstance(
            anc, (ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            caller = anc
        if isinstance(anc, ast.ClassDef):
            cls = anc
            break
    return caller, cls


def _in_funnel(ctx, call: ast.Call, proto: Protocol) -> bool:
    """Is this release inside a closure assigned to a funnel attr
    (``handle._on_close = lambda: ... .close(rid)``) or inside a def by
    that name?"""
    for anc in ctx.ancestors(call):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if anc.name in proto.funnel_attrs:
                return True
            parent = ctx.parents.get(anc)
        elif isinstance(anc, ast.Lambda):
            parent = ctx.parents.get(anc)
        else:
            continue
        if isinstance(parent, ast.Assign):
            for t in parent.targets:
                tparts = cg._dotted_parts(t)
                if tparts and tparts[-1] in proto.funnel_attrs:
                    return True
        return False
    return False


# ------------------------------------------------------------------- driving


def analyze(ctxs: list) -> ResourceAnalysis:
    """Build the protocol model and run the owned-set walk plus the
    census/choke scan. Pure function of the contexts; use
    ``resource_analysis`` for the per-run cached variant the rules
    share."""
    index = cg.project_index(ctxs)
    model = ResourceModel(index)
    analysis = ResourceAnalysis(model)
    _lexical_scan(index, model, analysis)
    walker = _Walker(index, analysis, _Summaries(index, model))
    walker.run()
    return analysis


_ANALYSIS_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def resource_analysis(ctxs: list) -> ResourceAnalysis:
    if not ctxs:
        return ResourceAnalysis(ResourceModel(cg.ProjectIndex(())))
    anchor = ctxs[0]
    paths = tuple(c.path for c in ctxs)
    cached = _ANALYSIS_CACHE.get(anchor)
    if cached is not None and cached[0] == paths:
        return cached[1]
    analysis = analyze(ctxs)
    _ANALYSIS_CACHE[anchor] = (paths, analysis)
    return analysis


# ------------------------------------------------------------- presentation


def render_witness(ev) -> str:
    return " -> ".join(ev.stack) if getattr(ev, "stack", ()) else "<entry>"


def render_table(analysis: ResourceAnalysis) -> str:
    """The ownership table: every protocol, its op pairing, and the site
    census — the engagement surface, independent of walk reachability."""
    lines = []
    n_acq = sum(len(t["acquire"]) for t in analysis.census.values())
    n_rel = sum(
        len(t["release"]) + len(t["refund"])
        for t in analysis.census.values()
    )
    lines.append(
        f"resource ownership: {len(analysis.model.protocols)} protocol(s), "
        f"{n_acq} acquire site(s), {n_rel} release site(s), "
        f"{len(analysis.transfers)} transfer(s), "
        f"{len(analysis.leak_edges())} leak edge(s)"
    )
    lines.append("")
    for p in analysis.model.protocols:
        t = analysis.census[p.name]
        lines.append(
            f"  {p.name:<13} {p.noun} (owner: {', '.join(p.owner_classes)})"
        )
        lines.append(
            f"    acquire  {'/'.join(p.acquire_ops)}"
            f"  [{len(t['acquire'])} site(s)]"
        )
        rel = f"    release  {'/'.join(p.release_ops)}"
        rel += f"  [{len(t['release'])} site(s)"
        if p.refund_kwarg:
            rel += f", {len(t['refund'])} refund"
        rel += "]"
        lines.append(rel)
        if p.sink_tails:
            lines.append(f"    sinks    {', '.join(p.sink_tails)}")
        if p.funnel_attrs:
            lines.append(
                f"    funnel   {', '.join(p.funnel_attrs)}"
                f"  [{sum(1 for n, _ in analysis.funnel_sites if n == p.name)}"
                " funneled site(s)]"
            )
        if p.shed_exceptions:
            lines.append(f"    shed     {', '.join(p.shed_exceptions)}")
    return "\n".join(lines)


def render_report(analysis: ResourceAnalysis, *, verbose: bool = False) -> str:
    """Table plus the per-entry-point owned-set walk: every tracked
    acquire, its witness path root, and how the walk saw it resolved."""
    lines = [render_table(analysis), "", "owned-set walk (tracked acquires):"]
    by_root: dict[str, list[AcquireEv]] = {}
    for ev in analysis.acquires:
        root = ev.stack[0].split(" (")[0] if ev.stack else "<entry>"
        by_root.setdefault(root, []).append(ev)
    if not analysis.acquires:
        lines.append("  (no acquire site reached from any entry point)")
    for root in sorted(by_root):
        lines.append(f"  {root}")
        for ev in sorted(by_root[root], key=lambda e: (e.site.path,
                                                       e.site.line)):
            out = ", ".join(sorted(ev.outcomes)) or "caller-owned"
            lines.append(
                f"    {ev.proto:<13} {ev.site}  in {ev.func}  [{out}]"
            )
            if verbose:
                lines.append(f"        via {render_witness(ev)}")
    edges = analysis.leak_edges()
    if edges:
        lines.append("")
        lines.append("leak edges:")
        lines.extend("  " + line for line in render_edges(analysis))
    return "\n".join(lines)


def render_edges(analysis: ResourceAnalysis) -> list[str]:
    out = []
    for ev in analysis.leaks:
        kind = "refund-missing-on-shed" if ev.shed else "leak-on-error-path"
        out.append(
            f"{kind}: {ev.noun} acquired at {ev.acquire_site} still owned "
            f"when {ev.exc} escapes {ev.func} at {ev.raise_site} "
            f"(via {render_witness(ev)})"
        )
    for ev in analysis.doubles:
        flavor = "release after transfer" if ev.after_transfer else (
            "second release on one path"
        )
        out.append(
            f"double-release: {ev.proto} {ev.subject!r} — {flavor} "
            f"(first {ev.first}, again {ev.second})"
        )
    for ev in analysis.chokes:
        out.append(
            f"release-outside-choke-point: {ev.proto} release {ev.desc} at "
            f"{ev.site} does not flow through "
            f"{'/'.join(ev.funnel)} (and is not a refund)"
        )
    return out


def render_dot(analysis: ResourceAnalysis) -> str:
    """Graphviz export: per-protocol ownership flow — acquire ops into the
    protocol node, protocol node out to release ops, dashed edges into the
    transfer sinks the walk observed."""
    lines = ["digraph resources {", "  rankdir=LR;", "  node [shape=box];"]
    sinks_seen: dict[str, set[str]] = {}
    for ev in analysis.transfers:
        sinks_seen.setdefault(ev.proto, set()).add(ev.sink)
    for p in analysis.model.protocols:
        lines.append(
            f'  "{p.name}" [shape=ellipse, label="{p.name}\\n{p.noun}"];'
        )
        for op in p.acquire_ops:
            node = f"{p.name}.{op}"
            lines.append(f'  "{node}" [label="{op}"];')
            lines.append(f'  "{node}" -> "{p.name}";')
        for op in p.release_ops:
            node = f"{p.name}.{op}"
            lines.append(f'  "{node}" [label="{op}"];')
            lines.append(f'  "{p.name}" -> "{node}";')
        for sink in sorted(sinks_seen.get(p.name, set()) | set(
            s for n, site in analysis.funnel_sites if n == p.name
            for s in p.funnel_attrs
        )):
            node = f"{p.name}.{sink}"
            lines.append(f'  "{node}" [shape=folder, label="{sink}"];')
            lines.append(f'  "{p.name}" -> "{node}" [style=dashed];')
    lines.append("}")
    return "\n".join(lines)
