"""``python -m cake_tpu.analysis [paths...]`` — see analysis/cli.py."""

import sys

from cake_tpu.analysis.cli import lint_main

sys.exit(lint_main())
