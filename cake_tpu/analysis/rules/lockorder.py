"""Lock-order and blocking-under-lock rules over the interprocedural
lock-set analysis (cake_tpu/analysis/locks.py).

The runtime's lock hierarchy — engine ``_cv`` over the prefix-cache/
allocator guards, worker session locks under the connection lock, the obs
modules' telemetry locks at the leaves — is a machine-checkable invariant
enforced by nothing at runtime except the stuck-epoch watchdog (which sees
the hang, not the cause). These rules consume the global lock-order graph
and the held-set events the ``locks`` pass computes, so the invariant
gates at review time:

  * ``lock-order-cycle`` — lock A held while B is acquired on one path
    and B held while A is acquired on another: the classic ABBA deadlock,
    reported once per cycle with one witness call path per direction.
  * ``blocking-call-under-lock`` — a socket op, ``Thread.join``,
    ``time.sleep``, ``block_until_ready``/jit dispatch, ``Event.wait``,
    or a *different* Condition's ``wait`` reached while a lock is held:
    every other thread that needs the lock stalls behind the block — the
    class the watchdog catches at runtime, caught at review time.
  * ``callback-under-lock`` — a stored callable (observer/hook/
    ``_on_close``-style) invoked with a lock held: the callee can call
    back into the lock's owner (self-deadlock on a plain Lock, silent
    re-entrancy on an RLock) or block arbitrarily. Snapshot under the
    lock, fire outside it (the ``StreamHandle._emit`` pattern).
  * ``notify-outside-lock`` — ``Condition.notify``/``notify_all`` on a
    path where the condition's lock is not held: raises RuntimeError at
    runtime, and any path that *almost* reaches it that way is one refactor
    from doing so.

All four see only locks the identity model resolved; an expression the
model cannot name produces no finding (the engine-wide conservatism
contract).
"""

from __future__ import annotations

from typing import Iterable

from cake_tpu.analysis import locks as la
from cake_tpu.analysis.engine import FileContext, Finding, Rule, register


def _held_names(held) -> str:
    return ", ".join(f"`{h}`" for h in held)


def _finding(
    rule: Rule, site: la.Site, message: str
) -> Finding:
    return Finding(
        rule=rule.name,
        path=site.path,
        line=site.line,
        col=site.col,
        severity=rule.severity,
        message=message,
    )


@register
class LockOrderCycle(Rule):
    name = "lock-order-cycle"
    severity = "error"
    scope = "project"
    description = (
        "Two (or more) locks acquired in opposite orders on different "
        "interprocedural paths — lock A held while B is acquired on one "
        "path, B held while A is acquired on another: one thread per path "
        "and the embrace deadlocks; reported once per cycle with a witness "
        "call path for each direction (break it by fixing the canonical "
        "order `cake-tpu locks` renders, or narrow one critical section)"
    )

    def check_project(self, ctxs: list[FileContext]) -> Iterable[Finding]:
        analysis = la.lock_analysis(ctxs)
        for cyc in analysis.cycles():
            edges = list(zip(cyc, (*cyc[1:], cyc[0])))
            parts = []
            anchor = None
            for a, b in edges:
                ev = analysis.witness(a, b)
                if ev is None:
                    continue
                if anchor is None:
                    anchor = ev.site
                parts.append(
                    f"`{a}` then `{b}` at {ev.site} "
                    f"(via {la.render_witness(ev)})"
                )
            if anchor is None:
                continue
            chain = " -> ".join(str(c) for c in (*cyc, cyc[0]))
            yield _finding(
                self,
                anchor,
                f"lock-order cycle {chain}: " + "; but ".join(parts) + (
                    " — two threads taking the paths concurrently "
                    "deadlock; acquire in one global order"
                ),
            )


@register
class BlockingCallUnderLock(Rule):
    name = "blocking-call-under-lock"
    severity = "error"
    scope = "project"
    description = (
        "A blocking call — socket op, `Thread.join`, `time.sleep`, "
        "`block_until_ready`/jit dispatch, `Event.wait`, or a DIFFERENT "
        "Condition's `wait` — reached (possibly through calls, "
        "project-wide) while a lock is held: every thread needing that "
        "lock stalls behind the block, the convoy/hang class the "
        "stuck-epoch watchdog catches at runtime; move the blocking call "
        "outside the critical section (snapshot under the lock, block "
        "outside)"
    )

    def check_project(self, ctxs: list[FileContext]) -> Iterable[Finding]:
        analysis = la.lock_analysis(ctxs)
        seen: set[tuple] = set()
        for ev in analysis.blockings:
            key = (ev.site, ev.desc, ev.held)
            if key in seen:
                continue
            seen.add(key)
            yield _finding(
                self,
                ev.site,
                f"`{ev.desc}` ({ev.kind}) called while holding "
                f"{_held_names(ev.held)} (path: {la.render_witness(ev)}); "
                "threads contending for the lock stall behind this call — "
                "hoist it out of the critical section",
            )
        for ev in analysis.waits:
            if not ev.others:
                continue  # waiting on its own condition releases it
            key = (ev.site, str(ev.lock), ev.others)
            if key in seen:
                continue
            seen.add(key)
            yield _finding(
                self,
                ev.site,
                f"`{ev.lock}.wait()` keeps {_held_names(ev.others)} held "
                "while parked (a Condition releases only its OWN lock in "
                f"wait; path: {la.render_witness(ev)}); the waker may need "
                "the held lock first — classic stall; drop it before "
                "waiting",
            )


@register
class CallbackUnderLock(Rule):
    name = "callback-under-lock"
    severity = "error"
    scope = "project"
    description = (
        "A stored callable (observer/listener/hook/`_on_close`-style "
        "attribute, or an element of a `*_listeners`/`*_callbacks` "
        "container) invoked while a lock is held: the callee is arbitrary "
        "user code that can call back into the lock's owner (deadlock on "
        "a Lock, silent re-entrancy on an RLock) or block; snapshot the "
        "callbacks under the lock and fire them after releasing it (the "
        "`StreamHandle._emit` pattern)"
    )

    def check_project(self, ctxs: list[FileContext]) -> Iterable[Finding]:
        analysis = la.lock_analysis(ctxs)
        seen: set[tuple] = set()
        for ev in analysis.callbacks:
            key = (ev.site, ev.desc)
            if key in seen:
                continue
            seen.add(key)
            yield _finding(
                self,
                ev.site,
                f"callback `{ev.desc}` invoked while holding "
                f"{_held_names(ev.held)} (path: {la.render_witness(ev)}); "
                "arbitrary callee code under a lock is the re-entrancy "
                "vector — snapshot under the lock, invoke after release",
            )


@register
class NotifyOutsideLock(Rule):
    name = "notify-outside-lock"
    severity = "error"
    scope = "project"
    description = (
        "`Condition.notify()`/`notify_all()` reached on a path where the "
        "condition's lock is NOT held (entry points and their transitive "
        "callees are analyzed with propagated held sets, so helpers only "
        "ever called under the lock stay clean): raises RuntimeError "
        "(\"cannot notify on un-acquired lock\") the first time that path "
        "runs — wrap the notify in `with <cond>:`"
    )

    def check_project(self, ctxs: list[FileContext]) -> Iterable[Finding]:
        analysis = la.lock_analysis(ctxs)
        seen: set[la.Site] = set()
        for ev in analysis.notifies:
            if ev.held or ev.site in seen:
                continue
            seen.add(ev.site)
            yield _finding(
                self,
                ev.site,
                f"`{ev.lock}` notified without its lock held (path: "
                f"{la.render_witness(ev)}); threading raises RuntimeError "
                "on un-acquired notify — wrap in `with` on the condition",
            )
