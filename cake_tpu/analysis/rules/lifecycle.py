"""Resource-lifecycle rules over the interprocedural owned-set analysis
(cake_tpu/analysis/resources.py).

The serving path's ownership protocols — KV pages, prefix leases, quota
grants, lane registrations, retained KV — are manually paired, and the
recurring bug class (the PR 10 shed-refund bug, the insert-before-unpin
ordering, every chaos test's "pool drains" assertion) is a resource
acquired on one path and not released on some exception/shed/cancel path.
These rules consume the protocol table, the owned-set walk, and the
choke-point scan, so the pairing gates at review time:

  * ``leak-on-error-path`` — a ``raise`` escapes the acquiring frame with
    the resource still owned and untransferred: no matching handler, no
    ``finally`` that releases, no sink that parked it.
  * ``double-release`` — the same release subject reachable twice on one
    path, or a direct release of a subject already transferred into a
    sink (the registered drain will release it again).
  * ``release-outside-choke-point`` — a funneled release (quota
    ``close``) spelled outside its declared ``_on_close`` choke point and
    not a ``refund=True`` admission rollback: every ad-hoc close site is
    a double-close or a missed-close waiting for a refactor.
  * ``refund-missing-on-shed`` — a grant still owned when a
    shed/overload exception class escapes, with no refund on that edge:
    the admission estimate is charged for work that never ran.

All four see only calls the protocol model resolved (owning class or
declared receiver tail); everything else produces no finding — the
engine-wide conservatism contract. They check product code only: test
files exercise acquire/release APIs deliberately out of protocol
(idempotency tests release twice, teardown helpers close directly).
"""

from __future__ import annotations

from pathlib import PurePath
from typing import Iterable

from cake_tpu.analysis import resources as ra
from cake_tpu.analysis.engine import FileContext, Finding, Rule, register


def _product(path: str) -> bool:
    parts = PurePath(path).parts
    return "tests" not in parts and not PurePath(path).name.startswith(
        "test_"
    )


def _finding(rule: Rule, site: ra.Site, message: str) -> Finding:
    return Finding(
        rule=rule.name,
        path=site.path,
        line=site.line,
        col=site.col,
        severity=rule.severity,
        message=message,
    )


@register
class LeakOnErrorPath(Rule):
    name = "leak-on-error-path"
    severity = "error"
    scope = "project"
    description = (
        "a raise escapes the acquiring frame with a resource (pages/"
        "lease/grant/lane) still owned and untransferred — the exception "
        "edge drops it"
    )

    def check_project(self, ctxs: list[FileContext]) -> Iterable[Finding]:
        analysis = ra.resource_analysis(ctxs)
        seen: set[tuple] = set()
        for ev in analysis.leaks:
            if ev.shed or not _product(ev.raise_site.path):
                continue  # shed flavor belongs to refund-missing-on-shed
            key = (ev.proto, ev.acquire_site, ev.raise_site)
            if key in seen:
                continue
            seen.add(key)
            yield _finding(
                self,
                ev.raise_site,
                f"{ev.noun} acquired at {ev.acquire_site} is still owned "
                f"when `{ev.exc}` escapes `{ev.func}` — release it in a "
                f"finally/handler on this edge, or transfer it to a "
                f"registry a release site drains",
            )


@register
class DoubleRelease(Rule):
    name = "double-release"
    severity = "error"
    scope = "project"
    description = (
        "the same release subject is reachable twice on one path, or a "
        "resource is released directly after being transferred into a "
        "sink whose drain releases it"
    )

    def check_project(self, ctxs: list[FileContext]) -> Iterable[Finding]:
        analysis = ra.resource_analysis(ctxs)
        seen: set[tuple] = set()
        for ev in analysis.doubles:
            if not _product(ev.second.path):
                continue
            key = (ev.proto, ev.first, ev.second)
            if key in seen:
                continue
            seen.add(key)
            how = (
                f"already transferred into a sink at {ev.first}"
                if ev.after_transfer
                else f"already released at {ev.first}"
            )
            yield _finding(
                self,
                ev.second,
                f"{ev.proto} subject `{ev.subject}` is {how} — this "
                f"release double-frees on the same path",
            )


@register
class ReleaseOutsideChokePoint(Rule):
    name = "release-outside-choke-point"
    severity = "warn"
    scope = "project"
    description = (
        "a funneled release (quota close) is spelled outside its declared "
        "_on_close choke point and is not a refund=True admission rollback"
    )

    def check_project(self, ctxs: list[FileContext]) -> Iterable[Finding]:
        analysis = ra.resource_analysis(ctxs)
        seen: set[tuple] = set()
        for ev in analysis.chokes:
            if not _product(ev.site.path):
                continue
            key = (ev.proto, ev.site)
            if key in seen:
                continue
            seen.add(key)
            yield _finding(
                self,
                ev.site,
                f"{ev.proto} release `{ev.desc}` does not flow through the "
                f"`{'/'.join(ev.funnel)}` choke point and is not a refund "
                f"— route completion releases through the registered "
                f"close callback",
            )


@register
class RefundMissingOnShed(Rule):
    name = "refund-missing-on-shed"
    severity = "error"
    scope = "project"
    description = (
        "a quota grant is still owned when a shed/overload exception "
        "escapes, with no refund on that edge — the tenant is charged "
        "for work that never ran"
    )

    def check_project(self, ctxs: list[FileContext]) -> Iterable[Finding]:
        analysis = ra.resource_analysis(ctxs)
        seen: set[tuple] = set()
        for ev in analysis.leaks:
            if not ev.shed or not _product(ev.raise_site.path):
                continue
            key = (ev.proto, ev.acquire_site, ev.raise_site)
            if key in seen:
                continue
            seen.add(key)
            yield _finding(
                self,
                ev.raise_site,
                f"{ev.noun} at {ev.acquire_site} has no refund on the "
                f"`{ev.exc}` shed edge escaping `{ev.func}` — close it "
                f"with refund=True before re-raising",
            )
