"""Paged-KV discipline: block-table snapshots must not outlive the
allocator state they were read from.

The invariant (PR 4's design rule, which prefix-cache CoW splicing makes
easy to break): ``PageAllocator.block_tables`` is the ONE source of truth
for where a lane's KV lives. ``fork``/``fork_chain``/``make_private``/
``extend``/``map_range``/``unmap_page``/``release`` rewrite rows in place —
a row (or whole-table) value read BEFORE such a call describes mappings
that no longer exist. Writing through it scribbles freed or CoW-shared
pages; reading through it gathers garbage. The paged backend therefore
re-reads ``self.allocator.block_tables`` at every dispatch instead of
caching it (runtime/batch_backend.py), and this rule machine-checks that
discipline: a local/attribute that captured a block-table read, a call that
can mutate the allocator, then a USE of the stale capture — flagged at the
use site.

Copies are NOT exempt: ``jnp.asarray(alloc.block_tables[lane])`` is a
snapshot of the same stale mappings (the bug is time, not aliasing). A
re-read after the mutation (rebinding the name, or reading
``.block_tables`` inline at the use site) is the fix and is not flagged.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from cake_tpu.analysis import _util as u
from cake_tpu.analysis.engine import FileContext, Finding, Rule, register

# Allocator methods that REMAP lane rows (the staleness trigger — refcount-
# only operations like retain_pages/release_pages/reclaim never move a
# lane's mapping and are deliberately excluded). The unambiguous names flag
# on ANY receiver; the generic ones (a ``release``/``reset``/``fork``/
# ``extend`` exists on many objects) only when the receiver looks like the
# allocator or the prefix cache that splices chains through it.
_MUTATORS_UNAMBIGUOUS = {
    "fork_chain", "make_private", "map_range", "unmap_page",
    "release_lanes",
}
_MUTATORS_GENERIC = {"fork", "extend", "release", "reset"}
_ALLOCATORISH = ("alloc", "prefix", "_cache")


def _reads_block_tables(node: ast.AST) -> bool:
    return any(
        isinstance(n, ast.Attribute) and n.attr == "block_tables"
        for n in ast.walk(node)
    )


def _mutator_receiverish(recv: str | None) -> bool:
    return recv is not None and any(s in recv.lower() for s in _ALLOCATORISH)


def _is_mutation(call: ast.Call) -> bool:
    if not isinstance(call.func, ast.Attribute):
        return False
    attr = call.func.attr
    if attr in _MUTATORS_UNAMBIGUOUS:
        return True
    return attr in _MUTATORS_GENERIC and _mutator_receiverish(
        u.dotted(call.func.value)
    )


def _events(fn: ast.AST) -> Iterator[tuple[int, str, str | None, ast.AST]]:
    """(line, kind, name, node) for captures, mutations, and loads."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and _reads_block_tables(node.value):
            for t in node.targets:
                name = u.dotted(t)
                if name is not None:
                    yield node.lineno, "capture", name, node
        elif isinstance(node, ast.Call) and _is_mutation(node):
            yield node.lineno, "mutate", None, node
        elif isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
            getattr(node, "ctx", None), ast.Load
        ):
            name = u.dotted(node)
            if name is not None:
                yield node.lineno, "load", name, node


@register
class StaleBlockTable(Rule):
    name = "stale-block-table"
    severity = "error"
    description = (
        "A captured block-table row/table is used after an allocator "
        "mutation (fork/make_private/extend/release/...) that can remap "
        "it — re-read allocator.block_tables at the use site instead."
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for fn in u.functions(ctx.tree):
            capture_lines: dict[str, set[int]] = {}
            bind_lines: dict[str, list[int]] = {}  # every assignment
            mutations: list[int] = []
            loads: list[tuple[int, str, ast.AST]] = []
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        name = u.dotted(t)
                        if name is not None:
                            bind_lines.setdefault(name, []).append(
                                node.lineno
                            )
            for line, kind, name, node in _events(fn):
                if kind == "capture":
                    capture_lines.setdefault(name, set()).add(line)
                elif kind == "mutate":
                    mutations.append(line)
                else:
                    loads.append((line, name, node))
            if not capture_lines or not mutations:
                continue
            reported: set[tuple[str, int]] = set()
            for line, name, node in loads:
                if name not in capture_lines:
                    continue
                # The latest binding BEFORE this load decides what value the
                # load sees: a rebinding after the mutation (the re-read
                # fix) supersedes the stale capture and is not flagged.
                before = [b for b in bind_lines.get(name, []) if b < line]
                if not before:
                    continue
                binding = max(before)
                if binding not in capture_lines[name]:
                    continue
                if any(binding < m < line for m in mutations) and (
                    name,
                    line,
                ) not in reported:
                    reported.add((name, line))
                    yield ctx.finding(
                        self,
                        node,
                        f"`{name}` captured block-table state at line "
                        f"{binding} but is used after an allocator "
                        "mutation that can remap it (fork/make_private/"
                        "extend/release) — re-read `.block_tables` here",
                    )
